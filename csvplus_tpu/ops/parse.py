"""Device-side CSV parsing: bytes as u8 tensors (SURVEY.md §7 hard part 1).

TPUs have no string ops, but a CSV chunk is just a ``uint8[n]`` tensor:

* separators are vectorized compares (``data == ','``, ``data == '\\n'``);
* field offsets fall out of one ``sum`` (host sync for the count — the
  only data-dependent allocation) plus ``nonzero`` with a static size;
* per-record field counts are differences of the delimiter prefix-sum
  sampled at newline positions;
* **dictionary encoding happens on device too**: fields (<= 8 bytes) are
  gathered into NUL-padded byte matrices and packed big-endian into two
  int32 lanes (sign-flipped so signed compare == byte order), a two-key
  stable ``lax.sort`` groups equal fields, run boundaries become dense
  ranks via a cumulative sum, and a scatter returns codes in row order.
  Only the (few) unique values are ever touched by the host, to build
  the sorted string dictionary.

Scope (the honest fast path, per SURVEY's strategy): simple rectangular
CSV — no quotes, no comment lines, no blank interior lines, no CR — the
shape machine-generated data-lake files overwhelmingly have.  Anything
else falls back to the native C++ / Python scanners, which are the
behavioral spec.  Differential tests pin equality against the Reader.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_NL = 10
_CR = 13
_QUOTE = 34
_SIGN = np.int32(-0x80000000)  # sign-flip bias: signed order == byte order


@jax.jit
def _scan_features(data: jax.Array, delim: jax.Array):
    """One fused pass over the byte tensor: eligibility + separator masks."""
    nl = data == _NL
    dl = data == delim
    sep = nl | dl
    n_sep = jnp.sum(sep)
    n_nl = jnp.sum(nl)
    return sep, nl, dl, n_sep, n_nl


@partial(jax.jit, static_argnames=("n_sep", "n_nl", "trailing_nl"))
def _offsets_kernel(sep, nl, dl, n_sep: int, n_nl: int, trailing_nl: bool):
    """Field starts/ends and per-record field counts, statically sized."""
    n = sep.shape[0]
    sep_pos = jnp.nonzero(sep, size=n_sep)[0]
    nl_pos = jnp.nonzero(nl, size=n_nl)[0]

    n_fields = n_sep + (0 if trailing_nl else 1)
    starts = jnp.zeros(n_fields, dtype=jnp.int32)
    starts = starts.at[1:].set((sep_pos + 1)[: n_fields - 1].astype(jnp.int32))
    ends = jnp.concatenate(
        [sep_pos.astype(jnp.int32), jnp.full(1, n, jnp.int32)]
    )[:n_fields]

    # fields per record: delimiters before each newline, differenced
    dl_cum = jnp.cumsum(dl)
    dl_at_nl = dl_cum[nl_pos]
    prev = jnp.concatenate([jnp.zeros(1, dl_at_nl.dtype), dl_at_nl[:-1]])
    rec_counts = (dl_at_nl - prev + 1).astype(jnp.int32)
    if not trailing_nl:
        total_dl = dl_cum[-1] if n else jnp.int32(0)
        last = (total_dl - (dl_at_nl[-1] if n_nl else 0) + 1).astype(jnp.int32)
        rec_counts = jnp.concatenate([rec_counts, last[None]])
    return starts, ends, rec_counts


@partial(jax.jit, static_argnames=("width",))
def _encode_column_kernel(data, starts, lens, width: int):
    """Device dictionary-encode one column of fields (width <= 8 bytes).

    Returns (codes in row order, number of uniques, sorted unique hi/lo
    packs, first-row-index of each unique) — the host decodes only the
    uniques into the string dictionary.
    """
    m = starts.shape[0]
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    mask = jnp.arange(width, dtype=jnp.int32)[None, :] < lens[:, None]
    safe = jnp.clip(idx, 0, data.shape[0] - 1)
    mat = jnp.where(mask, jnp.take(data, safe, axis=0), 0).astype(jnp.int32)

    hw = min(4, width)
    hi = jnp.zeros(m, dtype=jnp.int32)
    for b in range(hw):
        hi = hi | (mat[:, b] << (8 * (3 - b)))
    lo = jnp.zeros(m, dtype=jnp.int32)
    for b in range(4, width):
        lo = lo | (mat[:, b] << (8 * (7 - b)))
    hi = hi ^ _SIGN  # signed compare now equals byte-lexicographic order
    lo = lo ^ _SIGN

    pos = jnp.arange(m, dtype=jnp.int32)
    hi_s, lo_s, pos_s = jax.lax.sort((hi, lo, pos), num_keys=2, is_stable=True)

    new_run = jnp.concatenate(
        [jnp.ones(1, bool), (hi_s[1:] != hi_s[:-1]) | (lo_s[1:] != lo_s[:-1])]
    )
    rank = jnp.cumsum(new_run) - 1  # dense code per sorted position
    codes = jnp.zeros(m, dtype=jnp.int32).at[pos_s].set(rank.astype(jnp.int32))
    n_uniq = rank[-1] + 1 if m else jnp.int32(0)
    # first sorted occurrence of each unique -> original row index
    uniq_rows = jnp.where(new_run, pos_s, m)  # m = +inf for segment mins
    uniq_first = jnp.full(m, m, jnp.int32).at[rank].min(uniq_rows)
    return codes, n_uniq, uniq_first


def parse_simple_csv_device(
    data: bytes, delimiter: str = ",", device=None
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, jax.Array]]:
    """Device scan of a simple CSV chunk.

    Returns (field_starts, field_lens, rec_counts, device u8 data) as in
    the native scanner's contract, or None when the chunk needs the
    full state machine (quotes / CR / blank lines / empty).
    """
    if not data:
        return None
    if len(data) >= 2**31:
        return None  # int32 offsets would wrap; the int64 scanners handle it
    # eligibility checks on host bytes (memchr-cheap) BEFORE any upload:
    # quotes/CR need the full state machine, NUL aliases encode padding,
    # blank lines change record numbering
    if (
        b'"' in data
        or b"\r" in data
        or b"\x00" in data
        or b"\n\n" in data
        or data.startswith(b"\n")
    ):
        return None
    arr = jax.device_put(np.frombuffer(data, dtype=np.uint8), device)
    sep, nl, dl, n_sep, n_nl = _scan_features(arr, jnp.uint8(ord(delimiter)))
    trailing_nl = data.endswith(b"\n")
    starts, ends, rec_counts = _offsets_kernel(
        sep, nl, dl, int(n_sep), int(n_nl), trailing_nl
    )
    starts_np = np.asarray(starts, dtype=np.int64)
    lens_np = (np.asarray(ends) - starts_np).astype(np.int32)
    return starts_np, lens_np, np.asarray(rec_counts), arr


_DEVICE_ENCODE_MAX_LEN = 8


def encode_column_device(
    data_dev: jax.Array,
    data_host: bytes,
    starts: np.ndarray,
    lens: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fully-device dictionary encode of one column (fields <= 8 bytes).

    Returns (sorted bytes dictionary, int32 codes) matching
    encode_strings' contract, or None for wider fields.
    """
    if starts.shape[0] == 0:
        return np.empty(0, dtype="S1"), np.empty(0, dtype=np.int32)
    width = int(lens.max())
    if width > _DEVICE_ENCODE_MAX_LEN:
        return None
    width = max(width, 1)
    codes, n_uniq, uniq_first = _encode_column_kernel(
        data_dev,
        jnp.asarray(starts, dtype=jnp.int32),
        jnp.asarray(lens, dtype=jnp.int32),
        width,
    )
    k = int(n_uniq)
    rows = np.asarray(uniq_first)[:k]
    # host touches only the unique values to build the dictionary
    dictionary = np.array(
        [data_host[starts[r] : starts[r] + lens[r]] for r in rows], dtype="S"
    )
    if dictionary.size == 0:
        dictionary = np.empty(0, dtype="S1")
    return dictionary, codes  # codes stay on device; no host round-trip
