"""Device-side CSV parsing: bytes as u8 tensors (SURVEY.md §7 hard part 1).

TPUs have no string ops, but a CSV chunk is just a ``uint8[n]`` tensor.
Division of labor (revised after profiling; compile-cache churn matters
more than moving every op to the device):

* separator scan + field offsets + per-record counts run in vectorized
  numpy — those index vectors are consumed on the host immediately
  (header policy, column slicing), so device-side computation would buy
  a round-trip plus a per-file-size XLA compile and nothing else;
* the byte buffer uploads once (pow2-bucketed so downstream kernels
  compile a bounded executable set) and **dictionary encoding — the
  heavy part — happens on device**: fields (<= 32 bytes) are
  gathered into NUL-padded byte matrices and packed big-endian into
  2/4/8 int32 lanes (sign-flipped so signed compare == byte order), a
  multi-key stable ``lax.sort`` groups equal fields, run boundaries
  become dense ranks via a cumulative sum, and a scatter returns codes
  in row order.  Only the (few) unique values are ever touched by the
  host, to build the sorted string dictionary.

Scope (the honest fast path, per SURVEY's strategy): simple rectangular
CSV — no quotes, no comment lines, no blank interior lines, no CR — the
shape machine-generated data-lake files overwhelmingly have.  Anything
else falls back to the native C++ / Python scanners, which are the
behavioral spec.  Differential tests pin equality against the Reader.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

_NL = 10
_CR = 13
_QUOTE = 34
_SIGN = np.int32(-0x80000000)  # sign-flip bias: signed order == byte order


def _offsets_np(host_arr: np.ndarray, delim_byte: int, trailing_nl: bool):
    """Field starts/ends and per-record field counts, in numpy.

    The offset vectors are consumed on the host (column slicing + header
    policy) immediately, so computing them device-side would only add a
    round-trip — and a per-file-size compile.  The numpy version is
    C-speed, shape-churn-free, and identical in output.
    """
    n = host_arr.shape[0]
    nl_mask = host_arr == _NL
    dl_mask = host_arr == delim_byte
    sep_pos = np.flatnonzero(nl_mask | dl_mask)
    nl_pos = np.flatnonzero(nl_mask)
    n_sep = sep_pos.shape[0]

    n_fields = n_sep + (0 if trailing_nl else 1)
    starts = np.zeros(n_fields, dtype=np.int64)
    starts[1:] = (sep_pos + 1)[: n_fields - 1]
    ends = np.append(sep_pos, n)[:n_fields]

    # fields per record: delimiters before each newline, differenced
    dl_cum = np.cumsum(dl_mask)
    dl_at_nl = dl_cum[nl_pos]
    rec_counts = np.diff(dl_at_nl, prepend=0) + 1
    if not trailing_nl:
        total_dl = int(dl_cum[-1]) if n else 0
        last = total_dl - (int(dl_at_nl[-1]) if nl_pos.size else 0) + 1
        rec_counts = np.append(rec_counts, last)
    return starts, ends, rec_counts.astype(np.int32)


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("lanes",))
def _encode_column_kernel(data, starts, lens, lanes: int = 2):  # analysis: allow[JIT001] — arity fixed per pipeline shape
    """Device dictionary-encode one column of fields (<= 4*lanes bytes).

    Fields are gathered into NUL-padded byte matrices and packed
    big-endian into *lanes* sign-flipped int32 lanes, so a multi-key
    signed sort equals byte-lexicographic order at any width.  *lanes*
    is static and power-of-two bucketed (2/4/8 -> 8/16/32 bytes), and
    the caller buckets the row count, so the jit cache stays tiny.
    Returns (codes in row order, number of uniques, first-row-index of
    each unique) — the host decodes only the uniques into the string
    dictionary.
    """
    width = 4 * lanes
    m = starts.shape[0]
    idx = starts[:, None] + jnp.arange(width, dtype=jnp.int32)[None, :]
    mask = jnp.arange(width, dtype=jnp.int32)[None, :] < lens[:, None]
    safe = jnp.clip(idx, 0, data.shape[0] - 1)
    mat = jnp.where(mask, jnp.take(data, safe, axis=0), 0).astype(jnp.int32)

    words = []
    for w in range(lanes):
        word = jnp.zeros(m, dtype=jnp.int32)
        for b in range(4):
            word = word | (mat[:, 4 * w + b] << (8 * (3 - b)))
        words.append(word ^ _SIGN)  # signed compare == byte order

    pos = jnp.arange(m, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(
        tuple(words) + (pos,), num_keys=lanes, is_stable=True
    )
    pos_s = sorted_ops[-1]

    neq = None
    for w_s in sorted_ops[:-1]:
        d = w_s[1:] != w_s[:-1]
        neq = d if neq is None else (neq | d)
    new_run = jnp.concatenate([jnp.ones(1, bool), neq])
    rank = jnp.cumsum(new_run) - 1  # dense code per sorted position
    codes = jnp.zeros(m, dtype=jnp.int32).at[pos_s].set(rank.astype(jnp.int32))
    n_uniq = rank[-1] + 1 if m else jnp.int32(0)
    # first sorted occurrence of each unique -> original row index
    uniq_rows = jnp.where(new_run, pos_s, m)  # m = +inf for segment mins
    uniq_first = jnp.full(m, m, jnp.int32).at[rank].min(uniq_rows)
    return codes, n_uniq, uniq_first


def parse_simple_csv_device(
    data: bytes, delimiter: str = ",", device=None
) -> Optional[Tuple[np.ndarray, np.ndarray, np.ndarray, jax.Array]]:
    """Device scan of a simple CSV chunk.

    Returns (field_starts, field_lens, rec_counts, device u8 data) as in
    the native scanner's contract, or None when the chunk needs the
    full state machine (quotes / CR / blank lines / empty).
    """
    if not data:
        return None
    if len(data) >= 2**31:
        return None  # int32 offsets would wrap; the int64 scanners handle it
    # eligibility checks on host bytes (memchr-cheap) BEFORE any upload:
    # quotes/CR need the full state machine, NUL aliases encode padding,
    # blank lines change record numbering
    if (
        b'"' in data
        or b"\r" in data
        or b"\x00" in data
        or b"\n\n" in data
        or data.startswith(b"\n")
    ):
        return None
    # bucket the upload size so downstream kernels compile a bounded set
    # of executables; NUL padding lies beyond real_n and is never a
    # separator (eligibility already rejected NULs inside the data).
    # Pow2 up to 64MB, then 1.25x geometric steps so a large file never
    # pads to ~2x its size
    real_n = len(data)
    padded = _bucket_len(real_n)
    host_arr = np.frombuffer(data, dtype=np.uint8)
    if padded != real_n:
        host_arr = np.concatenate(
            [host_arr, np.zeros(padded - real_n, dtype=np.uint8)]
        )
    arr = jax.device_put(host_arr, device)
    trailing_nl = data.endswith(b"\n")
    starts, ends, rec_counts = _offsets_np(
        host_arr[:real_n], ord(delimiter), trailing_nl
    )
    lens_np = (ends - starts).astype(np.int32)
    return starts, lens_np, rec_counts, arr


_BUCKET_POW2_CAP = 64 << 20


def _bucket_len(n: int) -> int:
    """Upload-size bucket: pow2 below 64MB, then 1.25x geometric steps
    (bounded jit cache either way, bounded padding waste above)."""
    if n <= 2048:
        return 2048
    if n <= _BUCKET_POW2_CAP:
        return 1 << (n - 1).bit_length()
    b = _BUCKET_POW2_CAP
    while b < n:
        b = int(b * 1.25)
    return b


_DEVICE_ENCODE_MAX_LEN = 32  # 8 int32 lanes


def encode_column_device(
    data_dev: jax.Array,
    data_host: bytes,
    starts: np.ndarray,
    lens: np.ndarray,
) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Fully-device dictionary encode of one column (fields <= 32 bytes,
    packed into 2/4/8 int32 lanes by the column's widest field).

    Returns (sorted bytes dictionary, int32 codes) matching
    encode_strings' contract, or None for wider fields.
    """
    if starts.shape[0] == 0:
        return np.empty(0, dtype="S1"), np.empty(0, dtype=np.int32)
    max_len = int(lens.max())
    if max_len > _DEVICE_ENCODE_MAX_LEN:
        return None
    # lanes bucketed to powers of two: 8-, 16- or 32-byte kernel variants
    lanes = 2
    while 4 * lanes < max_len:
        lanes *= 2
    # bucket the row count (pow2, floor 2048) so the jitted kernel
    # compiles O(log n) executables total; pad entries duplicate field 0,
    # which cannot change the dictionary or the real rows' codes
    m = starts.shape[0]
    m_pad = max(1 << (m - 1).bit_length() if m > 1 else 1, 2048)
    if m_pad != m:
        starts = np.concatenate([starts, np.full(m_pad - m, starts[0])])
        lens = np.concatenate([lens, np.full(m_pad - m, lens[0], dtype=lens.dtype)])
    codes, n_uniq, uniq_first = _encode_column_kernel(
        data_dev,
        jnp.asarray(starts, dtype=jnp.int32),
        jnp.asarray(lens, dtype=jnp.int32),
        lanes=lanes,
    )
    codes = codes[:m]
    k = int(n_uniq)
    rows = np.asarray(uniq_first)[:k]
    # host touches only the unique values to build the dictionary
    dictionary = np.array(
        [data_host[starts[r] : starts[r] + lens[r]] for r in rows], dtype="S"
    )
    if dictionary.size == 0:
        dictionary = np.empty(0, dtype="S1")
    return dictionary, codes  # codes stay on device; no host round-trip
