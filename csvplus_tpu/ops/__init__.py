"""Device kernels: vectorized predicates, joins, sorts, dedup."""
