"""Device index build: multi-key sort over dictionary codes.

The reference builds an index by materializing all rows and running a
comparison sort with a per-comparison multi-column string compare
(csvplus.go:722-736, 794-807).  On device the same ordering comes out of
one fused ``lax.sort`` over the key columns' **dictionary codes**: each
dictionary is sorted, so integer code order == byte-lexicographic string
order, and ``lax.sort`` with ``num_keys=k`` sorts lexicographically by
(col0, col1, ..., colk) exactly like the reference's left-to-right
compare.  ``is_stable=True`` refines the reference's unstable sort into a
deterministic order that matches the host executor's stable sort, so
differential tests can require exact equality.

A trailing iota operand rides along as the permutation, used to gather
every non-key column once after the sort.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..columnar.table import DeviceTable, StringColumn
from ..utils.env import env_int


@partial(jax.jit, static_argnames=("num_keys",))
def _sort_kernel(operands: Tuple[jax.Array, ...], num_keys: int):
    """Stable lexicographic sort; last operand is the row permutation."""
    return jax.lax.sort(operands, num_keys=num_keys, is_stable=True)


# Mesh-sharded tables at or above this row count sort through the
# distributed sample-sort (parallel/dsort.py) instead of the replicated
# lax.sort, which lands the whole array on every chip.
DSORT_MIN_ROWS = env_int("CSVPLUS_DSORT_MIN_ROWS", 1_000_000)


def _sharded_mesh(key_cols) -> "Optional[object]":
    """The named mesh the key codes are row-sharded over, or None when
    any column is unsharded / opaque-sharded / single-device."""
    mesh = None
    for c in key_cols:
        sh = getattr(c.codes, "sharding", None)
        m = getattr(sh, "mesh", None)
        if m is None or len(sh.device_set) <= 1:
            return None
        if mesh is None:
            mesh = m
        elif m is not mesh and m != mesh:
            # same device count over DIFFERENT meshes (devices, shape or
            # axis names) would run the sample-sort with the wrong
            # placement (ADVICE r3); Mesh.__eq__ covers all three
            return None
    return mesh


def _packed_sort_lanes(key_cols) -> "Optional[Tuple[jax.Array, ...]]":
    """Key columns packed into sample-sort lanes, mirroring the join's
    key tiers (ops/join.py): one int32 lane up to 31 packed bits, dual
    nonnegative 31-bit (hi, lo) lanes up to 62, None beyond.  Because
    each dictionary is sorted, packed order == the multi-column
    lexicographic code order the replicated sort produces."""
    from .join import _bits_for, _pack_qk_kernel, pack_lanes

    bits = [_bits_for(c.dict_size) for c in key_cols]
    total = sum(bits)
    if total > 62:
        return None
    shifts = []
    acc = 0
    for b in reversed(bits):
        shifts.insert(0, acc)
        acc += b
    if total <= 31:
        # fused pack (codes are nonnegative, so the kernel's miss
        # masking is the identity) instead of an eager per-column loop
        lane = _pack_qk_kernel(
            tuple(c.codes for c in key_cols), tuple(shifts)
        )
        return (lane,)
    hi, lo = pack_lanes([c.codes for c in key_cols], shifts, bits)
    return (hi, lo)


def sort_table(table: DeviceTable, key_columns: Sequence[str]) -> DeviceTable:
    """Return a new table with rows sorted by the key columns.

    Mesh-sharded tables of at least :data:`DSORT_MIN_ROWS` rows route
    through the distributed sample-sort — per-shard sorts plus ONE
    all_to_all exchange — instead of the replicated ``lax.sort``
    (SURVEY §2 "index build (distributed)"; the semantics anchor is the
    reference's whole-dataset sort, csvplus.go:722-736)."""
    key_cols = [table.columns[c] for c in key_columns]
    for c in key_cols:
        # sorting BY a column requires code order == value order; a
        # deferred-union lane dictionary settles here (no-op otherwise)
        c._ensure_sorted_lanes()
    if table.nrows >= DSORT_MIN_ROWS:
        mesh = _sharded_mesh(key_cols)
        # packed lanes require real codes in every key cell; the index
        # build has already validated that (first_missing_cell), other
        # callers fall back when absent cells exist
        if mesh is not None and not any(c.has_absent for c in key_cols):
            lanes = _packed_sort_lanes(key_cols)
            if lanes is not None:
                from ..parallel.dsort import distributed_sort_device
                from ..utils.observe import telemetry

                with telemetry.stage("dsort", table.nrows):
                    iota = jnp.arange(table.nrows, dtype=jnp.int32)
                    _, perm = distributed_sort_device(mesh, lanes, iota)
                out = {
                    name: col.gather(perm) for name, col in table.columns.items()
                }
                return DeviceTable(out, table.nrows, table.device)

    iota = jnp.arange(table.nrows, dtype=jnp.int32)
    operands = tuple(c.codes for c in key_cols) + (iota,)
    sorted_ops = _sort_kernel(operands, num_keys=len(key_cols))
    perm = sorted_ops[-1]

    out = {}
    sorted_keys = dict(zip(key_columns, sorted_ops[: len(key_cols)]))
    for name, col in table.columns.items():
        if name in sorted_keys:
            # key columns come out of the sort already permuted
            out[name] = col.with_codes(sorted_keys[name])
        else:
            out[name] = col.gather(perm)
    return DeviceTable(out, table.nrows, table.device)


@jax.jit
def _adjacent_dup_kernel(*key_codes: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(any_dup, first_dup_index) over sorted key columns.

    A row i>0 is a duplicate when every key column equals row i-1 — the
    columnar form of the reference's adjacent scan (csvplus.go:749-753).
    """
    eq = None
    for k in key_codes:
        e = k[1:] == k[:-1]
        eq = e if eq is None else (eq & e)
    any_dup = jnp.any(eq)
    first = jnp.argmax(eq) + 1  # row index of the duplicate row
    return any_dup, first


def find_adjacent_duplicate(
    table: DeviceTable, key_columns: Sequence[str]
) -> "int | None":
    """Index of the first row whose key equals the previous row's, or None."""
    if table.nrows < 2:
        return None
    codes = tuple(table.columns[c].codes for c in key_columns)
    any_dup, first = _adjacent_dup_kernel(*codes)
    if bool(any_dup):
        return int(first)
    return None


@jax.jit
def _run_starts_kernel(*key_codes: jax.Array) -> jax.Array:
    """Boolean mask: True where row i starts a new key run (i=0 included)."""
    n = key_codes[0].shape[0]
    neq = jnp.zeros(n - 1, dtype=bool)
    for k in key_codes:
        neq = neq | (k[1:] != k[:-1])
    return jnp.concatenate([jnp.ones(1, dtype=bool), neq])


def run_starts(table: DeviceTable, key_columns: Sequence[str]):
    """Host bool array marking the first row of each equal-key run."""
    import numpy as np

    if table.nrows == 0:
        return np.zeros(0, dtype=bool)
    if table.nrows == 1:
        return np.ones(1, dtype=bool)
    codes = tuple(table.columns[c].codes for c in key_columns)
    return np.asarray(_run_starts_kernel(*codes))
