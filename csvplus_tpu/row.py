"""The Row type: one record of a data source.

A ``Row`` is a mapping from column names to string values — columns are
addressed by name, never by position (reference: ``type Row map[string]string``
csvplus.go:59 and README.md:76-79).  It subclasses ``dict`` so that plain
dicts and Rows interoperate freely; all reference accessors (csvplus.go:61-205)
exist both under Go-style names (``HasColumn``) and Python-style names
(``has_column``).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


class MissingColumnError(KeyError):
    """A named column is absent from a row.

    Message format pinned by the reference: ``missing column %q``
    (csvplus.go:129, 144, 171).
    """

    def __init__(self, name: str):
        self.column = name
        # KeyError repr-quotes its sole arg; store formatted message instead.
        super().__init__(name)
        self._msg = f'missing column "{name}"'

    def __str__(self) -> str:  # noqa: D105
        return self._msg


class ConversionError(ValueError):
    """A cell value failed a numeric conversion.

    Message format pinned by reference tests (csvplus_test.go:932, 954):
    ``column "x": cannot convert "v" to integer: invalid syntax``.
    """


class Row(dict):
    """One line from a data source: column name -> string value."""

    __slots__ = ()

    # -- predicates / safe access (csvplus.go:61-75) ----------------------

    def has_column(self, col: str) -> bool:
        """True when the specified column is present (csvplus.go:62-65)."""
        return col in self

    def safe_get_value(self, col: str, subst: str = "") -> str:
        """Value under *col* if present, else *subst* (csvplus.go:69-75)."""
        return self.get(col, subst)

    # -- canonical forms (csvplus.go:77-104) ------------------------------

    def header(self) -> List[str]:
        """All column names, sorted (csvplus.go:78-87)."""
        return sorted(self.keys())

    def __str__(self) -> str:
        """Canonical string form (csvplus.go:90-104): sorted-key JSON-ish."""
        if not self:
            return "{}"
        parts = ", ".join(f'"{k}" : "{self[k]}"' for k in self.header())
        return "{ " + parts + " }"

    def __repr__(self) -> str:  # keep dict repr for debugging
        return f"Row({dict.__repr__(self)})"

    # -- projection (csvplus.go:106-150) ----------------------------------

    def select_existing(self, *cols: str) -> "Row":
        """New Row with only the listed columns that exist (csvplus.go:108-118)."""
        return Row({c: self[c] for c in cols if c in self})

    def select(self, *cols: str) -> "Row":
        """New Row with exactly the listed columns; raises
        :class:`MissingColumnError` if any is absent (csvplus.go:122-134)."""
        r = Row()
        for c in cols:
            try:
                r[c] = self[c]
            except KeyError:
                raise MissingColumnError(c) from None
        return r

    def select_values(self, *cols: str) -> List[str]:
        """Values of the listed columns in order; raises
        :class:`MissingColumnError` if any is absent (csvplus.go:138-150)."""
        try:
            return [self[c] for c in cols]
        except KeyError as e:
            raise MissingColumnError(e.args[0]) from None

    def clone(self) -> "Row":
        """Shallow copy (csvplus.go:153-161)."""
        return Row(self)

    # -- typed getters (csvplus.go:163-205) --------------------------------

    def value_as_int(self, column: str) -> int:
        """Value of *column* as int (csvplus.go:165-183).

        Unlike Python's ``int()``, the reference's ``strconv.Atoi`` rejects
        surrounding whitespace and underscores; we match that strictness.
        """
        if column not in self:
            raise MissingColumnError(column)
        val = self[column]
        if _GO_INT_RE.match(val):
            try:
                return int(val, 10)
            except ValueError:
                pass
        raise ConversionError(
            f'column "{column}": cannot convert "{val}" to integer: invalid syntax'
        )

    def value_as_float(self, column: str) -> float:
        """Value of *column* as float (csvplus.go:187-205)."""
        if column not in self:
            raise MissingColumnError(column)
        val = self[column]
        if _GO_FLOAT_RE.match(val):
            try:
                return float(val)
            except (ValueError, OverflowError):
                pass
        raise ConversionError(
            f'column "{column}": cannot convert "{val}" to float: invalid syntax'
        )

    # Go-style aliases (the reference API names, csvplus.go:61-205) --------
    HasColumn = has_column
    SafeGetValue = safe_get_value
    Header = header
    SelectExisting = select_existing
    Select = select
    SelectValues = select_values
    Clone = clone
    ValueAsInt = value_as_int
    ValueAsFloat64 = value_as_float


import re as _re

# strconv.Atoi: optional sign + decimal digits only.
_GO_INT_RE = _re.compile(r"^[+-]?[0-9]+$")
# strconv.ParseFloat accepts decimal/exponent forms, inf/nan, hex floats.
# We accept the common decimal forms; Python float() covers inf/nan spellings
# that Go also accepts ("inf", "Infinity", "NaN" case-insensitively).
_GO_FLOAT_RE = _re.compile(
    r"^[+-]?((\d+(\.\d*)?|\.\d+)([eE][+-]?\d+)?|[iI][nN][fF]([iI][nN][iI][tT][yY])?|[nN][aA][nN])$"
)


def merge_rows(left: Row, right: Row) -> Row:
    """Merged row; on column-name collision the *right* value wins.

    Reference: ``mergeRows`` csvplus.go:571-583 — Join merges
    ``(indexRow, streamRow)`` so the stream row's value survives
    (csvplus.go:560).
    """
    r = Row(left)
    r.update(right)
    return r


def equal_rows(columns: Iterable[str], r1: Row, r2: Row) -> bool:
    """True when the listed columns have equal values in both rows
    (reference: ``equalRows`` csvplus.go:759-767)."""
    return all(r1.get(c) == r2.get(c) for c in columns)


def all_columns_unique(columns: Tuple[str, ...]) -> bool:
    """True when the column list has no duplicates (csvplus.go:770-782)."""
    return len(set(columns)) == len(columns)
