"""The Row type: one record of a data source.

A ``Row`` is a mapping from column names to string values — columns are
addressed by name, never by position (reference: ``type Row map[string]string``
csvplus.go:59 and README.md:76-79).  It subclasses ``dict`` so that plain
dicts and Rows interoperate freely; all reference accessors (csvplus.go:61-205)
exist both under Go-style names (``HasColumn``) and Python-style names
(``has_column``).
"""

from __future__ import annotations

from typing import Iterable, List, Tuple


class MissingColumnError(KeyError):
    """A named column is absent from a row.

    Message format pinned by the reference: ``missing column %q``
    (csvplus.go:129, 144, 171).
    """

    def __init__(self, name: str):
        self.column = name
        # KeyError repr-quotes its sole arg; store formatted message instead.
        super().__init__(name)
        self._msg = f'missing column "{name}"'

    def __str__(self) -> str:  # noqa: D105
        return self._msg


class ConversionError(ValueError):
    """A cell value failed a numeric conversion.

    Message format pinned by reference tests (csvplus_test.go:932, 954):
    ``column "x": cannot convert "v" to integer: invalid syntax``.
    """


class Row(dict):
    """One line from a data source: column name -> string value."""

    __slots__ = ()

    # -- predicates / safe access (csvplus.go:61-75) ----------------------

    def has_column(self, col: str) -> bool:
        """True when the specified column is present (csvplus.go:62-65)."""
        return col in self

    def safe_get_value(self, col: str, subst: str = "") -> str:
        """Value under *col* if present, else *subst* (csvplus.go:69-75)."""
        return self.get(col, subst)

    # -- canonical forms (csvplus.go:77-104) ------------------------------

    def header(self) -> List[str]:
        """All column names, sorted (csvplus.go:78-87)."""
        return sorted(self.keys())

    def __str__(self) -> str:
        """Canonical string form (csvplus.go:90-104): sorted-key JSON-ish."""
        if not self:
            return "{}"
        parts = ", ".join(f'"{k}" : "{self[k]}"' for k in self.header())
        return "{ " + parts + " }"

    def __repr__(self) -> str:  # keep dict repr for debugging
        return f"Row({dict.__repr__(self)})"

    # -- projection (csvplus.go:106-150) ----------------------------------

    def select_existing(self, *cols: str) -> "Row":
        """New Row with only the listed columns that exist (csvplus.go:108-118)."""
        return Row({c: self[c] for c in cols if c in self})

    def select(self, *cols: str) -> "Row":
        """New Row with exactly the listed columns; raises
        :class:`MissingColumnError` if any is absent (csvplus.go:122-134)."""
        r = Row()
        for c in cols:
            try:
                r[c] = self[c]
            except KeyError:
                raise MissingColumnError(c) from None
        return r

    def select_values(self, *cols: str) -> List[str]:
        """Values of the listed columns in order; raises
        :class:`MissingColumnError` if any is absent (csvplus.go:138-150)."""
        try:
            return [self[c] for c in cols]
        except KeyError as e:
            raise MissingColumnError(e.args[0]) from None

    def clone(self) -> "Row":
        """Shallow copy (csvplus.go:153-161)."""
        return Row(self)

    # -- typed getters (csvplus.go:163-205) --------------------------------

    def value_as_int(self, column: str) -> int:
        """Value of *column* as int (csvplus.go:165-183).

        Unlike Python's ``int()``, the reference's ``strconv.Atoi`` rejects
        surrounding whitespace and underscores, and is 64-bit: values
        outside int64 are a ``value out of range`` error, not a bignum.
        """
        if column not in self:
            raise MissingColumnError(column)
        val = self[column]
        if not _GO_INT_RE.match(val):
            raise ConversionError(
                f'column "{column}": cannot convert "{val}" to integer: invalid syntax'
            )
        # avoid CPython's 4300-digit int() limit: only the significant
        # digits matter (Go parses any number of leading zeros)
        digits = val.lstrip("+-").lstrip("0")
        if len(digits) > 19:  # > int64 for sure
            v = None
        else:
            v = int(digits or "0", 10)
            if val[0] == "-":
                v = -v
        if v is not None and -(1 << 63) <= v < (1 << 63):
            return v
        raise ConversionError(
            f'column "{column}": cannot convert "{val}" to integer: value out of range'
        )

    def value_as_float(self, column: str) -> float:
        """Value of *column* as float (csvplus.go:187-205), accepting the
        full ``strconv.ParseFloat`` grammar — decimal/exponent forms,
        inf/infinity/nan spellings, hex floats, underscore separators."""
        if column not in self:
            raise MissingColumnError(column)
        val = self[column]
        res = parse_go_float(val)
        if isinstance(res, float):
            return res
        raise ConversionError(
            f'column "{column}": cannot convert "{val}" to float: {res}'
        )

    # Go-style aliases (the reference API names, csvplus.go:61-205) --------
    HasColumn = has_column
    SafeGetValue = safe_get_value
    Header = header
    SelectExisting = select_existing
    Select = select
    SelectValues = select_values
    Clone = clone
    ValueAsInt = value_as_int
    ValueAsFloat64 = value_as_float


import re as _re

# strconv.Atoi: optional sign + decimal digits only (no underscores —
# Atoi parses with an explicit base, where Go disallows separators).
_GO_INT_RE = _re.compile(r"^[+-]?[0-9]+$")
# ParseFloat specials: inf/infinity take an optional sign, nan does NOT
# (Go's special() only matches a bare "nan").
_GO_SPECIAL_RE = _re.compile(r"^(?:[+-]?(?i:inf(?:inity)?)|(?i:nan))$")
# Hex float: binary ("p") exponent REQUIRED, >=1 mantissa digit overall.
_GO_HEX_RE = _re.compile(
    r"^[+-]?0[xX](?P<i>[0-9a-fA-F]*)(?:\.(?P<f>[0-9a-fA-F]*))?[pP][+-]?[0-9]+$"
)
# Decimal: >=1 mantissa digit; exponent digits required when e present.
_GO_DEC_RE = _re.compile(r"^[+-]?(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]+)(?:[eE][+-]?[0-9]+)?$")


def _underscores_ok(s: str) -> bool:
    """Go's digit-separator placement rule for numeric literals: every
    underscore sits between two digits, or between the base prefix and a
    digit (strconv's underscoreOK semantics)."""
    if s[:1] in ("+", "-"):
        s = s[1:]
    saw = "^"  # ^ start, 0 digit/base-prefix, _ underscore, ! other
    i = 0
    is_hex = False
    if len(s) >= 2 and s[0] == "0" and s[1] in "bBoOxX":
        i = 2
        saw = "0"  # the base prefix counts as a digit for separators
        is_hex = s[1] in "xX"
    while i < len(s):
        c = s[i]
        if "0" <= c <= "9" or (is_hex and c in "abcdefABCDEF"):
            saw = "0"
        elif c == "_":
            if saw != "0":
                return False
            saw = "_"
        else:
            if saw == "_":
                return False
            saw = "!"
        i += 1
    return saw != "_"


def parse_go_float(s: str):
    """``strconv.ParseFloat(s, 64)`` (Go grammar and range semantics).

    Returns the parsed float, or the Go error suffix as a plain string —
    ``"invalid syntax"`` or ``"value out of range"`` (overflow to ±Inf
    and complete underflow to 0 are range errors in Go).
    """
    if _GO_SPECIAL_RE.match(s):
        low = s.lstrip("+-").lower()
        if low == "nan":
            return float("nan")
        return float("-inf") if s[0] == "-" else float("inf")
    t = s
    if "_" in t:
        if not _underscores_ok(t):
            return "invalid syntax"
        t = t.replace("_", "")
    m = _GO_HEX_RE.match(t)
    if m:
        mantissa = (m.group("i") or "") + (m.group("f") or "")
        if not mantissa:
            return "invalid syntax"  # "0x.p1" — no mantissa digits
        try:
            v = float.fromhex(t)
        except OverflowError:
            return "value out of range"
        except ValueError:
            return "invalid syntax"
    elif _GO_DEC_RE.match(t):
        mantissa = _re.split(r"[eE]", t, maxsplit=1)[0]
        try:
            v = float(t)
        except (ValueError, OverflowError):
            return "value out of range"
    else:
        return "invalid syntax"
    if v in (float("inf"), float("-inf")):
        return "value out of range"
    if v == 0.0 and any(c in "123456789abcdefABCDEF" for c in mantissa):
        return "value out of range"
    return v


def merge_rows(left: Row, right: Row) -> Row:
    """Merged row; on column-name collision the *right* value wins.

    Reference: ``mergeRows`` csvplus.go:571-583 — Join merges
    ``(indexRow, streamRow)`` so the stream row's value survives
    (csvplus.go:560).
    """
    r = Row(left)
    r.update(right)
    return r


def equal_rows(columns: Iterable[str], r1: Row, r2: Row) -> bool:
    """True when the listed columns have equal values in both rows
    (reference: ``equalRows`` csvplus.go:759-767)."""
    return all(r1.get(c) == r2.get(c) for c in columns)


def all_columns_unique(columns: Tuple[str, ...]) -> bool:
    """True when the column list has no duplicates (csvplus.go:770-782)."""
    return len(set(columns)) == len(columns)
