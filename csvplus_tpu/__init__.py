"""csvplus_tpu — a TPU-native rebuild of the csvplus ETL library.

The reference (github.com/maxim2266/csvplus, mounted at /root/reference)
extends Go's encoding/csv with a fluent lazy-pipeline API, indices and
joins.  This package re-creates that complete API in Python — same three
entities (``Row``, ``DataSource``, ``Index``), same combinators, same
behavioral contracts — and adds what the reference never had: a columnar
execution backend where pipelines lower to fused JAX/XLA/Pallas kernels on
TPU, scale over a ``jax.sharding.Mesh`` with ICI all-to-all partitioned
joins, and beat the host row-at-a-time path by orders of magnitude.

Quick start (host path — full reference parity)::

    import csvplus_tpu as csvplus

    people = csvplus.FromFile("people.csv").SelectColumns("name", "surname", "id")
    csvplus.Take(people) \
        .Filter(csvplus.Like({"name": "Amelia"})) \
        .Map(csvplus.SetValue("name", "Julia")) \
        .ToCsvFile("out.csv", "name", "surname")

Device path (columnar, one chip or a mesh)::

    people = csvplus.FromFile("people.csv").OnDevice("tpu")
    people.Filter(csvplus.Like({"name": "Amelia"})).ToRows()

Both Go-style (``FromFile``/``Filter``/``ToCsvFile``) and Python-style
(``from_file``/``filter``/``to_csv_file``) names are exported.
"""

from .errors import CsvPlusError, DataSourceError, StopPipeline
from .row import (
    ConversionError,
    MissingColumnError,
    Row,
    merge_rows,
)
from .source import DataSource, RowFunc, take, take_rows
from .reader import Reader, from_file, from_read_closer, from_reader
from .index import Index, create_index, create_unique_index, load_index
from .sinks import to_rows_many
from .predicates import All, Any_, Like, Not, Predicate
from .exprs import Rename, SetValue, Update
from . import obs
from . import plan
from . import serve
from . import storage
from .utils import telemetry, profile_to

# Go-style API aliases (reference names; BASELINE.json exercises these)
Take = take
TakeRows = take_rows
FromFile = from_file
FromReader = from_reader
FromReadCloser = from_read_closer
LoadIndex = load_index
ToRowsMany = to_rows_many
Any = Any_  # Go's csvplus.Any; shadows builtins.any only inside this module

__all__ = [
    # types
    "Row",
    "DataSource",
    "RowFunc",
    "Index",
    "Reader",
    # errors
    "CsvPlusError",
    "DataSourceError",
    "StopPipeline",
    "MissingColumnError",
    "ConversionError",
    # constructors
    "take",
    "take_rows",
    "from_file",
    "from_reader",
    "from_read_closer",
    "load_index",
    "create_index",
    "create_unique_index",
    "to_rows_many",
    # predicates & symbolic exprs
    "Predicate",
    "All",
    "Any",
    "Any_",
    "Not",
    "Like",
    "Rename",
    "SetValue",
    "Update",
    # helpers
    "merge_rows",
    "obs",
    "plan",
    "serve",
    "storage",
    "telemetry",
    "profile_to",
    # Go-style aliases
    "Take",
    "TakeRows",
    "FromFile",
    "FromReader",
    "FromReadCloser",
    "LoadIndex",
    "ToRowsMany",
]

__version__ = "0.1.0"
