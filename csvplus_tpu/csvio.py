"""CSV record parsing and writing with the reference's exact semantics.

The reference delegates to Go's ``encoding/csv`` (csvplus.go:1091-1097);
Python's stdlib ``csv`` differs in comment handling, field-count policy and
error strictness, so this module implements the Go behavior directly:

* records end at ``\\n`` or ``\\r\\n``; quoted fields may span lines;
* fully blank lines are skipped; a line whose first character equals the
  comment char is skipped (checked only at record start);
* RFC-4180 quoting with ``""`` doubling; without *lazy_quotes* a bare ``"``
  in an unquoted field or a stray ``"`` in a quoted field is an error with
  Go's exact messages (``bare \" in non-quoted field`` /
  ``extraneous or missing \" in quoted-field``);
* *trim_leading_space* skips leading white space in each field;
* field-count policy is enforced by the caller (:mod:`csvplus_tpu.reader`)
  with Go's ``wrong number of fields`` message.

This pure-Python implementation is the **specification**; the native C++
chunk scanner (csvplus_tpu/native) implements the same state machine for
the high-throughput columnar ingest path, and is differential-tested
against this module.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, TextIO

from .errors import CsvPlusError

ERR_BARE_QUOTE = 'bare " in non-quoted field'
ERR_QUOTE = 'extraneous or missing " in quoted-field'
ERR_FIELD_COUNT = "wrong number of fields"


class CsvParseError(CsvPlusError):
    """A malformed CSV construct; message matches Go's csv.ParseError.Err."""


def _is_space(c: str) -> bool:
    return c.isspace() and c not in "\r\n"


def parse_records(
    stream: TextIO,
    delimiter: str = ",",
    comment: Optional[str] = None,
    lazy_quotes: bool = False,
    trim_leading_space: bool = False,
) -> Iterator[List[str]]:
    """Yield one record (list of field strings) at a time from *stream*."""
    if len(delimiter) != 1:
        raise ValueError("csv delimiter must be a single character")
    if comment is not None and len(comment) != 1:
        raise ValueError("csv comment char must be a single character")

    # Split records strictly at '\n' like Go's csv reader: Python streams
    # opened with newline='' (and some user-supplied streams) treat a lone
    # '\r' as a line ending, which would corrupt fields containing bare
    # carriage returns — re-join such fragments.
    def _lf_lines():
        buf = []
        while True:
            piece = stream.readline()
            if piece == "":
                if buf:
                    yield "".join(buf)
                return
            buf.append(piece)
            if piece.endswith("\n"):
                yield "".join(buf)
                buf = []

    _gen = _lf_lines()

    def readline() -> str:
        return next(_gen, "")

    while True:
        line = readline()
        if line == "":
            return  # EOF
        # record start: skip comment lines and blank lines
        if comment is not None and line.startswith(comment):
            continue
        if line in ("\n", "\r\n"):
            continue
        yield _parse_one(line, readline, delimiter, lazy_quotes, trim_leading_space)


def _strip_eol(line: str) -> "tuple[str, bool]":
    """Remove a trailing record terminator; returns (body, had_terminator)."""
    if line.endswith("\r\n"):
        return line[:-2], True
    if line.endswith("\n"):
        return line[:-1], True
    return line, False


def _parse_one(
    line: str,
    readline,
    delimiter: str,
    lazy_quotes: bool,
    trim_leading_space: bool,
) -> List[str]:
    fields: List[str] = []
    body, _ = _strip_eol(line)
    pos = 0

    while True:  # one field per loop
        if trim_leading_space:
            while pos < len(body) and _is_space(body[pos]):
                pos += 1

        if pos < len(body) and body[pos] == '"':
            # ---- quoted field -------------------------------------------
            pos += 1
            buf: List[str] = []
            while True:
                if pos >= len(body):
                    # quoted field continues on the next line
                    nxt = readline()
                    if nxt == "":
                        if lazy_quotes:
                            fields.append("".join(buf))
                            return fields
                        raise CsvParseError(ERR_QUOTE)
                    nxt_body, _ = _strip_eol(nxt)
                    buf.append("\n")  # the line break is part of the field
                    body, pos = nxt_body, 0
                    continue
                c = body[pos]
                if c == '"':
                    if pos + 1 < len(body) and body[pos + 1] == '"':
                        buf.append('"')  # doubled quote -> literal
                        pos += 2
                        continue
                    # closing quote: must be followed by delimiter or EOL
                    pos += 1
                    if pos >= len(body):
                        fields.append("".join(buf))
                        return fields
                    if body[pos] == delimiter:
                        fields.append("".join(buf))
                        pos += 1
                        break  # next field
                    if lazy_quotes:
                        buf.append('"')
                        continue
                    raise CsvParseError(ERR_QUOTE)
                buf.append(c)
                pos += 1
        else:
            # ---- unquoted field -----------------------------------------
            start = pos
            while pos < len(body) and body[pos] != delimiter:
                if body[pos] == '"' and not lazy_quotes:
                    raise CsvParseError(ERR_BARE_QUOTE)
                pos += 1
            fields.append(body[start:pos])
            if pos >= len(body):
                return fields
            pos += 1  # skip delimiter; next field


# ---------------------------------------------------------------------------
# writer — Go csv.Writer semantics (default settings, UseCRLF=false)
# ---------------------------------------------------------------------------


def _field_needs_quotes(field: str, delimiter: str) -> bool:
    if field == "":
        return False
    if field == "\\.":
        return True  # Postgres end-of-data marker, quoted by Go too
    if delimiter in field or '"' in field or "\r" in field or "\n" in field:
        return True
    return field[0].isspace()


def write_record(out, fields: List[str], delimiter: str = ",") -> None:
    """Write one CSV record in Go csv.Writer's canonical form."""
    parts: List[str] = []
    for f in fields:
        if _field_needs_quotes(f, delimiter):
            parts.append('"' + f.replace('"', '""') + '"')
        else:
            parts.append(f)
    out.write(delimiter.join(parts))
    out.write("\n")
