"""Eager sinks: the terminals that drive a lazy chain.

Reference: ``ToCsv``/``ToCsvFile`` csvplus.go:376-415, ``ToJSON``/
``ToJSONFile`` csvplus.go:445-480, ``ToRows`` csvplus.go:483-490, plus the
atomic ``writeFile`` helper (csvplus.go:418-443): on any error — including
an exception unwinding through the sink — the partially-written file is
closed and removed, so sinks never leave partial outputs behind.

A device-planned source executes its fused plan inside the ``src(fn)``
call itself (its driver is :func:`csvplus_tpu.columnar.exec.plan_runner`),
so sinks are agnostic: output bytes and error wrapping are identical on
both paths.
"""

from __future__ import annotations

import os
from typing import IO, List

from .csvio import write_record
from .row import Row
from .utils.gojson import go_json_object


def to_csv(src, out: IO[str], *columns: str) -> None:
    """Write selected columns in canonical CSV form: header line first,
    fixed arity (csvplus.go:379-406).

    Device-planned sources encode the whole body with vectorized numpy
    string ops (byte-identical to the streaming writer); anything that
    needs per-row error semantics streams row by row.
    """
    if not columns:
        raise ValueError("empty column list in ToCsv() function")

    write_record(out, list(columns))

    if getattr(src, "plan", None) is not None:
        from .columnar.csvenc import encode_csv_body
        from .columnar.exec import device_table_for

        table = device_table_for(src)  # memoized: never runs a prefix twice
        if table is not None:
            body = encode_csv_body(table, columns)
            if body is not None:
                out.write(body)
                return
            # stream the already-computed table for exact per-row
            # missing-column errors / partial output
            from .source import iterate

            iterate(
                table.to_rows(),
                lambda row: write_record(out, row.select_values(*columns)),
                clone=False,
            )
            return

    def fn(row: Row) -> None:
        write_record(out, row.select_values(*columns))

    src(fn)


def to_csv_file(src, name: str, *columns: str) -> None:
    """CSV sink to a named file with no-partial-output guarantee
    (csvplus.go:411-415)."""
    _write_file(name, lambda f: to_csv(src, f, *columns))


def to_json(src, out: IO[str]) -> None:
    """Stream rows as a JSON array of objects (csvplus.go:446-475).

    Matches the reference's byte format exactly: Go's ``json.Encoder``
    emits each object compactly with **sorted keys**, followed by a
    newline; objects are comma-separated inside ``[...]`` and flushed in
    ~10KB batches.  The reference sets ``SetEscapeHTML(false)``
    (csvplus.go:456), so ``&<>`` pass through unescaped; Go's remaining
    escaping rules (``\\u0008``/``\\u000c`` for backspace/form-feed,
    always-escaped U+2028/U+2029) are reproduced by
    :func:`csvplus_tpu.utils.gojson.go_json_object`.
    """
    if getattr(src, "plan", None) is not None:
        from .columnar.csvenc import encode_json_body
        from .columnar.exec import device_table_for

        table = device_table_for(src)
        if table is not None:
            body = encode_json_body(table)
            if body is not None:
                out.write("[" + body + "]")
                return
            # heterogeneous rows: stream the computed table instead
            from .source import iterate

            rows_out: List[Row] = []
            iterate(table.to_rows(), rows_out.append, clone=False)
            src = lambda fn: [fn(r) for r in rows_out]  # noqa: E731

    buf: List[str] = ["["]
    buf_len = 1
    count = 0

    def emit(row: Row) -> None:
        nonlocal buf_len, count
        count += 1
        if count != 1:
            buf.append(",")
            buf_len += 1
        s = go_json_object(row) + "\n"
        buf.append(s)
        buf_len += len(s)
        if buf_len > 10000:
            out.write("".join(buf))
            buf.clear()
            buf_len = 0

    src(emit)

    buf.append("]")
    out.write("".join(buf))


def to_json_file(src, name: str) -> None:
    """JSON sink to a named file with no-partial-output guarantee
    (csvplus.go:478-480)."""
    _write_file(name, lambda f: to_json(src, f))


def to_rows(src) -> List[Row]:
    """Materialize the source into a list of Rows (csvplus.go:483-490).

    A device-planned source executes its fused plan inside ``src(fn)``
    (see :func:`csvplus_tpu.columnar.exec.plan_runner`), so sinks need no
    device special-casing — and error wrapping is identical either way."""
    hint = getattr(src, "_rows_hint", None)
    if hint is not None:
        # take_rows-backed source (the point-lookup hot path): clone
        # straight off the backing list — identical to what iterate()
        # would deliver, minus the per-row callback machinery
        return [Row(r) for r in hint]
    out: List[Row] = []
    src(out.append)
    return out


def to_rows_many(sources) -> List[List[Row]]:
    """Materialize a batch of sources — one Row list per source, in
    order.  The natural sink for :meth:`Index.find_many` results: the
    batched lookup engine has already amortized the search and decode,
    so this is pure iteration."""
    out = []
    for src in sources:
        hint = getattr(src, "_rows_hint", None)
        out.append(
            [Row(r) for r in hint] if hint is not None else to_rows(src)
        )
    return out


def _write_file(name: str, fn, mode: str = "w") -> None:
    """Create *name*, run *fn(file)*; on ANY failure remove the file
    (csvplus.go:418-443).  ``mode="wb"`` for binary sinks."""
    if "b" in mode:
        f = open(name, mode)
    else:
        f = open(name, mode, encoding="utf-8", newline="")
    try:
        fn(f)
        f.close()  # close failure (e.g. ENOSPC flush) also removes the file
    except BaseException:
        try:
            f.close()
        except OSError:
            pass
        try:
            os.remove(name)
        except OSError:
            pass
        raise
