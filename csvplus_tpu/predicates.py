"""Predicate combinator DSL: All / Any / Not / Like.

Reference: csvplus.go:1240-1293.  In the reference these return opaque Go
closures.  Here they are *callable objects* — they work anywhere a plain
``row -> bool`` function works (host path), but they are also **symbolic**
(``__plan_expr__ = True``): the device executor can introspect them and
lower the whole boolean expression to a fused vectorized kernel over
columnar data instead of calling back into Python per row.
"""

from __future__ import annotations

from typing import Callable, Mapping, Union

from .row import Row

PredLike = Union[Callable[[Row], bool], "Predicate"]


class Predicate:
    """Base class: a callable row predicate that is also a symbolic expr."""

    __plan_expr__ = True
    __slots__ = ()

    def __call__(self, row: Row) -> bool:  # pragma: no cover - abstract
        raise NotImplementedError

    # boolean-algebra sugar (not in the reference, natural in Python)
    def __and__(self, other: PredLike) -> "All":
        return All(self, other)

    def __or__(self, other: PredLike) -> "Any_":
        return Any_(self, other)

    def __invert__(self) -> "Not":
        return Not(self)


class Like(Predicate):
    """True when the input row matches every (column, value) pair of the
    match row (csvplus.go:1279-1293)."""

    __slots__ = ("match",)

    def __init__(self, match: Mapping[str, str]):
        if not match:
            raise ValueError("empty match row in Like() predicate")
        self.match = dict(match)

    def __call__(self, row: Row) -> bool:
        for key, val in self.match.items():
            if key not in row or row[key] != val:
                return False
        return True

    def __repr__(self) -> str:
        return f"Like({self.match!r})"


class All(Predicate):
    """Logical AND of the given predicates (csvplus.go:1243-1253)."""

    __slots__ = ("preds",)

    def __init__(self, *preds: PredLike):
        self.preds = tuple(preds)

    def __call__(self, row: Row) -> bool:
        return all(p(row) for p in self.preds)

    def __repr__(self) -> str:
        return f"All{self.preds!r}"

    @property
    def symbolic(self) -> bool:
        return all(getattr(p, "__plan_expr__", False) for p in self.preds)


class Any_(Predicate):
    """Logical OR of the given predicates (csvplus.go:1258-1268)."""

    __slots__ = ("preds",)

    def __init__(self, *preds: PredLike):
        self.preds = tuple(preds)

    def __call__(self, row: Row) -> bool:
        return any(p(row) for p in self.preds)

    def __repr__(self) -> str:
        return f"Any{self.preds!r}"

    @property
    def symbolic(self) -> bool:
        return all(getattr(p, "__plan_expr__", False) for p in self.preds)


class Not(Predicate):
    """Logical negation of the given predicate (csvplus.go:1271-1275)."""

    __slots__ = ("pred",)

    def __init__(self, pred: PredLike):
        self.pred = pred

    def __call__(self, row: Row) -> bool:
        return not self.pred(row)

    def __repr__(self) -> str:
        return f"Not({self.pred!r})"

    @property
    def symbolic(self) -> bool:
        return getattr(self.pred, "__plan_expr__", False)
