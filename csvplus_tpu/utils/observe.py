"""Observability: per-stage telemetry, now a shim over ``csvplus_tpu.obs``.

The reference has no instrumentation at all (SURVEY.md §5: the only
observability is error line numbers).  This module grew from "row
counts and wall times" into the compatibility surface of a first-class
subsystem (:mod:`csvplus_tpu.obs`, docs/OBSERVABILITY.md): per-stage
wall times and row counts, named counters, host-sync accounting — and,
whenever a span trace is active in the calling context, every stage
recorded here ALSO opens a span in that trace, so the flat table and
the hierarchical per-query view come from the same instrumentation
points:

* :data:`telemetry` — opt-in collector of per-stage statistics from the
  device plan executor, the columnar ingest, the joins, and the serving
  dispatcher; cheap enough to leave on in production pipelines (a few
  host ops per stage, never per row).  Mutation is lock-guarded: ingest
  workers and the serve dispatcher record stages concurrently
  (THREAD001 covers the entry points);
* :func:`profile_to` — context manager around ``jax.profiler.trace`` so
  a whole pipeline run can be captured for XProf/Perfetto; the span
  exporter (:func:`csvplus_tpu.obs.export.export_chrome_trace`) writes
  the host-side trace into the same ``log_dir`` so both open together;
* ``TraceAnnotation`` pass-through so executor stages show up as named
  ranges inside device traces.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Iterator, List

from ..obs.span import tracer

# count-shaped stage extras that SUM when records of one stage name
# merge (next to the ``_s``-suffix per-worker second tallies); the skew
# trio lets a multi-join pipeline's ``join:skew`` rows report total
# routed rows, not the last join's
_SUMMED_EXTRAS = frozenset(
    {"chunks", "hot_keys", "rows_broadcast", "rows_repartitioned"}
)


@dataclass
class StageRecord:
    """One executed pipeline stage."""

    stage: str  # e.g. "Filter", "Join", "ingest:native-encoded"
    rows_in: int
    rows_out: int
    seconds: float
    # any other keys the stage body set (e.g. the sharded-ingest
    # assembly's n_shards / max_shard_rows placement evidence)
    extra: dict = field(default_factory=dict)

    def __str__(self) -> str:
        return (
            f"{self.stage:<24} {self.rows_in:>12} -> {self.rows_out:<12}"
            f" {self.seconds * 1e3:9.2f} ms"
        )


@dataclass
class Telemetry:
    """Opt-in pipeline statistics collector (process-global singleton)."""

    enabled: bool = False
    records: List[StageRecord] = field(default_factory=list)
    # elements explicitly synced device->host by the partitioned join's
    # device orchestration (hot-key samples + overflow scalars): the
    # evidence that the multi-chip probe path crosses O(1)-ish data per
    # stage, not O(n) (VERDICT round-2 weak #3's done criterion)
    host_sync_elements: int = 0
    # generic named counters for subsystems whose evidence is a tally,
    # not a stage timing — e.g. the plan verifier's diagnostics-per-rule
    # counts ("verify.resolution", "verify.divergence-risk", ...)
    counters: Dict[str, int] = field(default_factory=dict)
    # mutation guard: ingest workers and the serve dispatcher call
    # count()/add_stage() concurrently with collecting readers
    _lock: threading.Lock = field(
        default_factory=threading.Lock, repr=False, compare=False
    )

    def reset(self) -> None:
        with self._lock:
            self.records.clear()
            self.host_sync_elements = 0
            self.counters.clear()

    def count_sync(self, n: int) -> None:
        if self.enabled:
            with self._lock:
                self.host_sync_elements += int(n)

    def count(self, name: str, n: int = 1) -> None:
        """Bump a named counter (no-op unless collection is enabled)."""
        if self.enabled:
            with self._lock:
                self.counters[name] = self.counters.get(name, 0) + int(n)

    @contextlib.contextmanager
    def collect(self) -> Iterator[List[StageRecord]]:
        """Enable collection within a scope; yields the record list."""
        prev = self.enabled
        self.enabled = True
        self.reset()
        try:
            yield self.records
        finally:
            self.enabled = prev

    @contextlib.contextmanager
    def stage(self, name: str, rows_in: int) -> Iterator[dict]:
        """Record one stage; the body may set ``out['rows_out']``, or set
        ``out['discard'] = True`` to drop the record (e.g. a fast-path
        tier that declined and handed off to another tier).

        Span shim: when a trace is active in the calling context
        (:data:`csvplus_tpu.obs.span.tracer`), the stage also opens a
        child span there — the hierarchical view needs no new call
        sites.  The span keeps even discarded/failed stages (annotated),
        because a trace records what HAPPENED, while the table records
        what counted."""
        handle = tracer.open_span(name, rows_in=int(rows_in))
        if not self.enabled and handle is None:
            yield {}
            return
        out: dict = {}
        t0 = time.perf_counter()
        try:
            with _trace_annotation(f"csvplus:{name}"):
                yield out
        except BaseException:
            if handle is not None:
                tracer.close_span(handle, error=True, **out)
                handle = None
            raise
        finally:
            if handle is not None:
                tracer.close_span(handle, **out)
        if out.get("discard") or not self.enabled:
            return
        with self._lock:
            self.records.append(
                StageRecord(
                    stage=name,
                    rows_in=rows_in,
                    rows_out=int(out.get("rows_out", rows_in)),
                    seconds=time.perf_counter() - t0,
                    extra={
                        k: v
                        for k, v in out.items()
                        if k not in ("rows_out", "discard")
                    },
                )
            )

    def barrier(self, x):
        """``jax.block_until_ready(x)`` when collecting, so async device
        work lands inside the stage that dispatched it and per-stage
        times are attributable.  A strict no-op (and zero dispatch-
        overlap cost) when collection is off — headline timings are
        measured with telemetry disabled, the per-stage table with it
        enabled."""
        if self.enabled and x is not None:
            import jax

            jax.block_until_ready(x)
        return x

    def add_stage(
        self, name: str, rows_in: int, rows_out: int, seconds: float, **extra
    ) -> None:
        """Record a PRE-MEASURED stage — for work accumulated across many
        small slices (e.g. per-chunk producer waits or per-shard seals in
        the streaming ingest) where a contextmanager per slice would
        drown the measurement in bookkeeping.  One record per call; also
        mirrored as a pre-measured span when a trace is active."""
        tracer.add_span(name, float(seconds), rows_in=int(rows_in), **extra)
        if not self.enabled:
            return
        with self._lock:
            self.records.append(
                StageRecord(
                    stage=name,
                    rows_in=int(rows_in),
                    rows_out=int(rows_out),
                    seconds=float(seconds),
                    extra=extra,
                )
            )

    def merged_stages(self) -> List[StageRecord]:
        """Records merged by stage name (first-seen order): seconds and
        row counts summed; ACCUMULABLE extras (keys ending in ``_s`` —
        per-worker second tallies like the staged ingest's ``scan_s`` /
        ``encode_s`` — plus the count-shaped ``chunks`` and the skew
        router's ``hot_keys`` / ``rows_broadcast`` /
        ``rows_repartitioned``) sum too, all other extras taken
        from the last record of the name (configuration-shaped values
        like ``workers`` or ``max_shard_rows`` must not add across
        records).  This is the per-stage table shape the bench artifacts
        carry — a 3-join pipeline records e.g. 'join:translate' once per
        join, but the artifact wants one line per stage kind."""
        with self._lock:
            records = list(self.records)
        order: List[str] = []
        merged: Dict[str, StageRecord] = {}
        for r in records:
            got = merged.get(r.stage)
            if got is None:
                order.append(r.stage)
                merged[r.stage] = StageRecord(
                    r.stage, r.rows_in, r.rows_out, r.seconds, dict(r.extra)
                )
            else:
                got.rows_in += r.rows_in
                got.rows_out += r.rows_out
                got.seconds += r.seconds
                for k, v in r.extra.items():
                    old = got.extra.get(k)
                    if (
                        (k.endswith("_s") or k in _SUMMED_EXTRAS)
                        and isinstance(v, (int, float))
                        and isinstance(old, (int, float))
                    ):
                        got.extra[k] = old + v
                    else:
                        got.extra[k] = v
        return [merged[name] for name in order]

    def to_json(self) -> dict:
        """JSON-safe snapshot: the merged stage table plus counters and
        host-sync accounting — the exact shape the bench artifacts
        embed, so drivers stop hand-rolling it."""
        merged = self.merged_stages()
        with self._lock:
            counters = dict(self.counters)
            host_sync = self.host_sync_elements
        return {
            "stage_table": [
                {
                    "stage": r.stage,
                    "rows_in": r.rows_in,
                    "rows_out": r.rows_out,
                    "seconds": round(r.seconds, 4),
                    **r.extra,
                }
                for r in merged
            ],
            "counters": counters,
            "host_sync_elements": host_sync,
        }

    def report(self) -> str:
        head = f"{'stage':<24} {'rows in':>12}    {'rows out':<12} {'time':>9}"
        with self._lock:
            records = list(self.records)
            counters = dict(self.counters)
            host_sync = self.host_sync_elements
        lines = [head] + [str(r) for r in records]
        if counters:
            lines.append("counters:")
            lines.extend(
                f"  {name:<38} {counters[name]:>12}"
                for name in sorted(counters)
            )
        lines.append(f"host_sync_elements: {host_sync}")
        return "\n".join(lines)


telemetry = Telemetry()


@contextlib.contextmanager
def _trace_annotation(name: str):
    # best-effort: only the annotation SETUP may be swallowed — exceptions
    # from the body must propagate unchanged (a yield inside the except
    # would turn them into "generator didn't stop after throw()")
    try:
        import jax.profiler

        cm = jax.profiler.TraceAnnotation(name)
    except Exception:
        cm = contextlib.nullcontext()
    with cm:
        yield


@contextlib.contextmanager
def profile_to(log_dir: str):
    """Capture a JAX device trace of the enclosed pipeline run for
    XProf/Perfetto (``jax.profiler.trace``).  Host-side spans exported
    with :func:`csvplus_tpu.obs.export.export_chrome_trace` into the
    same ``log_dir`` open alongside it."""
    import jax.profiler

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
