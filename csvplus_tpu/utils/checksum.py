"""Order-independent column checksums for full-result verification.

BASELINE's north-star criterion is "identical output rows".  Decoding
100M device rows to host dicts just to compare them would dwarf the
join being verified, so verification uses a per-column checksum that
both executors can produce cheaply:

* per VALUE: FNV-1a (32-bit) over the value's UTF-8 bytes — computed
  vectorized on host over a column's *dictionary* (each distinct value
  hashed once);
* per COLUMN: the sum mod 2^32 of every row's value hash — on device
  this is one gather (codes -> dictionary-hash table) and one reduce,
  so checksumming the full 100M-row result costs two ops per column
  and syncs one scalar.

With ``positional=True`` each row's hash is multiplied by the odd
weight ``2*i + 1`` (i = row position) before summing, making the sum
ORDER-SENSITIVE: a row permutation or cross-row cell swap between rows
holding different values changes the column sum with high probability
(a swap of rows i,j survives only when ``(h_i - h_j)*(j - i) == 0 mod
2^31`` — the usual 32-bit-checksum collision odds, not a guarantee).
The north-star parity check uses positional sums so stream order
(csvplus.go:552-568) is covered by the checksum itself, not just by
spot rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def fnv1a_values(values: np.ndarray) -> np.ndarray:
    """Vectorized 32-bit FNV-1a over each entry of an 'S' bytes array
    (trailing NUL padding excluded, matching the true value bytes)."""
    values = np.asarray(values)
    if values.dtype.kind == "U":
        values = np.char.encode(values, "utf-8")
    n = values.size
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    width = values.dtype.itemsize
    mat = np.frombuffer(values.tobytes(), dtype=np.uint8).reshape(n, width)
    lens = np.char.str_len(values)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for i in range(width):
            live = i < lens
            nh = (h ^ mat[:, i]) * _FNV_PRIME
            h = np.where(live, nh, h)
    return h


def checksum_host_rows(
    rows: Sequence, columns: Sequence[str], positional: bool = False
) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) for host Row dicts; an absent
    cell contributes 0.  ``positional=True`` makes the sum
    order-sensitive (see module docstring)."""
    out = {}
    for c in columns:
        vals = [r.get(c) for r in rows]
        present = np.array([v is not None for v in vals], dtype=bool)
        hashes = np.zeros(len(vals), dtype=np.uint32)
        if present.any():
            arr = np.array([v for v in vals if v is not None], dtype=np.str_)
            hashes[present] = fnv1a_values(arr)
        if positional and hashes.size:
            with np.errstate(over="ignore"):
                hashes = hashes * (
                    2 * np.arange(hashes.size, dtype=np.uint32) + np.uint32(1)
                )
        out[c] = int(np.add.reduce(hashes, dtype=np.uint32))
    return out


def fnv1a_lanes_device(lane_arrays):
    """32-bit FNV-1a per dictionary entry, computed ON DEVICE from the
    sign-flipped int32 lane packing (ops/lanes.py) — no dictionary
    download, so checksumming a device-lane column preserves its
    bounded-host-RSS contract (ADVICE r3).  Byte-for-byte identical to
    :func:`fnv1a_values` on the unpacked dictionary: bytes are extracted
    big-endian per lane word, trailing NULs excluded via a per-entry
    last-nonzero-byte length."""
    import jax.numpy as jnp

    from ..ops.lanes import _SIGN

    n = lane_arrays[0].shape[0]
    if n == 0:
        return jnp.empty(0, dtype=jnp.uint32)
    # bytes[i][pos] for pos = 4*lane + shift, big-endian within the word
    byte_cols = []
    for lane in lane_arrays:
        word = (jnp.asarray(lane) ^ jnp.int32(_SIGN)).astype(jnp.uint32)
        for shift in (24, 16, 8, 0):
            byte_cols.append((word >> shift) & jnp.uint32(0xFF))
    # value length = last non-NUL byte position + 1 (pack_host pads with
    # NULs; np.char.str_len strips exactly the trailing ones)
    length = jnp.zeros(n, dtype=jnp.int32)
    for pos, b in enumerate(byte_cols):
        length = jnp.maximum(length, jnp.where(b != 0, pos + 1, 0))
    h = jnp.full(n, jnp.uint32(2166136261))
    for pos, b in enumerate(byte_cols):
        nh = (h ^ b) * jnp.uint32(16777619)
        h = jnp.where(pos < length, nh, h)
    return h


def fnv1a_affix_int_device(prefix: bytes, values) -> "object":
    """32-bit FNV-1a per ROW of a typed affix-int32 column, computed ON
    DEVICE from the value lanes — byte-identical to :func:`fnv1a_values`
    over ``prefix + decimal(value)``, with no formatting and no
    dictionary (typed columns have neither).  The constant prefix folds
    into the seed on host; the per-row part hashes an optional '-' and
    the up-to-10 decimal digits MSB-first via pow10 gathers."""
    import jax.numpy as jnp

    h0 = int(_FNV_OFFSET)
    for b in prefix:
        h0 = ((h0 ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
    v = jnp.asarray(values)
    neg = v < 0
    av = jnp.where(neg, -v, v)  # |v| <= 2^31-1 (parser rejects INT32_MIN)
    h = jnp.full(v.shape, jnp.uint32(h0))
    h = jnp.where(neg, (h ^ jnp.uint32(ord("-"))) * jnp.uint32(_FNV_PRIME), h)
    pow10 = jnp.asarray([10**k for k in range(10)], dtype=jnp.int32)
    nd = jnp.ones(v.shape, jnp.int32)
    for k in range(1, 10):
        nd = nd + (av >= pow10[k]).astype(jnp.int32)
    for i in range(10):
        e = jnp.clip(nd - 1 - i, 0, 9)
        p = jnp.take(pow10, e, axis=0)
        digit = (av // p) % 10
        byte = (jnp.uint32(ord("0")) + digit.astype(jnp.uint32))
        active = i < nd
        h = jnp.where(active, (h ^ byte) * jnp.uint32(_FNV_PRIME), h)
    return h


def checksum_device_table(
    table,
    columns: Optional[Sequence[str]] = None,
    limit: Optional[int] = None,
    positional: bool = False,
) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) of a DeviceTable, computed on
    device: dictionary hashes upload once per column (each distinct
    value hashed once on host), then one gather + one reduce per column
    and a single scalar sync for the whole table.  Device-lane columns
    hash their packed lanes on device instead (no host download).
    ``positional=True`` makes the sums order-sensitive."""
    import jax
    import jax.numpy as jnp

    names = list(columns) if columns is not None else list(table.columns)
    n = table.nrows if limit is None else min(limit, table.nrows)
    weights = (
        2 * jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(1) if positional else None
    )
    # mesh-sharded tables: each column's reduction lowers to a cross-
    # device all-reduce; concurrent eagerly-dispatched collective
    # programs can race the XLA:CPU rendezvous (observed: 7-of-8
    # participants, hard abort), so their scalars sync one at a time
    serialize = any(
        len(getattr(table.columns[c].storage, "sharding", None).device_set) > 1
        if getattr(table.columns[c].storage, "sharding", None) is not None
        else False
        for c in names
    )
    sums = []
    for c in names:
        col = table.columns[c]
        if getattr(col, "kind", "str") == "int":
            # typed value lanes hash per row directly (no dictionary,
            # no demotion); all cells present by the typed invariant
            gathered = fnv1a_affix_int_device(col.prefix, col.values[:n])
        else:
            if (
                getattr(col, "dev_dictionary", None) is not None
                and col._dictionary is None
            ):
                htab = fnv1a_lanes_device(col.dev_dictionary)
            else:
                htab = jax.device_put(
                    fnv1a_values(col.dictionary).astype(jnp.uint32)
                )
            codes = col.codes[:n]
            gathered = jnp.take(htab, jnp.clip(codes, 0), axis=0)
            gathered = jnp.where(codes >= 0, gathered, jnp.uint32(0))
        if weights is not None:
            gathered = gathered * weights
        s = jnp.sum(gathered, dtype=jnp.uint32)
        sums.append(np.uint32(s) if serialize else s)
    if serialize:
        return {c: int(v) for c, v in zip(names, sums)}
    stacked = np.asarray(jnp.stack(sums)) if sums else np.empty(0, np.uint32)
    return {c: int(v) for c, v in zip(names, stacked)}
