"""Order-independent column checksums for full-result verification.

BASELINE's north-star criterion is "identical output rows".  Decoding
100M device rows to host dicts just to compare them would dwarf the
join being verified, so verification uses a per-column checksum that
both executors can produce cheaply:

* per VALUE: FNV-1a (32-bit) over the value's UTF-8 bytes — computed
  vectorized on host over a column's *dictionary* (each distinct value
  hashed once);
* per COLUMN: the sum mod 2^32 of every row's value hash — on device
  this is one gather (codes -> dictionary-hash table) and one reduce,
  so checksumming the full 100M-row result costs two ops per column
  and syncs one scalar.

The sum is order-independent; row ORDER is covered separately by the
row-count assert plus the host-executor comparison on a deterministic
prefix slice (both executors emit stream order, csvplus.go:552-568).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def fnv1a_values(values: np.ndarray) -> np.ndarray:
    """Vectorized 32-bit FNV-1a over each entry of an 'S' bytes array
    (trailing NUL padding excluded, matching the true value bytes)."""
    values = np.asarray(values)
    if values.dtype.kind == "U":
        values = np.char.encode(values, "utf-8")
    n = values.size
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    width = values.dtype.itemsize
    mat = np.frombuffer(values.tobytes(), dtype=np.uint8).reshape(n, width)
    lens = np.char.str_len(values)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for i in range(width):
            live = i < lens
            nh = (h ^ mat[:, i]) * _FNV_PRIME
            h = np.where(live, nh, h)
    return h


def checksum_host_rows(rows: Sequence, columns: Sequence[str]) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) for host Row dicts; an absent
    cell contributes 0."""
    out = {}
    for c in columns:
        vals = [r.get(c) for r in rows]
        present = np.array([v is not None for v in vals], dtype=bool)
        hashes = np.zeros(len(vals), dtype=np.uint32)
        if present.any():
            arr = np.array([v for v in vals if v is not None], dtype=np.str_)
            hashes[present] = fnv1a_values(arr)
        out[c] = int(np.add.reduce(hashes, dtype=np.uint32))
    return out


def checksum_device_table(
    table, columns: Optional[Sequence[str]] = None, limit: Optional[int] = None
) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) of a DeviceTable, computed on
    device: dictionary hashes upload once per column (each distinct
    value hashed once on host), then one gather + one reduce per column
    and a single scalar sync for the whole table."""
    import jax
    import jax.numpy as jnp

    names = list(columns) if columns is not None else list(table.columns)
    n = table.nrows if limit is None else min(limit, table.nrows)
    sums = []
    for c in names:
        col = table.columns[c]
        htab = jax.device_put(fnv1a_values(col.dictionary).astype(jnp.uint32))
        codes = col.codes[:n]
        gathered = jnp.take(htab, jnp.clip(codes, 0), axis=0)
        gathered = jnp.where(codes >= 0, gathered, jnp.uint32(0))
        sums.append(jnp.sum(gathered, dtype=jnp.uint32))
    stacked = np.asarray(jnp.stack(sums)) if sums else np.empty(0, np.uint32)
    return {c: int(v) for c, v in zip(names, stacked)}
