"""Order-independent column checksums for full-result verification.

BASELINE's north-star criterion is "identical output rows".  Decoding
100M device rows to host dicts just to compare them would dwarf the
join being verified, so verification uses a per-column checksum that
both executors can produce cheaply:

* per VALUE: FNV-1a (32-bit) over the value's UTF-8 bytes — computed
  vectorized on host over a column's *dictionary* (each distinct value
  hashed once);
* per COLUMN: the sum mod 2^32 of every row's value hash — on device
  this is one gather (codes -> dictionary-hash table) and one reduce,
  so checksumming the full 100M-row result costs two ops per column
  and syncs one scalar.

With ``positional=True`` each row's hash is multiplied by the odd
weight ``2*i + 1`` (i = row position) before summing, making the sum
ORDER-SENSITIVE: a row permutation or cross-row cell swap between rows
holding different values changes the column sum with high probability
(a swap of rows i,j survives only when ``(h_i - h_j)*(j - i) == 0 mod
2^31`` — the usual 32-bit-checksum collision odds, not a guarantee).
The north-star parity check uses positional sums so stream order
(csvplus.go:552-568) is covered by the checksum itself, not just by
spot rows.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

_FNV_OFFSET = np.uint32(2166136261)
_FNV_PRIME = np.uint32(16777619)


def fnv1a_values(values: np.ndarray) -> np.ndarray:
    """Vectorized 32-bit FNV-1a over each entry of an 'S' bytes array
    (trailing NUL padding excluded, matching the true value bytes)."""
    values = np.asarray(values)
    if values.dtype.kind == "U":
        values = np.char.encode(values, "utf-8")
    n = values.size
    if n == 0:
        return np.empty(0, dtype=np.uint32)
    width = values.dtype.itemsize
    mat = np.frombuffer(values.tobytes(), dtype=np.uint8).reshape(n, width)
    lens = np.char.str_len(values)
    h = np.full(n, _FNV_OFFSET, dtype=np.uint32)
    with np.errstate(over="ignore"):
        for i in range(width):
            live = i < lens
            nh = (h ^ mat[:, i]) * _FNV_PRIME
            h = np.where(live, nh, h)
    return h


def checksum_host_rows(
    rows: Sequence, columns: Sequence[str], positional: bool = False
) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) for host Row dicts; an absent
    cell contributes 0.  ``positional=True`` makes the sum
    order-sensitive (see module docstring)."""
    out = {}
    for c in columns:
        vals = [r.get(c) for r in rows]
        present = np.array([v is not None for v in vals], dtype=bool)
        hashes = np.zeros(len(vals), dtype=np.uint32)
        if present.any():
            arr = np.array([v for v in vals if v is not None], dtype=np.str_)
            hashes[present] = fnv1a_values(arr)
        if positional and hashes.size:
            with np.errstate(over="ignore"):
                hashes = hashes * (
                    2 * np.arange(hashes.size, dtype=np.uint32) + np.uint32(1)
                )
        out[c] = int(np.add.reduce(hashes, dtype=np.uint32))
    return out


def fnv1a_lanes_device(lane_arrays):
    """32-bit FNV-1a per dictionary entry, computed ON DEVICE from the
    sign-flipped int32 lane packing (ops/lanes.py) — no dictionary
    download, so checksumming a device-lane column preserves its
    bounded-host-RSS contract (ADVICE r3).  Byte-for-byte identical to
    :func:`fnv1a_values` on the unpacked dictionary: bytes are extracted
    big-endian per lane word, trailing NULs excluded via a per-entry
    last-nonzero-byte length."""
    import jax.numpy as jnp

    from ..ops.lanes import _SIGN

    n = lane_arrays[0].shape[0]
    if n == 0:
        return jnp.empty(0, dtype=jnp.uint32)
    # bytes[i][pos] for pos = 4*lane + shift, big-endian within the word
    byte_cols = []
    for lane in lane_arrays:
        word = (jnp.asarray(lane) ^ jnp.int32(_SIGN)).astype(jnp.uint32)
        for shift in (24, 16, 8, 0):
            byte_cols.append((word >> shift) & jnp.uint32(0xFF))
    # value length = last non-NUL byte position + 1 (pack_host pads with
    # NULs; np.char.str_len strips exactly the trailing ones)
    length = jnp.zeros(n, dtype=jnp.int32)
    for pos, b in enumerate(byte_cols):
        length = jnp.maximum(length, jnp.where(b != 0, pos + 1, 0))
    h = jnp.full(n, jnp.uint32(2166136261))
    for pos, b in enumerate(byte_cols):
        nh = (h ^ b) * jnp.uint32(16777619)
        h = jnp.where(pos < length, nh, h)
    return h


def _affix_rows_ops(h0, v):
    """Per-row FNV-1a of ``prefix + decimal(value)`` from the seed
    ``h0`` (the prefix folded on host).  Plain jnp ops: callable
    EAGERLY (each pass dispatches on its own, preserving the input's
    sharding — the mesh path needs this, see checksum_device_table) or
    under jit (the single-device path fuses it, see _jit_kernels)."""
    import jax.numpy as jnp

    neg = v < 0
    av = jnp.where(neg, -v, v)  # |v| <= 2^31-1 (no INT32_MIN cells)
    h = jnp.full(v.shape, h0)
    h = jnp.where(neg, (h ^ jnp.uint32(ord("-"))) * jnp.uint32(_FNV_PRIME), h)
    del neg  # eagerly this chain's live set IS the RSS peak at 100M
    pow10 = jnp.asarray([10**k for k in range(10)], dtype=jnp.int32)
    nd = jnp.ones(v.shape, jnp.int32)
    for k in range(1, 10):
        nd = nd + (av >= pow10[k]).astype(jnp.int32)
    for i in range(10):
        # one nested expression per digit: its temporaries die as the
        # enclosing op consumes them instead of persisting as locals
        byte = jnp.uint32(ord("0")) + (
            (av // jnp.take(pow10, jnp.clip(nd - 1 - i, 0, 9), axis=0)) % 10
        ).astype(jnp.uint32)
        h = jnp.where(i < nd, (h ^ byte) * jnp.uint32(_FNV_PRIME), h)
        del byte
    return h


def _dict_rows_ops(htab, codes):
    """Per-row hash via dictionary-hash-table gather; absent cells
    (code < 0) contribute 0.  Eager- and jit-callable like
    :func:`_affix_rows_ops`."""
    import jax.numpy as jnp

    g = jnp.take(htab, jnp.clip(codes, 0), axis=0)
    return jnp.where(codes >= 0, g, jnp.uint32(0))


def _pos_weights(n):
    import jax.numpy as jnp

    return 2 * jnp.arange(n, dtype=jnp.uint32) + jnp.uint32(1)


_JIT_KERNELS: dict = {}


def _jit_kernels() -> dict:
    """Jitted checksum kernels, built lazily (module import stays
    jax-free).  Fusing matters at scale, for memory before speed: the
    eager affix hash chain is ~30 unfused element-wise passes holding
    several full-column intermediates alive at once (~2GB of transient
    host RSS at 100M rows on the CPU backend, the same disease as the
    r06 probe-translate regression); the fused kernels stream them and
    return one scalar per column.  SINGLE-DEVICE columns only: on the
    virtual 8-device mesh these fused programs regressed peak host RSS
    ~1.6x at 100M rows (measured 7.2GB eager -> 11.8GB fused, with the
    positional weights as traced iota and no input slicing), so
    mesh-sharded columns keep the eager per-op chain whose every pass
    demonstrably preserves the input's sharding."""
    if _JIT_KERNELS:
        return _JIT_KERNELS
    import jax
    import jax.numpy as jnp

    _JIT_KERNELS.update(
        affix_rows=jax.jit(_affix_rows_ops),
        affix_sum=jax.jit(
            lambda h0, v: jnp.sum(_affix_rows_ops(h0, v), dtype=jnp.uint32)
        ),
        affix_wsum=jax.jit(
            lambda h0, v: jnp.sum(
                _affix_rows_ops(h0, v) * _pos_weights(v.shape[0]),
                dtype=jnp.uint32,
            )
        ),
        dict_sum=jax.jit(
            lambda htab, codes: jnp.sum(
                _dict_rows_ops(htab, codes), dtype=jnp.uint32
            )
        ),
        dict_wsum=jax.jit(
            lambda htab, codes: jnp.sum(
                _dict_rows_ops(htab, codes) * _pos_weights(codes.shape[0]),
                dtype=jnp.uint32,
            )
        ),
    )
    return _JIT_KERNELS


def _affix_seed(prefix: bytes) -> int:
    h0 = int(_FNV_OFFSET)
    for b in prefix:
        h0 = ((h0 ^ b) * int(_FNV_PRIME)) & 0xFFFFFFFF
    return h0


def fnv1a_affix_int_device(prefix: bytes, values) -> "object":
    """32-bit FNV-1a per ROW of a typed affix-int32 column, computed ON
    DEVICE from the value lanes — byte-identical to :func:`fnv1a_values`
    over ``prefix + decimal(value)``, with no formatting and no
    dictionary (typed columns have neither).  The constant prefix folds
    into the seed on host (passed traced, so every prefix shares one
    executable); the per-row part hashes an optional '-' and the
    up-to-10 decimal digits MSB-first via pow10 gathers, fused in one
    jitted kernel."""
    import jax.numpy as jnp

    return _jit_kernels()["affix_rows"](
        jnp.uint32(_affix_seed(prefix)), jnp.asarray(values)
    )


def checksum_device_table(
    table,
    columns: Optional[Sequence[str]] = None,
    limit: Optional[int] = None,
    positional: bool = False,
) -> Dict[str, int]:
    """Per-column row-hash sums (mod 2^32) of a DeviceTable, computed on
    device: dictionary hashes upload once per column (each distinct
    value hashed once on host), then one gather + one reduce per column
    and a single scalar sync for the whole table.  Device-lane columns
    hash their packed lanes on device instead (no host download).
    ``positional=True`` makes the sums order-sensitive."""
    import jax
    import jax.numpy as jnp

    names = list(columns) if columns is not None else list(table.columns)
    n = table.nrows if limit is None else min(limit, table.nrows)
    # full-table checksums must NOT slice: an eager [:n] on a mesh-
    # sharded array (even the no-op n == nrows) re-materializes it
    # outside its sharding, and positional weights are iota-generated
    # inside the jitted kernels for the same reason (see _jit_kernels)
    full = n == table.nrows
    # mesh-sharded tables: each column's reduction lowers to a cross-
    # device all-reduce; concurrent eagerly-dispatched collective
    # programs can race the XLA:CPU rendezvous (observed: 7-of-8
    # participants, hard abort), so their scalars sync one at a time.
    # The sharded path also stays EAGER per op — the fused jitted
    # kernels regressed peak host RSS ~1.6x at 100M mesh rows (see
    # _jit_kernels) — while single-device columns take the fused
    # kernels for their ~2GB-smaller transient footprint.
    serialize = any(
        len(getattr(table.columns[c].storage, "sharding", None).device_set) > 1
        if getattr(table.columns[c].storage, "sharding", None) is not None
        else False
        for c in names
    )
    kernels = None if serialize else _jit_kernels()
    # eager path: one weights buffer for the whole table, PLACED WITH
    # the hash array's own sharding (a mismatched operand would make
    # GSPMD gather the sharded side), and the weighted reduce as a
    # single dot — uint32 dot wraps mod 2^32 like the summed product
    # but never materializes the 400MB hash*weight array at 100M rows
    w_host = (
        2 * np.arange(n, dtype=np.uint32) + np.uint32(1)
        if serialize and positional
        else None
    )
    w_cache: dict = {}

    def _eager_wsum(hashes):
        from jax import lax

        if w_host is None:
            return jnp.sum(hashes, dtype=jnp.uint32)
        w = w_cache.get(hashes.sharding)
        if w is None:
            w = jax.device_put(w_host, hashes.sharding)
            w_cache[hashes.sharding] = w
        return lax.dot_general(
            hashes,
            w,
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.uint32,
        )

    sums = []
    for c in names:
        col = table.columns[c]
        if getattr(col, "kind", "str") == "int":
            # typed value lanes hash per row directly (no dictionary,
            # no demotion); all cells present by the typed invariant
            seed = jnp.uint32(_affix_seed(col.prefix))
            vals = col.values if full else col.values[:n]
            if kernels is not None:
                s = (
                    kernels["affix_wsum"](seed, vals)
                    if positional
                    else kernels["affix_sum"](seed, vals)
                )
            else:
                s = _eager_wsum(_affix_rows_ops(seed, vals))
        else:
            if (
                getattr(col, "dev_dictionary", None) is not None
                and col._dictionary is None
            ):
                htab = fnv1a_lanes_device(col.dev_dictionary)
            else:
                htab = jax.device_put(
                    fnv1a_values(col.dictionary).astype(jnp.uint32)
                )
            codes = col.codes if full else col.codes[:n]
            if kernels is not None:
                s = (
                    kernels["dict_wsum"](htab, codes)
                    if positional
                    else kernels["dict_sum"](htab, codes)
                )
            else:
                s = _eager_wsum(_dict_rows_ops(htab, codes))
        sums.append(np.uint32(s) if serialize else s)
    if serialize:
        return {c: int(v) for c, v in zip(names, sums)}
    stacked = np.asarray(jnp.stack(sums)) if sums else np.empty(0, np.uint32)
    return {c: int(v) for c, v in zip(names, stacked)}
