"""Shared environment-knob parsing.

Lives in utils (not columnar.ingest) because both the native scanner
and the columnar ingest read tuning knobs, and native must not import
columnar (it would be a layering cycle: columnar.typed imports
native.scanner).
"""

from __future__ import annotations

import os


def env_int(name: str, default: int) -> int:
    """An int env knob; malformed values degrade to the default (never
    abort an ingest over a typo'd tuning variable)."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default
