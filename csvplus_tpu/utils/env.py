"""Central environment-knob registry and parsing.

Lives in utils (not columnar.ingest) because both the native scanner
and the columnar ingest read tuning knobs, and native must not import
columnar (it would be a layering cycle: columnar.typed imports
native.scanner).

Every ``os.environ`` read in the package routes through the accessors
here (``env_str``/``env_int``/``env_float``), and every variable those
accessors are asked for must be declared in ``ENV_REGISTRY`` below —
the ENV001-R lint (analysis/astlint.py) enforces both directions
statically, and ``render_env_md()`` generates ``docs/ENV.md`` from the
registry so the committed doc can never drift from the code (drift is
itself a lint failure).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Mapping, Optional


@dataclass(frozen=True)
class EnvVar:
    """One registered knob: *kind* is documentation ("int", "float",
    "flag", "str", "json"), *default* is the rendered default column
    (call sites own the live default value), *description* one line."""

    name: str
    kind: str
    default: str
    description: str


ENV_REGISTRY: Dict[str, EnvVar] = {}


def _env(name: str, kind: str, default: str, description: str) -> str:
    ENV_REGISTRY[name] = EnvVar(name, kind, default, description)
    return name


# -- ingest / native scanner ------------------------------------------------
_env("CSVPLUS_SCAN_THREADS", "int", "16",
     "Cap on native scanner worker threads (shared per process).")
_env("CSVPLUS_INGEST_WORKERS", "int", "0 (auto)",
     "Pipelined-ingest encode workers; 0 sizes from the CPU count.")
_env("CSVPLUS_STREAM_MIN_BYTES", "int", "268435456",
     "Files at or above this size take the streaming (chunked) ingest.")
_env("CSVPLUS_STREAM_CHUNK_BYTES", "int", "67108864",
     "Chunk size for the streaming scanner's mmap windows.")
_env("CSVPLUS_STREAM_PREFETCH", "int", "1",
     "Chunks scanned ahead of the encode stage in streaming ingest.")
_env("CSVPLUS_DICT_DEVICE_MIN_DISTINCT", "int", "4000000",
     "Distinct-count threshold moving dictionary builds onto device.")
_env("CSVPLUS_TYPED_LANES", "flag", "1",
     "0 disables typed int/float lanes; every column stays dictionary.")
_env("CSVPLUS_NATIVE_SO", "str", "_scanner.so",
     "Alternate native-scanner artifact name (instrumented builds).")
_env("CSVPLUS_NATIVE_CFLAGS", "str", "(empty)",
     "Extra g++ flags (space-split) appended to the native build.")
_env("CSVPLUS_DEVICE_PARSE", "flag", "(auto)",
     "1/0 forces the on-device parse tier on/off; unset = RTT probe.")
_env("CSVPLUS_DEVICE_PARSE_MAX_RTT_MS", "float", "20.0",
     "RTT probe threshold above which device parse is disabled.")

# -- ops / parallel ---------------------------------------------------------
_env("CSVPLUS_DSORT_MIN_ROWS", "int", "1000000",
     "Sharded tables at/above this row count use distributed sample-sort.")
_env("CSVPLUS_DIRECT_PROBE_MAX_BITS", "int", "23",
     "Max packed-key bits served by the dictionary-direct probe table.")
_env("CSVPLUS_PARTITION_MIN_KEYS", "int", "4000000",
     "Build sides at/above this key count use the partitioned join.")
_env("CSVPLUS_POINT_MIRROR_MAX_KEYS", "int", "16000000",
     "Max sorted-key count mirrored to host for point lookups.")
_env("CSVPLUS_MIRROR_LRU_ROWS", "int", "65536",
     "Row budget for the host mirror LRU backing point reads.")
_env("CSVPLUS_JOIN_SKEW", "flag", "1",
     "0 disables skew detection/broadcast tier (bitwise-parity hatch).")
_env("CSVPLUS_JOIN_SKEW_THRESHOLD", "float", "1/(2*shards)",
     "Heavy-hitter share threshold tau for the broadcast tier.")
_env("CSVPLUS_JOIN_SKEW_SAMPLE", "int", "4096",
     "Strided sample cap for skew detection (sync-accounting bound).")

# -- storage ----------------------------------------------------------------
_env("CSVPLUS_WAL_SYNC", "str", "always",
     "WAL fsync policy: always | interval | never (typos raise).")
_env("CSVPLUS_WAL_SEGMENT_BYTES", "int", "8388608",
     "WAL segment roll size in bytes.")
_env("CSVPLUS_LSM_RATIO", "int", "4",
     "LSM tier fan-out ratio for the compaction ladder.")
_env("CSVPLUS_LSM_READAMP_TARGET", "float", "4.0",
     "Read-amplification target steering compaction scheduling.")
_env("CSVPLUS_LSM_PRUNE", "flag", "1",
     "0/off/false disables fence+filter pruning (parity hatch).")
_env("CSVPLUS_LSM_FILTER_BITS", "int", "10",
     "Bloom filter bits per key for LSM run pruning.")
_env("CSVPLUS_LSM_FILTER_SEED", "int", "0x5EED",
     "Bloom filter hash seed (masked to 32 bits).")

# -- serve ------------------------------------------------------------------
_env("CSVPLUS_SERVE_QUEUE", "int", "8192",
     "Admission queue bound for the serve tier.")
_env("CSVPLUS_SERVE_MAX_BATCH", "int", "4096",
     "Max lookups coalesced into one device batch.")
_env("CSVPLUS_SERVE_TICK_US", "int", "0",
     "Coalescing window in microseconds; 0 = drain-immediately.")
_env("CSVPLUS_PLANCACHE_SIZE", "int", "256",
     "Compiled-plan LRU entries for the serve tier.")

# -- analysis / resilience / obs --------------------------------------------
_env("CSVPLUS_VERIFY", "flag", "1",
     "0 skips plan verification before lowering (escape hatch).")
_env("CSVPLUS_OPTIMIZE", "flag", "1",
     "0 disables the plan rewriter entirely.")
_env("CSVPLUS_MULTIWAY", "flag", "1",
     "0 disables the multiway-fuse rewrite (cascaded bench leg).")
_env("CSVPLUS_FUSE", "flag", "1",
     "0 disables probe-pass fusion (staged bench leg).")
_env("CSVPLUS_PLANCERT_N", "int", "3",
     "Max plan size (stages incl. leaf) the plan-space certifier enumerates.")
_env("CSVPLUS_PLANCERT_BUDGET_S", "float", "60.0",
     "Wall-clock budget for make plan-cert; exceeding it fails the run.")
_env("CSVPLUS_FAULTS", "json", "(unset)",
     "Fault-injection plan: JSON list of specs or {seed, faults}.")
_env("CSVPLUS_FLIGHT_DIR", "str", "(tempdir)",
     "Directory for flight-recorder dumps.")


def _require(name: str) -> None:
    if name not in ENV_REGISTRY:
        raise KeyError(
            f"unregistered env var {name!r}: declare it in "
            "csvplus_tpu/utils/env.py ENV_REGISTRY (ENV001-R)"
        )


def env_str(
    name: str,
    default: Optional[str] = None,
    env: Optional[Mapping[str, str]] = None,
) -> Optional[str]:
    """The raw string value of a registered knob (or *default* when
    unset).  *env* substitutes an explicit mapping for ``os.environ``
    (the fault-injection override path)."""
    _require(name)
    source = os.environ if env is None else env
    return source.get(name, default)


def env_int(name: str, default: int) -> int:
    """An int env knob; malformed values degrade to the default (never
    abort an ingest over a typo'd tuning variable)."""
    _require(name)
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def env_float(name: str, default: float) -> float:
    """A float env knob; malformed values degrade to the default."""
    _require(name)
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def render_env_md() -> str:
    """The generated ``docs/ENV.md`` body.  Committed output must match
    byte-for-byte; ENV001-R compares on every lint run."""
    lines = [
        "# Environment variables",
        "",
        "<!-- GENERATED FILE — do not edit.  Regenerate with",
        "     `python -m csvplus_tpu.analysis env --write docs/ENV.md`.",
        "     ENV001-R fails lint when this file drifts from",
        "     csvplus_tpu/utils/env.py ENV_REGISTRY. -->",
        "",
        "Every `os.environ` read in the package routes through "
        "`csvplus_tpu/utils/env.py`,",
        "and every variable read there is declared in its `ENV_REGISTRY` "
        "— both enforced",
        "statically by the ENV001-R lint (`make lint`).",
        "",
        "| Variable | Kind | Default | Description |",
        "| --- | --- | --- | --- |",
    ]
    for var in ENV_REGISTRY.values():
        lines.append(
            f"| `{var.name}` | {var.kind} | `{var.default}` "
            f"| {var.description} |"
        )
    lines.append("")
    return "\n".join(lines)
