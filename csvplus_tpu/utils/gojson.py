"""Byte-exact Go ``encoding/json`` string/object encoding.

The reference's JSON sink (csvplus.go:446-475) uses a ``json.Encoder``
with ``SetIndent("", "")`` (compact) and — crucially —
``SetEscapeHTML(false)`` (csvplus.go:456), so ``&``, ``<`` and ``>``
pass through **unescaped**.  The remaining differences between Go's
encoder and Python's ``json.dumps(..., ensure_ascii=False)`` are:

* Go emits ``\\u0008`` / ``\\u000c`` for backspace / form-feed where
  Python uses the ``\\b`` / ``\\f`` shorthands;
* Go always escapes U+2028 / U+2029 (JS line separators) as
  ``\\u2028`` / ``\\u2029``; Python leaves them literal.

Everything else matches: ``\\"``, ``\\\\``, ``\\n``, ``\\r``, ``\\t``,
other control bytes as lowercase ``\\u00xx``, and non-ASCII passed
through as UTF-8.  This module implements the Go byte format exactly so
both JSON sinks (streaming and vectorized) are byte-identical to the
reference's output.
"""

from __future__ import annotations

import json

# char-ordinal -> escape sequence, exactly Go's encodeState.string
_GO_ESCAPES = {
    ord('"'): '\\"',
    ord("\\"): "\\\\",
    ord("\n"): "\\n",
    ord("\r"): "\\r",
    ord("\t"): "\\t",
    0x2028: "\\u2028",
    0x2029: "\\u2029",
}
for _c in range(0x20):
    _GO_ESCAPES.setdefault(_c, f"\\u{_c:04x}")


def go_json_string(s: str) -> str:
    """*s* as a Go-encoder JSON string literal (quotes included)."""
    return '"' + s.translate(_GO_ESCAPES) + '"'


def go_json_object(row) -> str:
    """A ``map[string]string`` as Go's encoder emits it: sorted keys,
    compact separators, Go string escaping.  Non-string values (not
    producible by the reference API, but possible via Python callbacks)
    fall back to ``json.dumps``."""
    parts = []
    for k in sorted(row):
        v = row[k]
        ev = (
            go_json_string(v)
            if isinstance(v, str)
            else json.dumps(v, ensure_ascii=False, sort_keys=True, separators=(",", ":"))
        )
        parts.append(go_json_string(k) + ":" + ev)
    return "{" + ",".join(parts) + "}"
