"""Cross-cutting utilities: observability, profiling, timers."""

from .observe import StageRecord, Telemetry, telemetry, profile_to

__all__ = ["StageRecord", "Telemetry", "telemetry", "profile_to"]
