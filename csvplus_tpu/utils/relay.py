"""Bounded producer/consumer relay iterator.

The one definition of the daemon-producer + bounded-queue + sentinel +
exception-relay pattern used by both pull-iteration over push pipelines
(:meth:`csvplus_tpu.source.DataSource.__iter__`) and the streamed-ingest
prefetch overlap (:func:`csvplus_tpu.columnar.ingest._prefetch_iter`).
Shared so shutdown races / traceback handling are fixed in one place.
"""

from __future__ import annotations

import queue as _queue
import threading as _threading


class RelayStopped(Exception):
    """Raised inside ``emit`` when the consumer abandoned the iterator;
    producers let it propagate (or translate it) to unwind promptly."""


def relay_iter(run, maxsize: int = 2):
    """Run ``run(emit)`` on a daemon thread; yield emitted items in order.

    * ``run`` calls ``emit(item)`` once per item.  When the consumer
      abandons the returned iterator, the next ``emit`` raises
      :class:`RelayStopped`, so the producer can never stay blocked
      pinning item memory.
    * Any other exception escaping ``run`` re-raises in the consumer at
      the position it occurred.
    * Memory is bounded by ``maxsize`` queued items.
    """
    q: "_queue.Queue" = _queue.Queue(maxsize=maxsize)
    stop = _threading.Event()
    _END = object()

    def emit(item) -> None:
        while True:
            if stop.is_set():
                raise RelayStopped
            try:
                q.put(item, timeout=0.1)
                return
            except _queue.Full:
                continue

    def producer() -> None:
        try:
            run(emit)
            item = _END
        except RelayStopped:
            return
        except BaseException as e:  # noqa: BLE001 — relayed to consumer
            item = e
        try:
            emit(item)
        except RelayStopped:
            pass

    t = _threading.Thread(target=producer, daemon=True, name="csvplus-relay")
    t.start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        stop.set()
        # drain so a producer mid-put is never left blocked
        while t.is_alive():
            try:
                q.get_nowait()
            except _queue.Empty:
                t.join(timeout=0.05)
