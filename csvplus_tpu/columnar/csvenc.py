"""Vectorized CSV encoding of columnar results.

The streaming sink (csvplus.go:379-406 analogue) calls a Python writer
per row; for a device-resident result that is the last Python loop left
in the pipeline.  This module assembles the whole CSV body with numpy
string ops instead:

* quoting/escaping runs once per **dictionary entry** (unique value),
  not per cell — ``needs-quotes`` per Go csv.Writer's rules (delimiter,
  quote, CR, LF, or a leading space/tab), ``""`` doubling via
  ``np.char.replace``;
* per-row lines are built by a vectorized ``np.char.add`` reduction over
  the selected columns' decoded-and-escaped dictionaries taken by code.

Output is byte-identical to the streaming writer
(:func:`csvplus_tpu.csvio.write_record`); the sink falls back to
streaming whenever exact per-row error semantics are in play (absent
cells / missing columns), so behavior parity is preserved.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .table import DeviceTable


def _escape_dictionary(d_str: np.ndarray, delimiter: str = ",") -> np.ndarray:
    """Go csv.Writer fieldNeedsQuotes + escaping, applied per unique value."""
    if d_str.size == 0:
        return d_str
    has_special = (
        (np.char.find(d_str, delimiter) >= 0)
        | (np.char.find(d_str, '"') >= 0)
        | (np.char.find(d_str, "\r") >= 0)
        | (np.char.find(d_str, "\n") >= 0)
    )
    first = d_str.astype("U1")
    # Go: unicode.IsSpace on the first rune; np.char.isspace("") is False
    leading_space = np.char.isspace(first)
    backslash_dot = d_str == "\\."
    needs = (has_special | leading_space | backslash_dot) & (d_str != "")
    if not needs.any():
        return d_str
    escaped = np.char.add(
        np.char.add('"', np.char.replace(d_str[needs], '"', '""')), '"'
    )
    out = d_str.astype(object)
    out[needs] = escaped
    return out.astype(np.str_)


def encode_json_body(table: DeviceTable) -> Optional[str]:
    """The JSON array body (between the brackets), byte-identical to the
    streaming sink (sorted keys, compact separators, newline per object,
    Go string escaping per csvplus.go:456's ``SetEscapeHTML(false)``);
    None when any column has absent cells (rows then differ in schema,
    so the streaming path handles them)."""
    from ..utils.gojson import go_json_string

    names = sorted(table.columns)
    cols = []
    for c in names:
        col = table.columns[c]
        if col.has_absent:
            return None
        cols.append(col)
    if table.nrows == 0:
        return ""
    if not names:
        # Zero columns: every row serializes as the empty object.
        return "\n,".join(["{}"] * table.nrows) + "\n"

    line = None
    for i, (name, col) in enumerate(zip(names, cols)):
        if getattr(col, "kind", "str") == "int":
            # typed: '"<escaped prefix><digits>"' per row — digits and
            # '-' never need JSON escaping, the constant prefix escapes
            # once (go_json_string returns the quoted form; reuse its
            # body)
            body = go_json_string(col.prefix.decode("utf-8"))[1:-1]
            digits = np.asarray(col.values).astype(np.str_)
            vals = np.char.add(np.char.add('"' + body, digits), '"')
        else:
            d = col.dictionary_str()
            enc = np.asarray(
                [go_json_string(v) for v in d.tolist()],
                dtype=np.str_,
            )
            vals = enc[np.asarray(col.codes)]
        prefix = ("{" if i == 0 else ",") + go_json_string(name) + ":"
        piece = np.char.add(prefix, vals)
        line = piece if line is None else np.char.add(line, piece)
    line = np.char.add(line, "}")
    return "\n,".join(line.tolist()) + "\n"


def encode_csv_body(table: DeviceTable, columns: Sequence[str]) -> Optional[str]:
    """The CSV body (no header) for the selected columns, or None when
    this fast path cannot guarantee streaming-sink parity (missing
    columns or absent cells -> the caller streams instead, reproducing
    exact per-row errors and partial output).

    With the native runtime available the body assembles as one
    pre-sized byte buffer: per-row field starts come from vectorized
    length gathers + an exclusive scan across columns, then one C++
    memcpy-per-cell scatter per column (no per-row Python strings) —
    the streaming sink's per-row writer at scale was the slowest honest
    tier in BENCH r3/r4."""
    cols = []
    for c in columns:
        col = table.columns.get(c)
        if col is None or col.has_absent:
            return None
        cols.append(col)
    if table.nrows == 0:
        return ""

    body = _encode_csv_body_native(table.nrows, cols)
    if body is not None:
        return body

    pieces = []
    for i, col in enumerate(cols):
        if getattr(col, "kind", "str") == "int":
            vals = col.formatted_str()
            if _affix_needs_quotes(col.prefix.decode("utf-8")):
                vals = _escape_dictionary(vals)
        else:
            d = _escape_dictionary(col.dictionary_str())
            vals = d[np.asarray(col.codes)]
        pieces.append(vals)
        if i < len(cols) - 1:
            pieces[-1] = np.char.add(vals, ",")
    line = pieces[0]
    for p in pieces[1:]:
        line = np.char.add(line, p)
    line = np.char.add(line, "\n")
    return "".join(line.tolist())


def _affix_needs_quotes(prefix: str) -> bool:
    """Whether a typed column's values can need CSV quoting: only via
    the constant prefix (digits and '-' never do, a typed value is never
    empty or ``\\.``, and its first rune is the prefix's first rune or a
    digit/'-')."""
    return any(ch in prefix for ch in ',"\r\n') or (
        prefix[:1].isspace() if prefix else False
    )


def _encode_csv_body_native(nrows: int, cols) -> Optional[str]:
    """C++ scatter assembly of the CSV body; None when the native
    library is unavailable (the numpy path is byte-identical)."""
    try:
        from ..native.scanner import _load

        lib = _load()
    except ImportError:
        return None
    import ctypes

    per_col = []
    field_lens = []
    for col in cols:
        if getattr(col, "kind", "str") == "int":
            # typed: the formatted rows ARE the blob (identity codes);
            # quoting can only come from the constant prefix
            enc_s = col.formatted_host()
            if _affix_needs_quotes(col.prefix.decode("utf-8")):
                esc = _escape_dictionary(np.char.decode(enc_s, "utf-8"))
                enc_s = np.char.encode(esc, "utf-8")
            lens = np.char.str_len(enc_s).astype(np.int32)
            blob = enc_s.tobytes()
            offs = np.arange(lens.size, dtype=np.int64) * enc_s.dtype.itemsize
            codes = np.arange(lens.size, dtype=np.int32)
            per_col.append((blob, offs, lens, codes))
            field_lens.append(lens.astype(np.int64))
            continue
        d = _escape_dictionary(col.dictionary_str())
        enc = np.char.encode(d, "utf-8") if d.size else np.empty(0, "S1")
        lens = np.char.str_len(enc).astype(np.int32)
        # PADDED blob: the scatter copies only lens[c] bytes per slot,
        # so the fixed-width 'S' buffer works as-is — zero per-entry
        # Python objects (tobytes is one memcpy)
        blob = enc.tobytes()
        offs = np.arange(lens.size, dtype=np.int64) * enc.dtype.itemsize
        codes = np.ascontiguousarray(np.asarray(col.codes), dtype=np.int32)
        per_col.append((blob, offs, lens, codes))
        field_lens.append(lens[codes].astype(np.int64))

    # per-row byte layout: each field is followed by one separator byte
    # (',' mid-row, '\n' at the end), rows laid out consecutively
    row_len = np.zeros(nrows, dtype=np.int64)
    for flens in field_lens:
        row_len += flens + 1
    row_off = np.zeros(nrows, dtype=np.int64)
    if nrows > 1:
        np.cumsum(row_len[:-1], out=row_off[1:])

    out = np.empty(int(row_len.sum()), dtype=np.uint8)
    col_start = row_off
    for i, ((blob, offs, lens, codes), flens) in enumerate(
        zip(per_col, field_lens)
    ):
        lib.csv_scatter_fields(
            blob,
            offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            codes.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            col_start.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            nrows,
            b"\n" if i == len(per_col) - 1 else b",",
            out.ctypes.data,
        )
        if i < len(per_col) - 1:
            col_start = col_start + flens + 1
    return out.tobytes().decode("utf-8")
