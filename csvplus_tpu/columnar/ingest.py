"""CSV -> DeviceTable ingestion (placeholder until M2 lands this round)."""


def reader_to_device(reader, device="tpu", **opts):
    raise NotImplementedError(
        "OnDevice(): the columnar device executor is not built yet in this "
        "checkout; use the host path (Take(reader)) meanwhile"
    )


def index_to_device(index, device="tpu"):
    raise NotImplementedError(
        "Index.on_device(): the columnar device executor is not built yet "
        "in this checkout"
    )
