"""CSV / Index -> DeviceTable ingestion.

``FromFile(...).OnDevice("tpu")`` — the north-star entry point from
BASELINE.json — parses the CSV with the Reader's exact header and
field-count policies (reference csvplus.go:1078-1146), columnarizes the
fields without ever building per-row dicts, dictionary-encodes each
column, and uploads the code arrays to HBM.  The returned DataSource
carries a ``Scan`` plan, so downstream symbolic combinators extend the
device plan; opaque callbacks transparently fall back to streaming decoded
rows (full API parity).

When the native C++ chunk scanner is available
(:mod:`csvplus_tpu.native`), large simple-CSV files bypass the Python
record parser entirely.
"""

from __future__ import annotations

import os
import time

from ..source import DataSource
from .table import DeviceTable


# shared with the native scanner (utils.env); the old name stays an
# alias because tests and downstream callers patch ingest._env_int
from ..utils.env import env_int as _env_int
from ..utils.env import env_str as _env_str



def _encoded_nrows(value) -> int:
    """Row count of one encoded column: (dictionary, codes) pairs count
    codes; ("int", prefix, values) typed tuples count values."""
    if len(value) == 3 and value[0] == "int":
        return int(value[2].shape[0])
    return int(value[1].shape[0])

def source_from_table(table: DeviceTable) -> DataSource:
    """Plan-capable DataSource over an existing DeviceTable."""
    from .exec import plan_runner
    from ..plan import Scan

    plan = Scan(table)
    ds = DataSource(None, plan=plan)
    ds._run = plan_runner(plan, fallback=table.iterate, owner=ds)
    return ds


def reader_to_device(
    reader, device: str = "tpu", shards: "int | None" = None, mesh=None, **opts
) -> DataSource:
    """Parse *reader*'s CSV into a DeviceTable and wrap it as a source.

    Fast path tiers: native scan + vectorized dictionary encode (no
    per-cell Python objects) > native scan + Python strings > pure-Python
    parse.  All three are differential-tested to identical results.

    ``shards=N`` (or an explicit ``mesh``) lays the columns row-sharded
    over a 1-D device mesh so the whole downstream pipeline runs SPMD.
    """
    from ..utils.observe import telemetry

    # source row number of data record 0, matching the host Reader's
    # 1-based record numbering (record 1 is the header when one is read)
    row_base = 2 if reader._header_from_first_row else 1

    path = getattr(reader, "_path", None)
    if path is not None and _stream_ingest_wanted(path):
        try:
            from ..native.scanner import StreamFallback
        except ImportError:
            StreamFallback = None
        if StreamFallback is not None:
            if mesh is None and shards:
                # resolve the mesh BEFORE ingest so chunks land directly
                # on their shard (VERDICT r4 next #3) instead of staging
                # the full table on one device and resharding
                from ..parallel.mesh import make_mesh

                mesh = make_mesh(shards)
                shards = None
            try:
                with telemetry.stage("ingest:streamed", 0) as _t:
                    table = _stream_to_table(reader, path, device, mesh=mesh)
                    table.row_base = row_base
                    _t["rows_out"] = table.nrows
                return source_from_table(_maybe_shard(table, shards, mesh))
            except (ImportError, StreamFallback):
                pass
    if path is not None and _device_parse_enabled():
        try:
            from ..native import scanner as _sc

            with telemetry.stage("ingest:device-parsed", 0) as _t:
                enc = _sc.read_device_parsed_columns(reader, path)
                if enc is not None:
                    names, data = enc
                    nrows = _encoded_nrows(data[names[0]]) if names else 0
                    table = DeviceTable.from_encoded(
                        {n: data[n] for n in names}, nrows, device=device
                    )
                    table.row_base = row_base
                    _t["rows_out"] = nrows
                else:
                    _t["discard"] = True
            if enc is not None:
                return source_from_table(_maybe_shard(table, shards, mesh))
        except ImportError:
            pass
    if path is not None:
        try:
            from ..native import scanner

            with telemetry.stage("ingest:native-encoded", 0) as _t:
                enc = scanner.read_encoded_columns_native(reader, path)
                if enc is not None:
                    names, data = enc
                    nrows = _encoded_nrows(data[names[0]]) if names else 0
                    table = DeviceTable.from_encoded(
                        {n: data[n] for n in names}, nrows, device=device
                    )
                    table.row_base = row_base
                    _t["rows_out"] = nrows
                else:
                    _t["discard"] = True  # tier declined; python tier records
            if enc is not None:
                return source_from_table(_maybe_shard(table, shards, mesh))
        except ImportError:
            pass
    with telemetry.stage("ingest:python", 0) as _t:
        names, data = _read_columns_fast(reader, **opts)
        table = DeviceTable.from_pylists({n: data[n] for n in names}, device=device)
        table.row_base = row_base
        _t["rows_out"] = table.nrows
    return source_from_table(_maybe_shard(table, shards, mesh))


_STREAM_MIN_BYTES = 256 << 20


def _stream_ingest_wanted(path: str) -> bool:
    """Chunk-streamed ingest engages for files big enough that the
    whole-file tiers' ``f.read()`` would hurt (default 256MB; tune with
    CSVPLUS_STREAM_MIN_BYTES, 0 disables)."""
    import os

    thresh = _env_int("CSVPLUS_STREAM_MIN_BYTES", _STREAM_MIN_BYTES)
    if thresh <= 0:
        return False
    try:
        return os.path.getsize(path) >= thresh
    except OSError:
        return False


def _stream_to_table(reader, path: str, device, mesh=None) -> DeviceTable:
    """Consume the native streaming chunk generator into one DeviceTable.

    Per chunk, each column's int32 codes are uploaded immediately (the
    next chunk's host scan overlaps the async transfer) and only the
    chunk's sorted dictionary stays on host.  After the last chunk,
    HOST-dictionary columns merge to a sorted union with codes remapped
    ON DEVICE (code order == string order, the table.py encoding
    invariant); device-LANE columns instead defer that union — see the
    lane paragraph below — so their codes are chunk-offset slots into
    an unsorted concatenated dictionary until an op needs code order.

    Memory contract: host RSS is bounded by a CONSTANT number of chunks
    of raw bytes/offsets — (CSVPLUS_STREAM_PREFETCH + 2) with the
    default overlap pipeline, one with CSVPLUS_STREAM_PREFETCH=0 — plus
    per-column dictionary state.  LOW-cardinality
    columns keep host dictionaries (total distinct values, flat at any
    file size).  A column whose running distinct count crosses
    ``CSVPLUS_DICT_DEVICE_MIN_DISTINCT`` (default 4M; values <= 32
    bytes) switches to DEVICE-LANE dictionaries (ops/lanes.py): each
    chunk's dictionary is packed into int32 byte lanes, uploaded, and
    freed on host; the column ships as the raw lane CONCATENATION with
    offset-shifted codes, and the global union sort is DEFERRED
    (StringColumn._ensure_sorted_lanes) until an operation actually
    needs code order — a payload column that is only decoded, gathered
    or checksummed never pays it.  A unique ``order_id`` at 100M rows
    therefore neither accumulates on host (VERDICT round-2 weak #5) nor
    costs a 100M-entry device sort at ingest (round-4 northstar
    profile) — strictly better than the reference, which materializes
    every row (csvplus.go:722-733).

    TYPED VALUE LANES (VERDICT r4 next #2): chunks the generator parses
    as ``("int", prefix, values)`` accumulate as narrowed int uploads
    and finalize as one :class:`~csvplus_tpu.columnar.typed.IntColumn` —
    no dictionary at any point.  A column whose later chunk stops
    conforming demotes: the accumulated value chunks re-encode through
    the exact dictionary path below (format + per-chunk unique), so the
    result is bitwise identical to a never-typed run.

    SHARDED INGEST (VERDICT r4 next #3, SURVEY §2 "host ingest
    parallelism"): with *mesh* set, each chunk's arrays upload straight
    to the mesh device that will own those rows (byte-position
    round-assignment, monotone so per-device row ranges stay
    contiguous); finalize stitches the per-device segments into ONE
    row-sharded global array via boundary-sliver moves — no full-table
    single-device buffer ever exists, and per-device memory is bounded
    by ~n/k plus a chunk.  Columns that would switch to device-LANE
    dictionaries raise :class:`StreamFallback` under a mesh (the
    whole-file tiers + ``with_sharding`` handle that shape); typed and
    host-dictionary columns — every north-star column — shard natively.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..native.scanner import stream_encoded_chunks
    from ..ops.lanes import lanes_for_width, pack_host
    from .table import StringColumn, default_device

    dev = default_device(device)
    shard_devs = None
    _fsize = _cb = 1
    if mesh is not None:
        from ..native.scanner import _stream_chunk_bytes

        shard_devs = list(mesh.devices.flat)
        _fsize = max(os.path.getsize(path), 1)
        _cb = _stream_chunk_bytes()
    # under a mesh, codes must be born on their shard: host encode only
    encoder = (
        _device_chunk_encoder(dev)
        if (_device_parse_enabled() and shard_devs is None)
        else None
    )
    prefetch_depth = _env_int("CSVPLUS_STREAM_PREFETCH", 1)
    lane_thresh = _env_int("CSVPLUS_DICT_DEVICE_MIN_DISTINCT", 4_000_000)
    names = None
    chunk_dicts: "dict[str, list]" = {}  # host mode: 'S' arrays
    chunk_lanes: "dict[str, list]" = {}  # lane mode: device lane tuples
    chunk_codes: "dict[str, list]" = {}
    # true running distinct count, tracked as an incremental host union
    # while BELOW the threshold (so it is bounded by the threshold) and
    # dropped the moment the column switches to device lanes
    running_union: "dict[str, np.ndarray | None]" = {}
    max_width: "dict[str, int]" = {}
    host_only: "dict[str, bool]" = {}  # width > lane cap: never switch
    nrows = 0

    def _to_lanes(d: "np.ndarray") -> tuple:
        lanes = lanes_for_width(max_width[c])
        return tuple(jax.device_put(l, dev) for l in pack_host(d, lanes))

    int_vals: "dict[str, list]" = {}  # typed mode: device value chunks
    # sharded ingest: per-shard SEALED int32 segments (one per completed
    # shard, in shard order).  The moment the monotone chunk->shard
    # assignment advances past a shard, that shard's pending typed
    # chunks concatenate to their final int32 form ON their shard —
    # async dispatch, so the finalize work overlaps the producer's
    # continued scan instead of concentrating at the barrier
    int_segs: "dict[str, list]" = {}
    int_prefix: "dict[str, bytes]" = {}
    # columns that left typed mode at any point: they must NEVER re-enter
    # it, or finalize's IntColumn branch would silently drop the
    # dictionary chunks accumulated in between
    int_demoted: "set[str]" = set()

    def add_dict_chunk(c, d, codes, tgt=None):
        """One chunk's (dictionary, codes) through the dictionary-path
        bookkeeping (host union / device-lane switching / narrowed code
        upload) — shared by the normal path and typed-chunk demotion.
        *tgt* is the device this chunk's codes live on (the chunk's
        shard under a mesh, the single ingest device otherwise)."""
        max_width[c] = max(max_width[c], d.dtype.itemsize)
        if max_width[c] > 32:  # past the lane cap (ops/lanes.py)
            host_only[c] = True
            if chunk_lanes[c]:
                # already committed to lanes and a later chunk brings
                # a wider value: this tier cannot finish the column —
                # the whole-file tiers handle the file instead
                from ..native.scanner import StreamFallback

                raise StreamFallback(
                    f'column "{c}" exceeded the lane width cap mid-stream'
                )
        if not host_only[c] and not chunk_lanes[c]:
            ru = running_union[c]
            if ru is None:
                running_union[c] = d
            else:
                dt = np.dtype(f"S{max_width[c]}")
                running_union[c] = np.union1d(ru.astype(dt), d.astype(dt))
        if isinstance(codes, np.ndarray):
            # narrow the upload to the smallest dtype the chunk's
            # dictionary needs (codes are nonnegative slot numbers):
            # a low-cardinality column ships 1-2 bytes/row instead
            # of 4, and the remap gather restores int32 on device
            if d.size <= 0xFF:
                codes = codes.astype(np.uint8)
            elif d.size <= 0xFFFF:
                codes = codes.astype(np.uint16)
        chunk_codes[c].append(jax.device_put(codes, tgt if tgt is not None else dev))
        if chunk_lanes[c] or (
            not host_only[c]
            and running_union[c] is not None
            and running_union[c].size >= lane_thresh
        ):
            if shard_devs is not None:
                # the deferred-lane representation cannot be built
                # shard-resident chunk by chunk; the whole-file tiers +
                # with_sharding handle this (rare now that typed lanes
                # absorb high-cardinality numeric ids)
                from ..native.scanner import StreamFallback

                raise StreamFallback(
                    f'column "{c}" crossed the lane threshold under sharded ingest'
                )
            # lane mode (newly or already): host dictionaries
            # convert to device lanes and are freed — the RSS bound
            running_union[c] = None
            if chunk_dicts[c]:
                chunk_lanes[c] = [_to_lanes(p) for p in chunk_dicts[c]]
                chunk_dicts[c] = []
            chunk_lanes[c].append(_to_lanes(d))
        else:
            chunk_dicts[c].append(d)

    def demote_typed(c):
        """Re-encode a no-longer-typed column's accumulated value chunks
        through the dictionary path — bitwise identical to a never-typed
        run (format_affix is the exact inverse of the native parse).
        Each re-encoded chunk (including any already-sealed per-shard
        segment) stays on the device its values live on."""
        from .typed import format_affix

        int_demoted.add(c)
        for dev_arr in int_segs.get(c, []) + int_vals[c]:
            v = np.asarray(dev_arr).astype(np.int32)
            strs = format_affix(int_prefix[c], v)
            dd, cc = np.unique(strs, return_inverse=True)
            add_dict_chunk(
                c,
                dd,
                cc.astype(np.int32),
                tgt=dev_arr.device if shard_devs is not None else None,
            )
        int_vals[c] = []
        int_segs[c] = []

    def seal_typed_shard():
        """Finalize the just-completed shard's pending typed chunks into
        one int32 segment resident on that shard.  Eager concat = async
        dispatch: the device-side work overlaps the next chunks' scan."""
        for c in names or ():
            pend = int_vals.get(c)
            if pend:
                int_segs[c].append(_values_concat(tuple(pend)))
                int_vals[c] = []

    chunks = stream_encoded_chunks(reader, path, encoder=encoder)
    if prefetch_depth > 0:
        # overlap chunk N+1's read+scan+encode (producer thread) with
        # chunk N's upload + dictionary-union bookkeeping (this thread);
        # host RSS bound becomes (depth + 2) chunks instead of 1
        chunks = _prefetch_iter(chunks, prefetch_depth)
    ci = -1
    tgt = dev
    cur_si = 0  # shard index the in-flight chunks belong to
    n_seals = 0
    # accumulated stage accounting (one add_stage record each at the
    # end): scan-wait = time this thread blocked on the producer's
    # read+scan+encode (the NON-overlapped part under prefetch), place =
    # consumer-side upload + dictionary bookkeeping, seal = per-shard
    # typed finalize dispatch
    t_wait = t_place = t_seal = 0.0
    _pc = time.perf_counter
    _it = iter(chunks)
    _END = object()
    while True:
        _t0 = _pc()
        item = next(_it, _END)
        t_wait += _pc() - _t0
        if item is _END:
            break
        cnames, encoded, n = item
        ci += 1
        if shard_devs is not None:
            # byte-position assignment: chunk i covers roughly bytes
            # [i*cb, (i+1)*cb), so its rows belong to the device owning
            # that fraction of the file.  Monotone in i, so each shard's
            # rows form one contiguous global range.
            k = len(shard_devs)
            si = min(k - 1, ci * _cb * k // _fsize)
            if si != cur_si:
                # the assignment is monotone: shard cur_si is complete
                _t0 = _pc()
                seal_typed_shard()
                t_seal += _pc() - _t0
                n_seals += 1
                cur_si = si
            tgt = shard_devs[si]
        _t0 = _pc()
        if names is None:
            names = cnames
            chunk_dicts = {c: [] for c in names}
            chunk_lanes = {c: [] for c in names}
            chunk_codes = {c: [] for c in names}
            running_union = {c: None for c in names}
            max_width = {c: 1 for c in names}
            host_only = {c: False for c in names}
            int_vals = {c: [] for c in names}
            int_segs = {c: [] for c in names}
        nrows += n
        for c in names:
            enc = encoded[c]
            if len(enc) == 3 and enc[0] == "int":
                _, prefix, vals = enc
                if c in int_demoted or (
                    c in int_prefix and int_prefix[c] != prefix
                ):
                    # prefix drift (or a column that already left typed
                    # mode): the established IntColumn prefix cannot hold
                    # this chunk.  Demote what accumulated and re-encode
                    # THIS chunk through the dictionary path too —
                    # overwriting int_prefix here would reinterpret every
                    # earlier chunk's values under the wrong affix.
                    from .typed import format_affix

                    if int_vals.get(c) or int_segs.get(c):
                        demote_typed(c)
                    int_demoted.add(c)
                    strs = format_affix(prefix, vals.astype(np.int32))
                    dd, cc = np.unique(strs, return_inverse=True)
                    add_dict_chunk(c, dd, cc.astype(np.int32), tgt=tgt)
                    continue
                int_prefix[c] = prefix
                # narrow the upload to the smallest dtype holding the
                # chunk's value range; device concat restores int32
                lo, hi = (int(vals.min()), int(vals.max())) if vals.size else (0, 0)
                if -128 <= lo and hi <= 127:
                    vals = vals.astype(np.int8)
                elif -32768 <= lo and hi <= 32767:
                    vals = vals.astype(np.int16)
                int_vals[c].append(jax.device_put(vals, tgt))
                continue
            if int_vals.get(c) or int_segs.get(c):
                demote_typed(c)  # column left typed mode this chunk
            add_dict_chunk(c, *enc, tgt=tgt)
        t_place += _pc() - _t0
    if names is None:  # empty file: defer to the whole-file tiers
        from ..native.scanner import StreamFallback

        raise StreamFallback("empty file")

    from ..native.scanner import _ingest_workers
    from ..utils.observe import telemetry

    # scan-wait is the producer time NOT hidden by the staged pipeline
    # (readahead + K chunk workers + ordered reassembly live inside the
    # generator; its own ingest:cut/encode/reorder-stall records carry
    # the per-worker attribution)
    telemetry.add_stage(
        "ingest:scan", nrows, nrows, t_wait,
        workers=(1 if encoder is not None else _ingest_workers()),
        prefetch=prefetch_depth,
    )
    telemetry.add_stage("ingest:place", nrows, nrows, t_place)

    if shard_devs is not None:
        # seal the last shard, then stitch: with every shard already one
        # int32 segment on its device, the barrier's remaining typed
        # work is boundary slivers + padding only
        _t0 = _pc()
        seal_typed_shard()
        t_seal += _pc() - _t0
        telemetry.add_stage(
            "ingest:seal", nrows, nrows, t_seal, n_seals=n_seals + 1
        )
        return _finalize_sharded(
            mesh,
            shard_devs,
            names,
            nrows,
            int_segs,
            int_prefix,
            chunk_dicts,
            chunk_codes,
        )

    out = {}
    for c in names:
        if int_vals.get(c):
            from .typed import IntColumn

            # the int_demoted bookkeeping above guarantees a column with
            # typed chunks never also holds dictionary/lane chunks —
            # this branch would silently drop them
            assert not chunk_dicts[c] and not chunk_lanes[c] and not chunk_codes[c]
            out[c] = IntColumn(int_prefix[c], _values_concat(tuple(int_vals[c])))
            continue
        dicts, codes = chunk_dicts[c], chunk_codes[c]
        if chunk_lanes[c]:
            lanes_list = chunk_lanes[c]
            if len(lanes_list) == 1:
                only = codes[0]
                if only.dtype != jnp.int32:
                    only = only.astype(jnp.int32)
                out[c] = StringColumn(None, only, dev_dictionary=lanes_list[0])
                continue
            # DEFER the global dictionary union (round-4 northstar
            # profile: this lax.sort dominated ingest for a 100M-unique
            # payload column that never needed it).  The column ships as
            # the raw chunk-dictionary CONCATENATION with codes shifted
            # by per-chunk offsets; ops that need code order == value
            # order trigger StringColumn._ensure_sorted_lanes() lazily.
            n_lanes = max(len(ls) for ls in lanes_list)
            concat = _concat_lanes_device(lanes_list, n_lanes)
            sizes = [int(ls[0].shape[0]) for ls in lanes_list]
            offsets = [0]
            for s in sizes[:-1]:
                offsets.append(offsets[-1] + s)
            out[c] = StringColumn(
                None,
                _offset_concat(codes, tuple(offsets)),
                dev_dictionary=concat,
                dev_dict_sorted=False,
            )
            continue
        if len(dicts) == 1:
            only = codes[0]
            if only.dtype != jnp.int32:  # narrowed upload: restore i32
                only = only.astype(jnp.int32)
            out[c] = (dicts[0], only)
            continue
        width = max(d.dtype.itemsize for d in dicts)
        dt = np.dtype(f"S{width}")
        union = np.unique(np.concatenate([d.astype(dt) for d in dicts]))
        mappings = [
            jax.device_put(np.searchsorted(union, d.astype(dt)).astype(np.int32), dev)
            for d in dicts
        ]
        # all chunks remap + concatenate in ONE jit call: over a
        # tunneled backend each eager op costs a compile per chunk
        # shape, which dominated the wall time at north-star scale
        out[c] = (union, _remap_concat(mappings, codes))
    return DeviceTable.from_encoded(out, nrows, device=dev)


def _prefetch_iter(gen, depth: int):
    """Run *gen* on a background thread, buffering up to *depth* items —
    the streamed tier's read+scan+encode then overlaps the consumer's
    device uploads (VERDICT r3 #3).  Exceptions (StreamFallback,
    DataSourceError, ...) re-raise in the consumer at the position they
    occurred; abandoning the iterator stops the producer promptly so a
    fallback path cannot leak a thread pinning chunk memory."""
    from ..utils.relay import relay_iter

    def run(emit) -> None:
        for item in gen:
            emit(item)

    return relay_iter(run, maxsize=depth)


def _device_chunk_encoder(device):
    """Per-chunk column encoder that runs the heavy dictionary encode ON
    DEVICE (ops/parse sort-rank kernel): the chunk's byte tensor uploads
    once (size-bucketed) and each column's codes are born on device —
    the streamed tier's marriage with the device-parse tier.  Declines
    (returns None per column) on fields wider than the kernel's 32-byte
    cap; the caller then uses the host vectorized encode."""
    import jax

    state: dict = {}

    def encode(combined, data, col_starts, col_lens):
        import numpy as np

        from ..ops.parse import _bucket_len, encode_column_device

        if len(data) >= 2**31:
            return None  # int32 offsets would wrap (ops/parse.py guard)
        if state.get("data") is not data:
            padded = _bucket_len(len(data))
            host_arr = np.frombuffer(data, dtype=np.uint8)
            if padded != len(data):
                host_arr = np.concatenate(
                    [host_arr, np.zeros(padded - len(data), dtype=np.uint8)]
                )
            # holding the bytes object keeps the identity check sound
            # (costs one chunk of extra host memory, freed next chunk)
            state["data"] = data
            state["dev"] = jax.device_put(host_arr, device)
        return encode_column_device(state["dev"], data, col_starts, col_lens)

    return encode


def _concat_lanes_device(lanes_list, n_lanes: int):
    """Concatenate per-chunk lane tuples (widening narrower chunks with
    the shared packed-NUL fill) into one device lane tuple, order
    preserved."""
    import jax.numpy as jnp

    from ..ops.lanes import widen_lanes_device

    widened = [widen_lanes_device(ls, n_lanes) for ls in lanes_list]
    return tuple(
        jnp.concatenate([w[i] for w in widened]) for i in range(n_lanes)
    )


_offset_kernel = None


def _offset_concat(codes, offsets):
    """Concatenate per-chunk code arrays shifted into the concatenated
    dictionary's slot space — one jitted call for the whole column."""
    global _offset_kernel
    if _offset_kernel is None:
        import functools

        import jax
        import jax.numpy as jnp

        @functools.partial(jax.jit, static_argnames=("offs",))
        def kernel(cks, offs):  # analysis: allow[JIT001]
            # the static offs tuple already keys one executable per
            # chunk layout; the add+concat fusion is the point
            return jnp.concatenate(
                [c.astype(jnp.int32) + o for c, o in zip(cks, offs)]
            )

        _offset_kernel = kernel
    return _offset_kernel(codes, offsets)


def _assemble_rows_sharded(mesh, shard_devs, arrs, nrows, pad_value):
    """Stitch per-chunk int32 device arrays (chunk order == global row
    order, each committed to its shard) into ONE row-sharded global
    array over *mesh*.

    Chunks were assigned to devices monotonically, so each device holds
    one contiguous global row range; the NamedSharding block structure
    wants row range [d*b, (d+1)*b) on flat device d (b = ceil(n/k)), so
    only boundary SLIVERS move between neighboring devices — per-device
    memory stays ~n/k and no full-table single-device buffer ever
    exists.  The tail pads with *pad_value* (outside every selection)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding

    from ..parallel.mesh import row_spec

    k = len(shard_devs)
    b = -(-nrows // k)  # ceil: NamedSharding block size
    # consecutive same-device chunk runs -> (global_start, seg_array)
    segs = []  # (gstart, arr) with arr committed to one device
    run, run_dev, run_start, gpos = [], None, 0, 0
    for arr in arrs:
        d = arr.device
        if run and d != run_dev:
            segs.append((run_start, run[0] if len(run) == 1 else jnp.concatenate(run)))
            run, run_start = [], gpos
        run_dev = d
        run.append(arr)
        gpos += int(arr.shape[0])
    if run:
        segs.append((run_start, run[0] if len(run) == 1 else jnp.concatenate(run)))

    bufs = []
    for d in range(k):
        # a tiny table can leave trailing devices fully past nrows:
        # their block is then all padding (t1 clamps up to t0)
        t0 = d * b
        t1 = max(t0, min((d + 1) * b, nrows))
        pieces = []
        for gs, arr in segs:
            ge = gs + int(arr.shape[0])
            lo, hi = max(gs, t0), min(ge, t1)
            if lo >= hi:
                continue
            sl = arr[lo - gs : hi - gs]
            if sl.device != shard_devs[d]:
                sl = jax.device_put(sl, shard_devs[d])
            pieces.append(sl)
        pad = b - (t1 - t0)
        if pad > 0:
            pieces.append(
                jax.device_put(
                    np.full(pad, pad_value, dtype=np.int32), shard_devs[d]
                )
            )
        if not pieces:  # nrows == 0 (header-only file): empty blocks
            pieces.append(
                jax.device_put(np.empty(0, dtype=np.int32), shard_devs[d])
            )
        buf = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
        bufs.append(buf)
    return jax.make_array_from_single_device_arrays(
        (b * k,), NamedSharding(mesh, row_spec(mesh)), bufs
    )


def _finalize_sharded(
    mesh,
    shard_devs,
    names,
    nrows,
    int_vals,
    int_prefix,
    chunk_dicts,
    chunk_codes,
):
    """Sharded-ingest finalize: every column becomes a globally
    row-sharded array assembled from its shard-resident chunks (typed
    value lanes or dictionary codes; lane-dictionary columns were
    excluded by StreamFallback upstream).

    Typed columns arrive PRE-SEALED — one int32 segment per shard,
    concatenated incrementally as the stream passed each shard boundary
    (``seal_typed_shard``) — so the barrier's typed work is boundary
    slivers + tail padding, not the full per-chunk concat+convert.
    Dictionary columns still finalize here: their global union needs
    every chunk's dictionary."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..utils.observe import telemetry
    from .table import DeviceTable, StringColumn
    from .typed import IntColumn

    out = {}
    with telemetry.stage("ingest:shard-assemble", nrows) as _t:
        _t["n_shards"] = len(shard_devs)
        _t["max_shard_rows"] = -(-nrows // len(shard_devs))
        for c in names:
            if int_vals.get(c):
                from .typed import PAD_VALUE

                assert not chunk_dicts[c] and not chunk_codes[c]
                arrs = [
                    a if a.dtype == jnp.int32 else a.astype(jnp.int32)
                    for a in int_vals[c]
                ]
                out[c] = IntColumn(
                    int_prefix[c],
                    _assemble_rows_sharded(
                        mesh, shard_devs, arrs, nrows, int(PAD_VALUE)
                    ),
                )
                continue
            dicts, codes = chunk_dicts[c], chunk_codes[c]
            if len(dicts) == 1:
                arrs = [
                    a if a.dtype == jnp.int32 else a.astype(jnp.int32)
                    for a in codes
                ]
                out[c] = StringColumn(
                    dicts[0],
                    _assemble_rows_sharded(mesh, shard_devs, arrs, nrows, -2),
                )
                continue
            width = max(d.dtype.itemsize for d in dicts)
            dt = np.dtype(f"S{width}")
            union = np.unique(np.concatenate([d.astype(dt) for d in dicts]))
            # remap each chunk ON ITS SHARD (the mapping table is tiny)
            arrs = [
                jnp.take(
                    jax.device_put(
                        np.searchsorted(union, d.astype(dt)).astype(np.int32),
                        ck.device,
                    ),
                    ck.astype(jnp.int32),
                    axis=0,
                )
                for d, ck in zip(dicts, codes)
            ]
            out[c] = StringColumn(
                union, _assemble_rows_sharded(mesh, shard_devs, arrs, nrows, -2)
            )
    table = DeviceTable(out, nrows, shard_devs[0])
    table._pre_sharded = True
    _trim_host_staging()
    return table


def _trim_host_staging() -> None:
    """Return freed streaming-ingest staging memory to the OS.

    The chunked scan + per-shard seals allocate and free hundreds of
    staging buffers; glibc keeps the freed pages resident in its arenas,
    so a long-lived process carries ~1GB of dead ingest staging as RSS
    into the join phase (measured at 100M rows).  ``malloc_trim``
    releases the retained pages; no-op on non-glibc platforms."""
    try:
        import ctypes

        ctypes.CDLL("libc.so.6").malloc_trim(0)
    except (OSError, AttributeError):
        # no glibc (CDLL raises OSError) or a libc without malloc_trim
        # (AttributeError): best-effort memory hygiene, nothing to report
        return


def _values_concat(chunks):
    """Concatenate per-chunk (narrow-uploaded) value arrays into one
    int32 device array.

    Deliberately EAGER: chunk count grows with file size, so a jitted
    tuple-of-arrays kernel would retrace (trace + XLA compile, tens of
    ms) for every distinct chunk count — far more than the fusion ever
    saved on a once-per-column concatenation."""
    import jax.numpy as jnp

    return jnp.concatenate([c.astype(jnp.int32) for c in chunks])


_remap_kernel = None


def _remap_concat(mappings, codes):
    global _remap_kernel
    if _remap_kernel is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def kernel(maps, cks):  # analysis: allow[JIT001]
            # retrace-per-chunk-count accepted HERE (unlike
            # _values_concat): the per-chunk takes must fuse into the
            # concatenation or each chunk materializes twice
            return jnp.concatenate(
                [jnp.take(m, c, axis=0) for m, c in zip(maps, cks)]
            )

        _remap_kernel = kernel
    return _remap_kernel(mappings, codes)


_link_rtt_cache: "list[float]" = []


def link_rtt_ms() -> float:
    """Measured dispatch+sync round-trip latency to the default device,
    in milliseconds (median of 3 tiny probes, cached per process).

    A locally-attached accelerator answers in well under a millisecond;
    a network-tunneled one takes tens to hundreds.  Tier choices that
    trade extra device round trips for device compute key off this."""
    if _link_rtt_cache:
        return _link_rtt_cache[0]
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    try:
        x = jax.device_put(np.zeros(8, dtype=np.int32))
        int(jnp.sum(x))  # warm the kernel so the probe measures RTT, not compile
        samples = []
        for _ in range(3):
            t0 = time.perf_counter()
            int(jnp.sum(x))
            samples.append((time.perf_counter() - t0) * 1000.0)
        rtt = sorted(samples)[1]
    except Exception:
        rtt = 0.0  # unprobeable backend: assume local
    _link_rtt_cache.append(rtt)
    return rtt


_DEVICE_PARSE_MAX_RTT_MS = 20.0


def _device_parse_enabled() -> bool:
    """The fully-on-device parse tier: default-on when the default backend
    is a *locally attached* accelerator (where the bytes would travel
    there anyway), opt-in via CSVPLUS_DEVICE_PARSE=1 elsewhere, opt-out
    with =0.

    Over a high-latency link (e.g. a network-tunneled chip) the device
    encode loses by measurement: it moves the raw byte tensor plus
    per-column offsets up and a full-length unique-rows vector down,
    ~6x the traffic of uploading host-encoded codes, and pays several
    dispatch round trips per column.  So when the measured link RTT
    exceeds ``CSVPLUS_DEVICE_PARSE_MAX_RTT_MS`` (default 20ms) the
    host-encode tiers take over unless the env flag forces otherwise."""
    flag = _env_str("CSVPLUS_DEVICE_PARSE")
    if flag is not None:
        return flag == "1"
    import jax

    if jax.default_backend() in ("cpu",):
        return False
    v = _env_str("CSVPLUS_DEVICE_PARSE_MAX_RTT_MS")
    try:
        thresh = float(v) if v else _DEVICE_PARSE_MAX_RTT_MS
    except ValueError:
        thresh = _DEVICE_PARSE_MAX_RTT_MS
    return link_rtt_ms() <= thresh


def _maybe_shard(table: DeviceTable, shards, mesh) -> DeviceTable:
    if getattr(table, "_pre_sharded", False):
        return table  # chunks already landed on their shards at ingest
    if mesh is None and shards:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(shards)
    return table.with_sharding(mesh) if mesh is not None else table


def _read_columns_fast(reader, **opts):
    """Columnar read — native C++ scanner when possible, Python fallback."""
    path = getattr(reader, "_path", None)
    if path is not None:
        try:
            from ..native import scanner

            cols = scanner.read_columns_native(reader, path)
            if cols is not None:
                return cols
        except ImportError:
            pass
    return reader.read_columns()


def index_to_device(index, device: str = "tpu"):
    """Columnarize an Index (sorted rows + key columns) for device joins.

    Returns a :class:`csvplus_tpu.ops.join.DeviceIndex` carrying the
    columnar table plus packed sorted keys.
    """
    from ..ops.join import DeviceIndex

    table = DeviceTable.from_rows(index._impl.rows, device=device)
    return DeviceIndex.build(table, index._impl.columns)
