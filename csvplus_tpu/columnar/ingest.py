"""CSV / Index -> DeviceTable ingestion.

``FromFile(...).OnDevice("tpu")`` — the north-star entry point from
BASELINE.json — parses the CSV with the Reader's exact header and
field-count policies (reference csvplus.go:1078-1146), columnarizes the
fields without ever building per-row dicts, dictionary-encodes each
column, and uploads the code arrays to HBM.  The returned DataSource
carries a ``Scan`` plan, so downstream symbolic combinators extend the
device plan; opaque callbacks transparently fall back to streaming decoded
rows (full API parity).

When the native C++ chunk scanner is available
(:mod:`csvplus_tpu.native`), large simple-CSV files bypass the Python
record parser entirely.
"""

from __future__ import annotations

from ..source import DataSource
from .table import DeviceTable


def source_from_table(table: DeviceTable) -> DataSource:
    """Plan-capable DataSource over an existing DeviceTable."""
    from .exec import plan_runner
    from ..plan import Scan

    plan = Scan(table)
    ds = DataSource(None, plan=plan)
    ds._run = plan_runner(plan, fallback=table.iterate, owner=ds)
    return ds


def reader_to_device(
    reader, device: str = "tpu", shards: "int | None" = None, mesh=None, **opts
) -> DataSource:
    """Parse *reader*'s CSV into a DeviceTable and wrap it as a source.

    Fast path tiers: native scan + vectorized dictionary encode (no
    per-cell Python objects) > native scan + Python strings > pure-Python
    parse.  All three are differential-tested to identical results.

    ``shards=N`` (or an explicit ``mesh``) lays the columns row-sharded
    over a 1-D device mesh so the whole downstream pipeline runs SPMD.
    """
    from ..utils.observe import telemetry

    # source row number of data record 0, matching the host Reader's
    # 1-based record numbering (record 1 is the header when one is read)
    row_base = 2 if reader._header_from_first_row else 1

    path = getattr(reader, "_path", None)
    if path is not None and _device_parse_enabled():
        try:
            from ..native import scanner as _sc

            with telemetry.stage("ingest:device-parsed", 0) as _t:
                enc = _sc.read_device_parsed_columns(reader, path)
                if enc is not None:
                    names, data = enc
                    nrows = data[names[0]][1].shape[0] if names else 0
                    table = DeviceTable.from_encoded(
                        {n: data[n] for n in names}, nrows, device=device
                    )
                    table.row_base = row_base
                    _t["rows_out"] = nrows
                else:
                    _t["discard"] = True
            if enc is not None:
                return source_from_table(_maybe_shard(table, shards, mesh))
        except ImportError:
            pass
    if path is not None:
        try:
            from ..native import scanner

            with telemetry.stage("ingest:native-encoded", 0) as _t:
                enc = scanner.read_encoded_columns_native(reader, path)
                if enc is not None:
                    names, data = enc
                    nrows = data[names[0]][1].shape[0] if names else 0
                    table = DeviceTable.from_encoded(
                        {n: data[n] for n in names}, nrows, device=device
                    )
                    table.row_base = row_base
                    _t["rows_out"] = nrows
                else:
                    _t["discard"] = True  # tier declined; python tier records
            if enc is not None:
                return source_from_table(_maybe_shard(table, shards, mesh))
        except ImportError:
            pass
    with telemetry.stage("ingest:python", 0) as _t:
        names, data = _read_columns_fast(reader, **opts)
        table = DeviceTable.from_pylists({n: data[n] for n in names}, device=device)
        table.row_base = row_base
        _t["rows_out"] = table.nrows
    return source_from_table(_maybe_shard(table, shards, mesh))


def _device_parse_enabled() -> bool:
    """The fully-on-device parse tier: default-on when the default backend
    is an accelerator (where the bytes would travel there anyway), opt-in
    via CSVPLUS_DEVICE_PARSE=1 elsewhere, opt-out with =0."""
    import os

    flag = os.environ.get("CSVPLUS_DEVICE_PARSE")
    if flag is not None:
        return flag == "1"
    import jax

    return jax.default_backend() not in ("cpu",)


def _maybe_shard(table: DeviceTable, shards, mesh) -> DeviceTable:
    if mesh is None and shards:
        from ..parallel.mesh import make_mesh

        mesh = make_mesh(shards)
    return table.with_sharding(mesh) if mesh is not None else table


def _read_columns_fast(reader, **opts):
    """Columnar read — native C++ scanner when possible, Python fallback."""
    path = getattr(reader, "_path", None)
    if path is not None:
        try:
            from ..native import scanner

            cols = scanner.read_columns_native(reader, path)
            if cols is not None:
                return cols
        except ImportError:
            pass
    return reader.read_columns()


def index_to_device(index, device: str = "tpu"):
    """Columnarize an Index (sorted rows + key columns) for device joins.

    Returns a :class:`csvplus_tpu.ops.join.DeviceIndex` carrying the
    columnar table plus packed sorted keys.
    """
    from ..ops.join import DeviceIndex

    table = DeviceTable.from_rows(index._impl.rows, device=device)
    return DeviceIndex.build(table, index._impl.columns)
