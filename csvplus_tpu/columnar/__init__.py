"""Columnar device layer: HBM-resident Arrow-style tables + plan executor."""
