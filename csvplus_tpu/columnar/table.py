"""HBM-resident columnar tables.

The reference's execution model is row dicts of strings (csvplus.go:59).
A TPU cannot chase per-row hash maps, so the device representation is
columnar and **dictionary-encoded**: each string column becomes

* ``dictionary`` — the column's unique values, sorted byte-
  lexicographically (host numpy array; UTF-8 byte order == code-point
  order, so this matches Go's ``strings.Compare`` sort semantics,
  csvplus.go:798);
* ``codes`` — ``int32[n]`` device array mapping row -> dictionary slot.
  Because the dictionary is sorted, code order == string order, so
  sorts, range searches and equality tests all run on the MXU/VPU as
  integer ops.  Code ``-1`` marks an absent cell (rows in an Index may
  have heterogeneous schemas after Transform stages).

Predicates, joins and sorts run entirely over the code arrays on device;
strings are only materialized back on the host at the sink boundary.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.recompile import register_kernel
from ..row import Row
from ..utils.env import env_int

ABSENT = np.int32(-1)


def default_device(device: Optional[str] = None):
    """Resolve a device spec ("tpu", "cpu", None=default) to a jax.Device."""
    if device is None or isinstance(device, str) and device == "default":
        return jax.devices()[0]
    if isinstance(device, str):
        try:
            return jax.devices(device)[0]
        except RuntimeError:
            # requested backend not present (e.g. "tpu" in a CPU test run):
            # fall back to the default device so pipelines still work
            return jax.devices()[0]
    return device  # already a jax.Device


def _to_bytes_array(values) -> np.ndarray:
    """UTF-8 encode a sequence/array of str into an 'S' bytes array."""
    arr = np.asarray(values, dtype=np.str_)
    return np.char.encode(arr, "utf-8")


def encode_strings(values: Sequence[str]) -> "tuple[np.ndarray, np.ndarray]":
    """Dictionary-encode a string column: (sorted unique values, int32 codes).

    Dictionaries are stored as UTF-8 **bytes** ('S' dtype): numpy bytes
    comparison is memcmp, i.e. exactly Go's ``strings.Compare`` byte-
    lexicographic order (csvplus.go:798), and it sidesteps per-entry
    Python string objects on the ingest path.  ``None`` entries (absent
    cells) encode as code -1 and do not enter the dictionary.
    """
    if isinstance(values, np.ndarray) and values.dtype.kind in ("U", "S"):
        # numpy string arrays cannot hold None: skip the per-element scan
        arr_b = values if values.dtype.kind == "S" else np.char.encode(values, "utf-8")
        dictionary, codes = np.unique(arr_b, return_inverse=True)
        return dictionary, codes.astype(np.int32)
    arr = np.asarray(values, dtype=object)
    present = np.array([v is not None for v in arr], dtype=bool)
    if present.all():
        dictionary, codes = np.unique(_to_bytes_array(values), return_inverse=True)
        return dictionary, codes.astype(np.int32)
    codes = np.full(len(arr), ABSENT, dtype=np.int32)
    if present.any():
        present_vals = _to_bytes_array([v for v in arr if v is not None])
        dictionary, inv = np.unique(present_vals, return_inverse=True)
        codes[present] = inv.astype(np.int32)
    else:
        dictionary = np.empty(0, dtype="S1")
    return dictionary, codes


def lookup_code(dictionary: np.ndarray, value: str) -> int:
    """Dictionary slot of *value*, or -1 when absent (host binary search)."""
    if dictionary.size == 0:
        return -1
    key = value.encode("utf-8") if dictionary.dtype.kind == "S" else value
    i = int(np.searchsorted(dictionary, key))
    if i < dictionary.size and dictionary[i] == key:
        return i
    return -1


class _LaneState:
    """Shared mutable state of one device-lane dictionary.

    ``with_codes``/``gather``/``with_sharding`` copies of a column all
    point at the SAME state, so the deferred union sort
    (:meth:`StringColumn._ensure_sorted_lanes`) runs once globally:
    after the first settle, ``trans`` (old slot -> sorted slot) lets
    every other copy remap its codes with one cheap gather instead of
    re-sorting the full dictionary."""

    __slots__ = ("lanes", "sorted", "trans", "lock")

    def __init__(self, lanes: tuple, sorted_: bool):
        self.lanes = lanes
        self.sorted = sorted_
        self.trans = None
        # sibling copies may settle concurrently (ingest runs a prefetch
        # producer thread plus encode pools); the union sort + remap must
        # be serialized so it runs once and trans is never read half-set
        self.lock = threading.Lock()


class StringColumn:
    """One dictionary-encoded string column.

    (See :class:`_LaneState` for the shared deferred-sort state of
    device-lane dictionaries.)

    The dictionary normally lives on host (sorted 'S' bytes).  HIGH-
    CARDINALITY columns may instead carry it on DEVICE as sign-flipped
    int32 byte lanes (ops/lanes.py) with ``dictionary=None``: host RSS
    then stays bounded through ingest and through every code-only
    operation (sorts, filters, joins via lane translation).  Reading
    ``.dictionary`` on such a column lazily downloads and unpacks the
    lanes — the sink-boundary cost, paid only when strings are actually
    materialized.
    """

    def __init__(
        self,
        dictionary: "np.ndarray | None",  # sorted 'S' bytes, host (or None)
        codes: jax.Array,  # int32[n] on device; -1 = absent cell
        _has_absent: "bool | None" = None,  # lazy cache: any absent cells?
        _str_dict: "np.ndarray | None" = None,  # lazy cache: decoded dict
        _codes_host: "np.ndarray | None" = None,  # lazy cache: host codes
        dev_dictionary: "tuple | None" = None,  # int32 lanes, device
        dev_dict_sorted: bool = True,  # False: unsorted concat, may hold dups
        _lane_state: "_LaneState | None" = None,  # share with sibling copies
    ):
        assert dictionary is not None or dev_dictionary is not None or (
            _lane_state is not None
        )
        self._dictionary = dictionary
        self._has_absent = _has_absent
        self._str_dict = _str_dict
        self._codes_host = _codes_host
        # streamed ingest defers the global dictionary sort: an UNSORTED
        # lane dictionary (concatenated chunk dictionaries, codes offset
        # per chunk) decodes/gathers/checksums fine, but anything that
        # relies on code order == value order or one-value-one-code
        # (find_code, joins, sorts, host materialization, persistence)
        # must call _ensure_sorted_lanes() first.  The lane state is
        # SHARED between with_codes/gather/with_sharding copies so the
        # global sort runs once; each copy then remaps its own codes
        # with one cheap gather.
        if _lane_state is not None:
            self._lane_state = _lane_state
        elif dev_dictionary is not None:
            self._lane_state = _LaneState(dev_dictionary, dev_dict_sorted)
        else:
            self._lane_state = None
        # (codes, dev_dict_sorted) publish as ONE tuple: the flag is True
        # when the codes index the CURRENT (settled) lane order, and a
        # concurrent reader (with_codes/gather/with_sharding copying a
        # column while a sibling settles on another thread) must never
        # see a remapped codes array paired with a stale flag — a single
        # attribute read is atomic under the GIL, two are not.
        self._codes_state = (
            codes,
            dev_dict_sorted if self._lane_state is not None else True,
        )

    kind = "str"

    @property
    def codes(self) -> jax.Array:
        return self._codes_state[0]

    @property
    def storage(self) -> jax.Array:
        """The kind-agnostic row-indexed device array (shared protocol
        with :class:`~csvplus_tpu.columnar.typed.IntColumn`): dictionary
        codes here, int32 value lanes there.  Row-materializing consumers
        (gathers, sorts' payload permutation, sync) use this so a typed
        payload column is never demoted just to ride along."""
        return self.codes

    def with_storage(self, arr) -> "StringColumn":
        return self.with_codes(arr)

    @property
    def _dev_dict_sorted(self) -> bool:
        return self._codes_state[1]

    @property
    def dev_dictionary(self) -> "tuple | None":
        """The device lane dictionary, coherent with ``self.codes``: if a
        sibling copy already settled the shared state, this column's
        codes remap (cheap gather, no sort) before the lanes are
        exposed."""
        st = self._lane_state
        if st is None:
            return None
        if self._dev_dict_sorted:
            # coherent and FINAL: either the state was born sorted or this
            # copy already remapped; settled lanes never change again
            return st.lanes
        with st.lock:
            # under the lock no sibling can be mid-settle: either the
            # state is still the unsorted concat (coherent with our
            # codes) or it settled completely and we remap before
            # exposing the sorted lanes
            if st.sorted:
                self._settle_locked(st)  # remap-only: the sort already ran
            return st.lanes

    @property
    def dictionary(self) -> np.ndarray:
        """The host dictionary — lazily materialized (download + unpack)
        for device-lane columns, then cached."""
        if self._dictionary is None:
            from ..ops.lanes import unpack_host

            self._ensure_sorted_lanes()
            self._dictionary = unpack_host(
                [np.asarray(l) for l in self._lane_state.lanes]
            )
        return self._dictionary

    def _ensure_sorted_lanes(self) -> None:
        """Sort + dedupe a deferred (unsorted-concat) lane dictionary ON
        DEVICE and remap this column's codes to the dense sorted slots —
        the lazy form of the streamed tier's dictionary union.  The sort
        runs ONCE per shared lane state (copies remap with one gather);
        columns only ever decoded/gathered/checksummed via the shared
        state never pay it (the round-4 northstar profile's dominant
        ingest cost was exactly this sort, paid eagerly for a payload
        column that never needed it)."""
        st = self._lane_state
        if st is None or self._dev_dict_sorted:
            return
        with st.lock:
            self._settle_locked(st)

    def _settle_locked(self, st: "_LaneState") -> None:
        """Settle the shared state (once) and remap this copy's codes.
        Caller must hold ``st.lock``."""
        if self._dev_dict_sorted:  # a sibling settled us meanwhile
            return
        from ..utils.observe import telemetry

        if not st.sorted:
            from ..ops.lanes import union_device

            with telemetry.stage(
                "lane-dict:deferred-sort", int(st.lanes[0].shape[0])
            ):
                union, (trans,) = union_device([st.lanes])
                # st.sorted is the publication flag: assign it LAST so a
                # racing reader can never see sorted lanes before the
                # translation table exists
                st.trans = trans
                st.lanes = union
                st.sorted = True
        trans = st.trans
        sh = getattr(self.codes, "sharding", None)
        if sh is not None and len(getattr(sh, "device_set", ())) > 1:
            # mesh-sharded codes: replicate the translation table onto
            # the codes' mesh so the remap gather is placement-legal
            trans = jax.device_put(
                trans,
                jax.sharding.NamedSharding(
                    sh.mesh, jax.sharding.PartitionSpec()
                ),
            )
        codes = self._codes_state[0]
        remapped = jnp.where(
            codes >= 0,
            jnp.take(trans, jnp.clip(codes, 0), axis=0),
            codes,
        )
        self._codes_host = None  # host mirror (if any) is stale
        # one atomic publication: remapped codes + settled flag together
        self._codes_state = (remapped, True)

    @property
    def dict_size(self) -> int:
        """Dictionary slot count WITHOUT forcing host materialization.
        Equals the distinct-value count once the lane state is settled;
        a DEFERRED (unsorted-concat) lane dictionary may overcount
        (duplicates across chunks) — code-order consumers settle via
        :meth:`_ensure_sorted_lanes` before sizing bit packs from this."""
        if self._dictionary is not None:
            return int(self._dictionary.size)
        return int(self._lane_state.lanes[0].shape[0])

    def find_code(self, value: str) -> int:
        """Dictionary slot of *value* or -1 — the device lane search for
        lane columns (search + verification fused in one jitted kernel,
        ONE scalar sync, no dictionary download), the host binary search
        otherwise."""
        if self._dictionary is not None:
            return lookup_code(self._dictionary, value)
        from ..ops.lanes import (
            MAX_LANE_BYTES,
            lanes_for_width,
            pack_host,
            translate_lanes,
        )

        key = value.encode("utf-8")
        if len(key) > MAX_LANE_BYTES:
            return -1  # wider than any lane-dictionary entry can be
        self._ensure_sorted_lanes()  # the lane search needs sorted order
        n_lanes = len(self.dev_dictionary)
        if lanes_for_width(len(key)) > n_lanes:
            return -1  # longer than every stored entry: cannot match
        q = pack_host(np.array([key], dtype="S"), n_lanes)
        qs = tuple(jnp.asarray(l) for l in q)
        return int(translate_lanes(self.dev_dictionary, qs)[0])

    def find_codes(self, values: Sequence[str]) -> np.ndarray:
        """Vectorized :meth:`find_code` over a batch of probe values —
        int64 codes, -1 where the value is not in the dictionary.

        One ``np.searchsorted`` over the host dictionary (or ONE jitted
        lane translation for device-lane dictionaries), instead of a
        binary search + device dispatch per probe: the per-column half
        of the batched lookup engine (``DeviceIndex.point_bounds_many``).
        """
        m = len(values)
        if m == 0:
            return np.empty(0, dtype=np.int64)
        if self._dictionary is not None:
            d = self._dictionary
            if d.size == 0:
                return np.full(m, -1, dtype=np.int64)
            if d.dtype.kind == "S":
                enc = np.array([v.encode("utf-8") for v in values], dtype="S")
            else:
                enc = np.asarray(values, dtype=d.dtype)
            pos = np.searchsorted(d, enc)
            pos_c = np.clip(pos, 0, d.size - 1)
            ok = d[pos_c] == enc
            return np.where(ok, pos_c, -1).astype(np.int64)
        from ..ops.lanes import (
            MAX_LANE_BYTES,
            lanes_for_width,
            pack_host,
            translate_lanes,
        )

        self._ensure_sorted_lanes()  # the lane search needs sorted order
        n_lanes = len(self.dev_dictionary)
        out = np.full(m, -1, dtype=np.int64)
        keys = [v.encode("utf-8") for v in values]
        # values wider than any stored entry can never match; translate
        # only the rest, in ONE fused device search over all of them
        fit = [
            i
            for i, k in enumerate(keys)
            if len(k) <= MAX_LANE_BYTES and lanes_for_width(len(k)) <= n_lanes
        ]
        if fit:
            sub = np.array([keys[i] for i in fit], dtype="S")
            q = pack_host(sub, n_lanes)
            qs = tuple(jnp.asarray(l) for l in q)
            out[fit] = np.asarray(translate_lanes(self.dev_dictionary, qs))
        return out

    @property
    def has_absent(self) -> bool:
        """True when any cell is absent (one cached scalar device sync).

        Columns parsed from CSV never have absent cells; only tables
        columnarized from heterogeneous rows do, so most paths skip the
        per-cell presence work entirely.
        """
        if self._has_absent is None:
            # absent is exactly -1; sharding pad rows use -2 and must not
            # defeat this fast path
            self._has_absent = bool(jnp.any(self.codes == ABSENT))
        return self._has_absent

    @classmethod
    def from_values(cls, values: Sequence[str], device) -> "StringColumn":
        dictionary, codes = encode_strings(values)
        # The encoder just saw every cell: record absence while it is a
        # free host scan.  A definite ``False`` here is what lets the
        # verifier prove columns PRESENT — the presence obligations the
        # plan rewriter's pushdown proofs consume (analysis/rewrite.py).
        has_absent = bool(codes.size) and bool(codes.min() < 0)
        return cls(
            dictionary, jax.device_put(codes, device), _has_absent=has_absent
        )

    @classmethod
    def constant(cls, value: str, n: int, device) -> "StringColumn":
        return cls(
            np.asarray([value.encode("utf-8")], dtype="S"),
            jax.device_put(np.zeros(n, dtype=np.int32), device),
        )

    def codes_host(self) -> np.ndarray:
        """Host mirror of the code array (one transfer, cached).

        Point-lookup paths (Index.find on a device-lazy index) decode
        matched ranges from this mirror in host numpy: one O(n) transfer
        buys microsecond lookups, instead of a device gather + download
        round trip per find."""
        if self._codes_host is None:
            self._ensure_sorted_lanes()  # mirror must be post-remap
            self._codes_host = np.asarray(self.codes)
        return self._codes_host

    def dictionary_str(self) -> np.ndarray:
        """The dictionary as python-str values (decoded lazily, cached)."""
        if self._str_dict is None:
            d = self.dictionary
            if d.dtype.kind == "S":
                self._str_dict = (
                    np.char.decode(d, "utf-8") if d.size else np.empty(0, np.str_)
                )
            else:
                self._str_dict = d
        return self._str_dict

    def __len__(self) -> int:
        return int(self.codes.shape[0])

    def with_codes(self, codes, dev_dict_sorted: "bool | None" = None) -> "StringColumn":
        """A column over *codes* carrying this column's dictionary and
        caches — the single definition of what survives a row gather:
        the decoded-dictionary cache always, and has_absent only when
        this column is known fully present (a subset of a fully-present
        column is fully present).

        *dev_dict_sorted* must be the flag snapshotted TOGETHER with the
        codes array the caller derived *codes* from (``_codes_state``);
        omitting it reads the current flag, which is only safe when no
        concurrent settle is possible (executor ops on already-settled
        columns — sorts/joins require code order, so their inputs have
        settled before they run)."""
        out = StringColumn(
            self._dictionary,
            codes,
            dev_dict_sorted=(
                self._dev_dict_sorted if dev_dict_sorted is None else dev_dict_sorted
            ),
            _lane_state=self._lane_state,
        )
        out._str_dict = self._str_dict
        if self._has_absent is False:
            out._has_absent = False
        return out

    def gather(self, sel, codes=None) -> "StringColumn":
        """New column of the selected row positions (device gather).

        *codes* substitutes a differently-placed copy of this column's
        codes (e.g. replicated onto the probe's mesh) — the dictionary
        and caches still come from self."""
        if codes is None:
            src, flag = self._codes_state  # one atomic coherent pair
        else:
            src, flag = codes, self._dev_dict_sorted
        idx = jnp.asarray(sel, dtype=jnp.int32)
        return self.with_codes(jnp.take(src, idx, axis=0), dev_dict_sorted=flag)

    def decode_codes(self, codes: np.ndarray) -> List[Optional[str]]:
        """Decode a host code slice against this column's dictionary;
        absent cells (negative codes, incl. the -2 sharding pad) become
        None.  The single definition of host-side code decoding, shared
        by :meth:`decode` and :meth:`DeviceTable.rows_from_mirror`.

        CALLER CONTRACT: *codes* must be snapshotted AFTER
        ``_ensure_sorted_lanes()`` (``decode``/``codes_host`` do this),
        because the deferred lane-dictionary sort remaps the code space.

        Small slices (point lookups) decode only the matched dictionary
        entries: decoding a 1M-entry dictionary to serve a 10-row
        ``Index.find`` cost ~1.3s of one-time work and was the round-3
        "device find 665 lookups/s" bottleneck."""
        if self.dict_size == 0:
            return [None] * codes.shape[0]
        if self._str_dict is None and codes.shape[0] * 16 < self.dict_size:
            d = self.dictionary
            sel = d[np.clip(codes, 0, d.size - 1)]
            if d.dtype.kind == "S":
                out = [v.decode("utf-8") for v in sel.tolist()]
            else:
                out = sel.tolist()
            if (codes < 0).any():
                out = [None if c < 0 else v for c, v in zip(codes.tolist(), out)]
            return out
        d = self.dictionary_str()
        vals = d[np.clip(codes, 0, d.size - 1)]
        out = vals.tolist()
        if (codes < 0).any():
            out = [None if c < 0 else v for c, v in zip(codes.tolist(), out)]
        return out

    def decode(self) -> List[Optional[str]]:
        """Materialize values on host; absent cells become None."""
        self._ensure_sorted_lanes()  # BEFORE the code snapshot below
        return self.decode_codes(np.asarray(self.codes))

    def _lanes_narrow(self) -> "tuple":
        """``(lane tuple, original-slot positions | None)`` — this
        dictionary as device lanes, restricted to entries narrow enough
        to lane-pack.  A host dictionary mixed into a lane-column join
        may hold values wider than MAX_LANE_BYTES; those can never equal
        any lane entry, so they are excluded here (positions returned so
        the caller can remap subset slots back to full slots) instead of
        failing the whole join."""
        if self.dev_dictionary is not None:
            self._ensure_sorted_lanes()  # translation assumes sorted lanes
            return self.dev_dictionary, None
        from ..ops.lanes import MAX_LANE_BYTES, lanes_for_width, pack_host

        d = self._dictionary
        width = d.dtype.itemsize if d.size else 1
        lanes = lanes_for_width(width)
        if lanes is not None:
            return tuple(jax.device_put(l) for l in pack_host(d, lanes)), None
        # host dictionaries are always 'S' bytes arrays (encode_strings
        # invariant), so byte lengths come straight from str_len
        keep = np.char.str_len(d) <= MAX_LANE_BYTES
        pos = np.flatnonzero(keep).astype(np.int32)
        sub = d[keep].astype(f"S{MAX_LANE_BYTES}")
        lanes = lanes_for_width(MAX_LANE_BYTES)
        return tuple(jax.device_put(l) for l in pack_host(sub, lanes)), pos

    def renumbered_to_col(self, other: "StringColumn") -> jax.Array:
        """Translate this column's codes into *other*'s code space —
        the device lane translation when either side keeps its
        dictionary on device (no host materialization), the host
        translation-table path otherwise.  Host dictionaries with
        entries wider than a lane can hold are handled by translating
        the narrow subset and treating wide values as no-match."""
        if self.dev_dictionary is None and other.dev_dictionary is None:
            return self.renumbered_to(other.dictionary)
        from ..ops.lanes import translate_lanes

        if self.dict_size == 0:
            return self.codes
        q_lanes, q_pos = self._lanes_narrow()
        b_lanes, b_pos = other._lanes_narrow()
        if b_lanes[0].shape[0] == 0 or q_lanes[0].shape[0] == 0:
            # preserve negative code identity (-2 sharding pads stay -2),
            # matching the main path below
            return jnp.where(self.codes >= 0, ABSENT, self.codes)
        trans = translate_lanes(b_lanes, q_lanes)
        if b_pos is not None:
            # subset slots of other -> other's full code space
            trans = jnp.where(
                trans >= 0,
                jnp.take(jnp.asarray(b_pos), jnp.clip(trans, 0), axis=0),
                -1,
            )
        if q_pos is not None:
            # scatter subset results back over self's full dictionary;
            # wide entries stay -1 (no-match)
            trans = (
                jnp.full(self.dict_size, -1, jnp.int32)
                .at[jnp.asarray(q_pos)]
                .set(trans)
            )
        # negative codes pass through unchanged (-1 absent stays -1,
        # -2 sharding pads stay -2), same as the empty-lane early return
        return _apply_code_translation(self.codes, trans)

    def renumbered_to(self, other_dictionary: np.ndarray) -> jax.Array:
        """Translate this column's codes into another dictionary's code
        space (host translation table + device gather); unmatched -> -1.

        This is how a probe-side join key enters the index's key space.
        """
        if self.dictionary.size == 0:
            return self.codes
        pos = np.searchsorted(other_dictionary, self.dictionary)
        pos = np.clip(pos, 0, max(other_dictionary.size - 1, 0))
        ok = (
            other_dictionary[pos] == self.dictionary
            if other_dictionary.size
            else np.zeros(self.dictionary.size, dtype=bool)
        )
        trans = np.where(ok, pos, -1).astype(np.int32)
        trans_dev = jax.device_put(trans, None)
        # unmatched becomes -1; negative codes pass through unchanged
        # (-1 absent stays -1, -2 sharding pads stay -2) so both
        # translation paths keep the same negative-code identity
        return _apply_code_translation(self.codes, jnp.asarray(trans_dev))


@register_kernel("table.apply_code_translation")
@jax.jit
def _apply_code_translation(codes: jax.Array, trans: jax.Array) -> jax.Array:
    """``trans[codes]`` with negative codes passed through unchanged —
    one fused kernel instead of three eager passes (the translation runs
    per probe execution on the warm-join path)."""
    return jnp.where(
        codes >= 0, jnp.take(trans, jnp.clip(codes, 0), axis=0), codes
    )


@register_kernel("table.sync_probe")
@jax.jit
def _sync_probe(*code_arrays: jax.Array) -> jax.Array:
    """sum(first element of each array) — a one-scalar dependency on all."""
    return sum(a[0].astype(jnp.int32) for a in code_arrays)


def same_placement(arrays) -> bool:
    """True when every array commits to the same device set (safe to
    pass together into one jitted computation)."""
    first = None
    for a in arrays:
        sh = getattr(a, "sharding", None)
        if sh is None:
            return False
        ds = frozenset(sh.device_set)
        if first is None:
            first = ds
        elif ds != first:
            return False
    return True


def merge_with_fallback(primary: StringColumn, fallback: StringColumn) -> StringColumn:
    """Cell-wise merge: primary's value where present, else fallback's.

    This is the columnar form of the reference's row merge on column-name
    collision (csvplus.go:571-583): the stream (primary) value wins, but a
    stream row *without* the cell keeps the index (fallback) value.
    Both columns are recoded into the union dictionary first.
    """
    if not primary.has_absent:  # one cached scalar sync, no O(n) transfer
        return primary
    union = np.union1d(primary.dictionary, fallback.dictionary)
    p = primary.renumbered_to(union)
    f = fallback.renumbered_to(union)
    return StringColumn(union, jnp.where(p >= 0, p, f))


class DeviceTable:
    """An ordered set of equal-length columns resident on one device.

    ``row_base`` is the source row number of table row 0, in the
    originating source's numbering convention (2 for a Reader ingest of a
    file with a header row, 1 for a headerless one, 0 for in-memory rows
    — matching the host paths' ``DataSourceError`` numbering).  It is
    only meaningful while row i of the table still IS source row i;
    executor stages that reorder or drop rows reset it to 0.
    """

    def __init__(
        self, columns: Dict[str, StringColumn], nrows: int, device, row_base: int = 0
    ):
        self.columns = columns
        self.nrows = nrows
        self.device = device
        self.row_base = row_base
        # set by producers whose construction already blocked on a scalar
        # that depends on every column (e.g. the fused flagship join's
        # match count): sync() is then a completed fact, not a round trip
        self.already_forced = False
        # serializes the mirror-decode LRU (rows_from_mirror_many): the
        # serving tier made concurrent lookups real, and an OrderedDict
        # being reordered (move_to_end) while another thread inserts or
        # evicts corrupts it — even cache HITS mutate recency order, so
        # every access must hold this
        self._mirror_lock = threading.Lock()

    @classmethod
    def from_pylists(
        cls, data: Dict[str, Sequence[str]], device=None
    ) -> "DeviceTable":
        dev = default_device(device)
        cols = {}
        nrows = 0
        for name, values in data.items():
            cols[name] = StringColumn.from_values(values, dev)
            nrows = len(values)
        return cls(cols, nrows, dev)

    @classmethod
    def from_encoded(
        cls,
        data: "Dict[str, tuple[np.ndarray, np.ndarray]]",
        nrows: int,
        device=None,
    ) -> "DeviceTable":
        """Build from already dictionary-encoded columns
        ((dictionary, codes) pairs, e.g. the native ingest fast path;
        a ready StringColumn — e.g. a device-lane-dictionary column from
        the streamed ingest — passes through unchanged)."""
        from .typed import IntColumn

        dev = default_device(device)
        cols = {}
        for name, value in data.items():
            if isinstance(value, (StringColumn, IntColumn)):
                cols[name] = value
                continue
            if len(value) == 3 and value[0] == "int":
                # typed value lanes from the native/streamed scanners
                _, prefix, vals = value
                cols[name] = IntColumn(
                    prefix,
                    vals
                    if isinstance(vals, jax.Array)
                    else jax.device_put(vals, dev),
                )
                continue
            dictionary, codes = value
            cols[name] = StringColumn(
                dictionary,
                codes if isinstance(codes, jax.Array) else jax.device_put(codes, dev),
            )
        return cls(cols, nrows, dev)

    @classmethod
    def from_rows(cls, rows: Sequence[Row], device=None) -> "DeviceTable":
        """Columnarize possibly-heterogeneous rows; missing cells -> absent."""
        names: List[str] = []
        seen = set()
        for r in rows:
            for k in r:
                if k not in seen:
                    seen.add(k)
                    names.append(k)
        data = {n: [r.get(n) for r in rows] for n in names}
        t = cls.from_pylists(data, device)
        t.nrows = len(rows)
        return t

    def column_names(self) -> List[str]:
        return list(self.columns)

    def with_sharding(self, mesh) -> "DeviceTable":
        """Re-lay every code array row-sharded over *mesh* (GSPMD).

        All executor ops (masks, gathers, sorts, probes) are jnp ops, so
        once the codes carry a ``NamedSharding`` XLA partitions the whole
        pipeline data-parallel and inserts collectives where gathers or
        sorts cross shards — the "pick a mesh, annotate shardings, let
        XLA insert collectives" recipe.  The explicit ``shard_map``
        partitioned join (csvplus_tpu/parallel/pjoin.py) remains the
        hand-optimized path for very large build sides.
        """
        from jax.sharding import NamedSharding
        from ..parallel.mesh import row_spec

        sharding = NamedSharding(mesh, row_spec(mesh))
        n_dev = mesh.devices.size
        pad = (-self.nrows) % n_dev  # NamedSharding needs divisibility
        cols = {}
        from .typed import IntColumn

        for name, col in self.columns.items():
            if isinstance(col, IntColumn):
                from .typed import PAD_VALUE

                vals = np.asarray(col.values)
                if pad:
                    # PAD_VALUE can never be a real cell (the parser
                    # bounds |v| <= INT32_MAX), so pad rows stay
                    # unambiguous through translations and demotion
                    vals = np.concatenate(
                        [vals, np.full(pad, PAD_VALUE, np.int32)]
                    )
                cols[name] = IntColumn(col.prefix, jax.device_put(vals, sharding))
                continue
            src_codes, dict_sorted = col._codes_state  # atomic coherent pair
            codes = np.asarray(src_codes)
            if pad:
                # -2 = padding (never matches; distinct from -1 = absent);
                # padding rows live beyond nrows, outside every selection
                codes = np.concatenate(
                    [codes, np.full(pad, -2, dtype=np.int32)]
                )
            moved = StringColumn(
                col._dictionary,
                jax.device_put(codes, sharding),
                dev_dict_sorted=dict_sorted,
                _lane_state=col._lane_state,
            )
            moved._str_dict = col._str_dict
            moved._has_absent = col._has_absent if not pad else None
            cols[name] = moved
        return DeviceTable(cols, self.nrows, mesh.devices.flat[0], self.row_base)

    def shard_row_counts(self) -> "dict[str, int]":
        """Rows resident per device for the first sharded column — the
        placement-balance evidence the skew-aware join bench records.

        This placement IS the broadcast tier's salt: a heavy key's fact
        rows stay scattered across shards at their ingest positions
        (instead of collapsing onto the key's range owner as the
        hash-repartition exchange would force), each shard answers its
        own hot rows from the replicated answer slots, and the
        positional scatter-back at emit (``.at[pos].set`` in
        ``parallel/pjoin.py``) folds the salt out again — which is why
        the skew-aware result is bitwise-identical to the unsalted
        path.  Empty dict when no column is sharded."""
        for col in self.columns.values():
            storage = col.storage
            shards = getattr(storage, "addressable_shards", None)
            if shards and len(shards) > 1:
                return {
                    str(s.device): int(s.data.shape[0]) for s in shards
                }
        return {}

    def short_desc(self) -> str:
        return f"{self.nrows}x{len(self.columns)}[{','.join(self.columns)}]"

    def sync(self) -> "DeviceTable":
        """Force completion of every column with ONE scalar round trip.

        Per-column ``block_until_ready`` costs one readiness ping per
        buffer; over a remote/tunneled backend each ping is a network
        round trip.  Instead, dispatch a trivial reduction that depends
        on every code array and sync its single scalar — it cannot
        complete before all inputs have.
        """
        if self.already_forced:
            return self
        cols = [c.storage for c in self.columns.values()]
        cols = [c for c in cols if c.shape[0]]
        if not cols:
            return self
        if same_placement(cols):
            int(_sync_probe(*cols))
        else:
            # mixed placements (e.g. a join of a single-device build table
            # into a mesh-sharded stream) cannot share one jitted call
            for c in cols:
                c.block_until_ready()
        return self

    def gather(self, sel) -> "DeviceTable":
        cols = {n: c.gather(sel) for n, c in self.columns.items()}
        return DeviceTable(cols, int(len(sel)), self.device)

    def to_rows(self, sel=None) -> List[Row]:
        """Decode (a selection of) the table back into host Rows; absent
        cells are omitted from their row, matching the host path's
        heterogeneous dicts."""
        cols = self.columns
        if sel is not None:
            cols = {n: c.gather(sel) for n, c in cols.items()}
            n = int(len(sel))
        else:
            n = self.nrows
        decoded = {name: c.decode() for name, c in cols.items()}
        names = list(decoded)
        out = []
        for i in range(n):
            row = Row()
            for name in names:
                v = decoded[name][i]
                if v is not None:
                    row[name] = v
            out.append(row)
        return out

    def rows_from_mirror(self, lower: int, upper: int) -> List[Row]:
        """Decode the row range [lower, upper) from host code mirrors.

        The device-lazy Index's point-lookup decode: each column's codes
        mirror to host once (StringColumn.codes_host), then every find
        is pure numpy — no device dispatch at all."""
        return self.rows_from_mirror_many([(lower, upper)])[0]

    # Decoded mirror blocks are cached per (lower, upper) range up to this
    # many rows; repeated probes of hot keys then skip the decode entirely.
    # Checked per call so tests can tune it via the environment.
    MIRROR_LRU_ROWS_DEFAULT = 65536

    def _mirror_lru_cap(self) -> int:
        return env_int("CSVPLUS_MIRROR_LRU_ROWS", self.MIRROR_LRU_ROWS_DEFAULT)

    def rows_from_mirror_many(
        self, bounds: Sequence[Tuple[int, int]]
    ) -> List[List[Row]]:
        """Batched :meth:`rows_from_mirror`: ONE gather + decode per
        column over the union of all requested ranges, split back into
        per-range row blocks, with a bounded LRU over decoded blocks.

        Returned blocks share Row objects with the cache (and across
        duplicate ranges) — the same sharing contract as the host tier's
        ``rows[lower:upper]`` slices; ``iterate`` clones on delivery.

        Thread-safe: the whole call holds ``_mirror_lock``.  The serving
        tier funnels lookups through ONE dispatcher thread, so the lock
        is normally uncontended — it exists so direct concurrent callers
        (the r08 stress test, user code sharing an Index across threads)
        get serialized decodes instead of a corrupted LRU, with results
        bitwise-equal to the serial order.
        """
        with self._mirror_lock:
            return self._rows_from_mirror_many_locked(bounds)

    def _rows_from_mirror_many_locked(
        self, bounds: Sequence[Tuple[int, int]]
    ) -> List[List[Row]]:
        lru = getattr(self, "_mirror_lru", None)
        if lru is None:
            from collections import OrderedDict

            lru = self._mirror_lru = OrderedDict()
            self._mirror_lru_rows = 0
        out: List[Optional[List[Row]]] = [None] * len(bounds)
        misses: Dict[Tuple[int, int], List[int]] = {}
        for i, (lo, hi) in enumerate(bounds):
            lo, hi = int(lo), int(hi)
            if hi <= lo:
                out[i] = []
                continue
            got = lru.get((lo, hi))
            if got is not None:
                lru.move_to_end((lo, hi))
                out[i] = got
            else:
                misses.setdefault((lo, hi), []).append(i)
        if misses:
            ranges = list(misses)
            starts = np.array([r[0] for r in ranges], dtype=np.int64)
            sizes = np.array([r[1] - r[0] for r in ranges], dtype=np.int64)
            # vectorized concat of aranges: arange(total) re-based per
            # range (an arange + concatenate per range is pure overhead
            # when most matches are single rows)
            offsets = np.concatenate(([0], np.cumsum(sizes)[:-1]))
            idx = (
                np.arange(int(sizes.sum()), dtype=np.int64)
                + np.repeat(starts - offsets, sizes)
            )
            decoded = {}
            for name, col in self.columns.items():
                if col.kind == "int":
                    decoded[name] = col.decode_take(idx)
                else:
                    decoded[name] = col.decode_codes(col.codes_host()[idx])
            names = list(decoded)
            off = 0
            for r in ranges:
                size = r[1] - r[0]
                block = [Row() for _ in range(size)]
                for name in names:
                    vals = decoded[name]
                    for j in range(size):
                        v = vals[off + j]
                        if v is not None:
                            block[j][name] = v
                off += size
                for i in misses[r]:
                    out[i] = block
                lru[r] = block
                self._mirror_lru_rows += size
            cap = self._mirror_lru_cap()
            while self._mirror_lru_rows > cap and len(lru) > 1:
                _, evicted = lru.popitem(last=False)
                self._mirror_lru_rows -= len(evicted)
        return out  # type: ignore[return-value]

    # -- iteration protocol so take(DeviceTable) works ---------------------

    def iterate(self, fn) -> None:
        """Stream decoded rows (the escape hatch for opaque callbacks)."""
        from ..source import iterate

        iterate(self.to_rows(), fn)

    Iterate = iterate

    @property
    def plan(self):
        from ..plan import Scan

        return Scan(self)
