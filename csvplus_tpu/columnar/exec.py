"""The device plan executor.

Walks a plan IR chain (:mod:`csvplus_tpu.plan`) rooted at a ``Scan`` of a
:class:`~csvplus_tpu.columnar.table.DeviceTable` and executes it with
columnar device kernels:

* ``Filter`` -> fused boolean mask on the VPU (:mod:`..ops.filter`);
* ``Top``/``DropRows`` -> selection-vector slicing (these are *ordered*
  operators, so they act on the current selection, preserving the host
  path's stream semantics, csvplus.go:313-342);
* ``SelectCols``/``DropCols``/``MapExpr`` -> column-metadata updates
  (a rename or constant write never touches row data);
* ``Join``/``Except`` -> packed-key probe kernels (:mod:`..ops.join`).

Execution keeps a **selection vector** (host int64 row ids) over
full-length device columns and materializes gathers as late as possible;
the only per-row host work is the final string decode at the sink
boundary.

Anything not expressible returns ``None`` from :func:`try_execute_plan`,
and the caller falls back to the host streaming path — behavior parity
always wins over device execution.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import plan as P
from ..errors import DataSourceError, StopPipeline
from ..row import MissingColumnError, Row
from .table import DeviceTable, StringColumn


class UnsupportedPlan(Exception):
    """Plan contains a stage the device executor cannot lower."""


class _View:
    """Full-length columns + an ordered selection vector of row ids."""

    __slots__ = ("cols", "sel", "device")

    def __init__(self, cols: Dict[str, StringColumn], sel: np.ndarray, device):
        self.cols = cols
        self.sel = sel
        self.device = device

    def materialize(self) -> DeviceTable:
        gathered = {n: c.gather(self.sel) for n, c in self.cols.items()}
        return DeviceTable(gathered, int(self.sel.shape[0]), self.device)


def _linearize(node: P.PlanNode) -> List[P.PlanNode]:
    chain: List[P.PlanNode] = []
    while not isinstance(node, P.Scan):
        chain.append(node)
        node = node.child
    chain.append(node)
    chain.reverse()
    return chain


def execute_plan(root: P.PlanNode) -> DeviceTable:
    """Run the plan and return the resulting materialized DeviceTable."""
    from ..ops.filter import UnsupportedPredicate, build_mask
    from ..ops import join as J

    stages = _linearize(root)
    scan = stages[0]
    assert isinstance(scan, P.Scan)
    table: DeviceTable = scan.table
    view = _View(
        dict(table.columns), np.arange(table.nrows, dtype=np.int64), table.device
    )

    for node in stages[1:]:
        if isinstance(node, P.Filter):
            nrows = _full_len(view)
            try:
                mask = build_mask(view.cols, nrows, node.pred)
            except UnsupportedPredicate as e:
                raise UnsupportedPlan(str(e)) from e
            mask_np = np.asarray(mask)
            view.sel = view.sel[mask_np[view.sel]]
        elif isinstance(node, P.Top):
            view.sel = view.sel[: node.n]
        elif isinstance(node, P.DropRows):
            view.sel = view.sel[node.n :]
        elif isinstance(node, P.SelectCols):
            missing = [c for c in node.columns if c not in view.cols]
            if missing:
                # the host path fails at the first streamed row; use the
                # 0-based position like the slice iterator (csvplus.go:242)
                raise DataSourceError(0, MissingColumnError(missing[0]))
            view.cols = {c: view.cols[c] for c in node.columns}
        elif isinstance(node, P.DropCols):
            view.cols = {
                n: c for n, c in view.cols.items() if n not in set(node.columns)
            }
        elif isinstance(node, P.MapExpr):
            _apply_map(view, node.expr)
        elif isinstance(node, P.Join):
            dev_index = node.index.device_table
            if dev_index is None or not dev_index.supported:
                raise UnsupportedPlan("join build side has no packed device index")
            stream = view.materialize()
            try:
                joined = J.join_tables(stream, dev_index, list(node.columns))
            except MissingColumnError as e:
                raise DataSourceError(0, e) from e
            view = _View(
                dict(joined.columns),
                np.arange(joined.nrows, dtype=np.int64),
                joined.device,
            )
        elif isinstance(node, P.Except):
            dev_index = node.index.device_table
            if dev_index is None or not dev_index.supported:
                raise UnsupportedPlan("except build side has no packed device index")
            stream = view.materialize()
            try:
                keep = J.except_mask(stream, dev_index, list(node.columns))
            except MissingColumnError as e:
                raise DataSourceError(0, e) from e
            view = _View(
                dict(stream.columns),
                np.flatnonzero(keep).astype(np.int64),
                stream.device,
            )
        else:
            raise UnsupportedPlan(f"no device lowering for {type(node).__name__}")

    return view.materialize()


def _full_len(view: _View) -> int:
    for c in view.cols.values():
        return len(c)
    return 0


def _apply_map(view: _View, expr) -> None:
    from ..exprs import Rename, SetValue, Update

    if isinstance(expr, Update):
        for e in expr.exprs:
            _apply_map(view, e)
        return
    if isinstance(expr, SetValue):
        n = _full_len(view)
        view.cols[expr.column] = StringColumn.constant(expr.value, n, view.device)
        return
    if isinstance(expr, Rename):
        # sequential pop/overwrite, matching the host expr exactly
        # (exprs.Rename: row[new] = row.pop(old) per mapping entry, so a
        # rename onto an existing name overwrites it, and chained renames
        # {'a':'b','b':'c'} cascade)
        for old, new in expr.mapping.items():
            if old in view.cols:
                view.cols[new] = view.cols.pop(old)
        return
    raise UnsupportedPlan(f"cannot lower map expression {expr!r} to device")


def try_execute_plan(root: Optional[P.PlanNode]) -> Optional[List[Row]]:
    """Execute the plan to host Rows, or None when not device-executable."""
    if root is None:
        return None
    try:
        return execute_plan(root).to_rows()
    except UnsupportedPlan:
        return None


def plan_runner(root: P.PlanNode, fallback=None):
    """A DataSource driver that executes *root* on device and streams the
    decoded rows; falls back to *fallback* when the plan is unsupported."""

    def run(fn) -> None:
        try:
            table = execute_plan(root)
        except UnsupportedPlan:
            if fallback is None:
                raise
            fallback(fn)
            return
        rows = table.to_rows()
        i = 0
        try:
            for i, row in enumerate(rows):
                fn(row)
        except StopPipeline:
            return
        except DataSourceError:
            raise
        except Exception as e:
            raise DataSourceError(i, e) from e

    return run
