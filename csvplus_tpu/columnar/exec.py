"""Device plan executor (placeholder until M2 lands this round)."""


def try_execute_plan(plan):
    # No device tables exist yet, so no plan can be device-executable;
    # sinks fall back to the host path on None.
    return None
