"""The device plan executor.

Walks a plan IR chain (:mod:`csvplus_tpu.plan`) rooted at a ``Scan`` of a
:class:`~csvplus_tpu.columnar.table.DeviceTable` and executes it with
columnar device kernels:

* ``Filter`` -> fused boolean mask on the VPU (:mod:`..ops.filter`);
* ``Top``/``DropRows`` -> selection-vector slicing (these are *ordered*
  operators, so they act on the current selection, preserving the host
  path's stream semantics, csvplus.go:313-342);
* ``SelectCols``/``DropCols``/``MapExpr`` -> column-metadata updates
  (a rename or constant write never touches row data);
* ``Join``/``Except`` -> packed-key probe kernels (:mod:`..ops.join`).

Execution keeps a **selection vector** (device int32 row ids) over
full-length device columns and materializes gathers as late as possible.
Data-dependent control flow stays on device — filters compact the
selection with a device boolean gather, windowing cuts come from a
device argmax — so the only values crossing to host per stage are O(1)
scalars (result sizes), and the only per-row host work is the final
string decode at the sink boundary.

Anything not expressible returns ``None`` from :func:`try_execute_plan`,
and the caller falls back to the host streaming path — behavior parity
always wins over device execution.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .. import plan as P
from ..errors import CsvPlusError, DataSourceError
from ..row import MissingColumnError, Row
from .table import DeviceTable, StringColumn


class UnsupportedPlan(Exception):
    """Plan contains a stage the device executor cannot lower."""


class _View:
    """Full-length columns + an ordered selection vector of row ids.

    ``full_len`` is the unsliced column length, tracked explicitly so a
    view with zero columns (everything dropped) still knows its row count
    — the host path streams empty rows in that case, and so must we.

    ``scan_base`` is the source row number of full-length row 0 (the
    originating table's ``row_base``), so ``scan_base + sel[i]`` is the
    source-convention row number of the i-th streamed row — exact until a
    Join replaces the row space, which resets it to 0 (Except merely
    narrows the selection, so it preserves the numbering).  This keeps
    device error row numbers aligned with the host paths' (the host wraps
    errors with the *originating* source's numbering, e.g. 1-based file
    records for a Reader, csvplus.go:1080-1146) for sources whose table
    carries a ``row_base`` — the ``Reader.on_device`` ingest tiers.  The
    generic ``DataSource.on_device`` route columnarizes an anonymous row
    stream (base 0), so its errors are numbered by streamed position.
    """

    __slots__ = (
        "cols",
        "_sel",
        "device",
        "full_len",
        "scan_base",
        "deferred_error",
        "identity",
    )

    def __init__(
        self,
        cols: Dict[str, StringColumn],
        sel: np.ndarray,
        device,
        full_len: int,
        scan_base: int = 0,
        identity: bool = False,
    ):
        self.cols = cols
        self.sel = sel  # the setter clears identity; restore from the arg
        self.device = device
        self.full_len = full_len
        self.scan_base = scan_base
        self.identity = identity
        # (stream index of the first validate failure, the exception) —
        # fired by consumers only if streaming reaches that row
        self.deferred_error = None

    @property
    def sel(self):
        return self._sel

    @sel.setter
    def sel(self, value):
        # any rewrite of the selection (filter/top/drop/except/...)
        # invalidates the identity shortcut
        self._sel = value
        self.identity = False

    def materialize(self) -> DeviceTable:
        if self.identity:
            # sel is arange(full_len) over unpadded columns: gathering
            # would copy every column through an identity permutation
            # (2.4GB of HBM churn at the 100M-row north star) — pass the
            # columns through with their caches intact instead
            table = DeviceTable(dict(self.cols), self.full_len, self.device)
        else:
            gathered = {n: c.gather(self.sel) for n, c in self.cols.items()}
            table = DeviceTable(gathered, int(self.sel.shape[0]), self.device)
        if self.deferred_error is not None:
            table.deferred_error = self.deferred_error
        return table


def _linearize(node: P.PlanNode) -> List[P.PlanNode]:
    return P.linearize(node)


def execute_plan(root: P.PlanNode) -> DeviceTable:
    """Run the plan and return the resulting materialized DeviceTable.

    With :data:`csvplus_tpu.utils.telemetry` enabled, every stage records
    (rows in, rows out, wall time) and shows as a named range in device
    profiles."""
    return execute_plan_view(root).materialize()


def execute_plan_view(root: P.PlanNode, preverified: bool = False) -> "_View":
    """Run the plan, returning the final executor view (columns +
    selection vector + source row numbering) without materializing.

    The static verifier (:mod:`csvplus_tpu.analysis`) runs first:
    unlowerable plans raise :class:`UnsupportedPlan` BEFORE any device
    work (the caller falls back to the host path exactly as it would
    have mid-execution), and invalid column references are known up
    front rather than discovered one stage at a time.  ``CSVPLUS_VERIFY=0``
    is the escape hatch back to the unverified lowering.

    ``preverified=True`` skips the verifier hook: the caller vouches
    that a plan of this exact STRUCTURAL shape already verified clean.
    The serving tier's plan-executable cache
    (:mod:`csvplus_tpu.serve.plancache`) is the one legitimate caller —
    it verifies each shape once at admission and keys the cache so any
    op/schema/placement change re-verifies.
    """
    if not preverified:
        from ..analysis import verify_before_lower

        verify_before_lower(root)
    stages = _linearize(root)
    # Validate lowers only as the FINAL stage.  Upstream of anything
    # else, the host's push semantics (check rows one by one, stop the
    # moment downstream stops) cannot be reproduced by an eager device
    # check — and even terminal validates defer their failure to
    # streaming time (see the P.Validate branch) so a consumer that
    # stops early never observes an error the host would not have
    # raised.  Parity wins (plan.py).
    for node in stages[:-1]:
        if isinstance(node, P.Validate):
            raise UnsupportedPlan("Validate is device-lowered only as last stage")
    scan = stages[0]
    assert isinstance(scan, (P.Scan, P.Lookup))
    table: DeviceTable = scan.table
    # full_len follows the stored column length, which may exceed nrows
    # when codes are padded for mesh-sharding divisibility; the selection
    # vector never reaches the padding rows
    stored_len = (
        len(next(iter(table.columns.values()))) if table.columns else table.nrows
    )
    import jax.numpy as jnp

    if isinstance(scan, P.Lookup):
        # a Scan restricted to a statically-known contiguous row range:
        # the selection starts as arange(lower, upper) over the index's
        # sorted table; every downstream stage lowers unchanged
        view = _View(
            dict(table.columns),
            jnp.arange(scan.lower, scan.upper, dtype=jnp.int32),
            table.device,
            stored_len,
            # host parity: streaming a find result numbers rows 0-based
            # within the matched slice, so shift the base by -lower
            scan_base=getattr(table, "row_base", 0) - scan.lower,
            identity=(
                scan.lower == 0
                and scan.upper == table.nrows
                and stored_len == table.nrows
            ),
        )
    else:
        view = _View(
            dict(table.columns),
            jnp.arange(table.nrows, dtype=jnp.int32),
            table.device,
            stored_len,
            scan_base=getattr(table, "row_base", 0),
            # identity shortcut only for unpadded tables: padded (mesh-
            # sharded) columns must be gathered down to nrows before any
            # consumer sees them
            identity=stored_len == table.nrows,
        )

    from ..obs.span import tracer
    from ..resilience import faults
    from ..utils.observe import telemetry

    # grouping span: in a trace, the per-node stages nest under one
    # plan:execute region instead of sitting flat beside unrelated work
    with tracer.span("plan:execute", nodes=len(stages) - 1):
        # chaos site: a transient raise here fails the whole execution
        # before any stage runs; the serving tier's retry re-executes
        # the cached executable (zero recompiles)
        faults.inject("exec:device")
        for node in stages[1:]:
            with telemetry.stage(type(node).__name__, int(view.sel.shape[0])) as _t:
                view = _exec_stage(view, node)
                _t["rows_out"] = int(view.sel.shape[0])

    return view


def _exec_stage(view: "_View", node: P.PlanNode) -> "_View":
    """Execute one plan node against the view (mutating or replacing it)."""
    from ..ops import join as J

    import jax.numpy as jnp

    if isinstance(node, P.Filter):
        # device compaction: boolean gather over the selection; only the
        # compacted size crosses to host (implicit in the eager shape)
        view.sel = view.sel[_sel_mask(view, node.pred)]
    elif isinstance(node, P.Validate):
        bad = ~_sel_mask(view, node.pred)
        if bool(jnp.any(bad)):  # one scalar sync on the happy path
            i = int(jnp.argmax(bad))  # device argmax -> first failure
            rowno = view.scan_base + int(view.sel[i])
            # DEFERRED: the failure fires only if streaming actually
            # reaches row i — a consumer stopping earlier (Top's EOF,
            # a user StopPipeline) must end cleanly, like the host's
            # per-row push check (csvplus.go:300-310)
            view.deferred_error = (i, DataSourceError(rowno, CsvPlusError(node.message)))
    elif isinstance(node, P.TakeWhile) or isinstance(node, P.DropWhile):
        stop = ~_sel_mask(view, node.pred)
        # device argmax finds the first false; two O(1) scalar syncs
        if bool(jnp.any(stop)):
            cut = int(jnp.argmax(stop))
        else:
            cut = int(view.sel.shape[0])
        if isinstance(node, P.TakeWhile):
            view.sel = view.sel[:cut]  # stop permanently at first false
        else:
            view.sel = view.sel[cut:]  # yield from first false onward
    elif isinstance(node, P.Top):
        view.sel = view.sel[: node.n]
    elif isinstance(node, P.DropRows):
        view.sel = view.sel[node.n :]
    elif isinstance(node, P.SelectCols):
        _apply_select(view, node.columns)
    elif isinstance(node, P.DropCols):
        view.cols = {
            n: c for n, c in view.cols.items() if n not in set(node.columns)
        }
    elif isinstance(node, P.MapExpr):
        _apply_map(view, node.expr)
    elif isinstance(node, P.Join):
        dev_index = node.index.device_table
        if dev_index is None or not dev_index.supported:
            raise UnsupportedPlan("join build side has no packed device index")
        _check_key_cells(view, node.columns)
        stream = view.materialize()
        try:
            joined = J.join_tables(stream, dev_index, list(node.columns))
        except MissingColumnError as e:  # backstop; _check_key_cells covers it
            raise DataSourceError(0, e) from e
        join_cols_len = (
            len(next(iter(joined.columns.values()))) if joined.columns else 0
        )
        view = _View(
            dict(joined.columns),
            jnp.arange(joined.nrows, dtype=jnp.int32),
            joined.device,
            joined.nrows,
            identity=join_cols_len == joined.nrows,
        )
    elif isinstance(node, P.MultiwayJoin):
        # Fused single-pass multiway join (ISSUE 17): every dimension's
        # keys validate against the ORIGINAL stream (the rewriter's
        # fusion license proves later keys PRESENT, so the cascade could
        # not have observed different cells), then one materialize feeds
        # one expansion — no intermediate table.
        specs = []
        for index, columns in node.joins:
            dev_index = index.device_table
            if dev_index is None or not dev_index.supported:
                raise UnsupportedPlan(
                    "join build side has no packed device index"
                )
            _check_key_cells(view, columns)
            specs.append((dev_index, tuple(columns)))
        stream = view.materialize()
        try:
            joined = J.multiway_join(stream, specs)
        except MissingColumnError as e:  # backstop; _check_key_cells covers it
            raise DataSourceError(0, e) from e
        join_cols_len = (
            len(next(iter(joined.columns.values()))) if joined.columns else 0
        )
        view = _View(
            dict(joined.columns),
            jnp.arange(joined.nrows, dtype=jnp.int32),
            joined.device,
            joined.nrows,
            identity=join_cols_len == joined.nrows,
        )
    elif isinstance(node, P.FusedProbe):
        # Fused probe pass (ISSUE 19): the absorbed Filter/Map/projection
        # run executes against the SAME lazy-view code paths the staged
        # stages use (so masks, metadata updates and error sites are
        # identical), and the probe then consumes the selection directly
        # — the staged pre-join ``materialize()`` never happens
        # (``multiway_join_selected`` composes the emit gather through
        # the selection instead).
        rows_full = int(view.sel.shape[0])
        for kind, payload in node.ops:
            if kind == "filter":
                view.sel = view.sel[_sel_mask(view, payload)]
            elif kind == "map":
                _apply_map(view, payload)
            elif kind == "select":
                _apply_select(view, payload)
            elif kind == "drop":
                view.cols = {
                    n: c for n, c in view.cols.items() if n not in set(payload)
                }
            else:
                raise UnsupportedPlan(f"no device lowering for fused op {kind!r}")
        specs = []
        for index, columns in node.joins:
            dev_index = index.device_table
            if dev_index is None or not dev_index.supported:
                raise UnsupportedPlan(
                    "join build side has no packed device index"
                )
            _check_key_cells(view, columns)
            specs.append((dev_index, tuple(columns)))
        rows_selected = int(view.sel.shape[0])
        if rows_selected == 0:
            # nothing selected: delegate to the staged join, whose empty
            # folds define the result schema — materialize is free here
            # (gathering zero rows), so the fused path has nothing to win
            joined = J.multiway_join(view.materialize(), specs)
        else:
            try:
                joined = J.multiway_join_selected(
                    view.cols, view.sel, view.device, specs,
                    identity=view.identity,
                )
            except MissingColumnError as e:  # backstop; _check_key_cells covers it
                raise DataSourceError(0, e) from e
        from ..obs.joinskew import joinskew

        joinskew.on_fused(
            "+".join(",".join(di.key_columns) for di, _ in specs),
            len(specs), rows_full, rows_selected, joined.nrows,
        )
        join_cols_len = (
            len(next(iter(joined.columns.values()))) if joined.columns else 0
        )
        view = _View(
            dict(joined.columns),
            jnp.arange(joined.nrows, dtype=jnp.int32),
            joined.device,
            joined.nrows,
            identity=join_cols_len == joined.nrows,
        )
    elif isinstance(node, P.Except):
        dev_index = node.index.device_table
        if dev_index is None or not dev_index.supported:
            raise UnsupportedPlan("except build side has no packed device index")
        _check_key_cells(view, node.columns)
        # the anti-join mask needs only the KEY columns: gather just
        # those instead of materializing the whole (possibly wide) view
        key_view = _View(
            {c: view.cols[c] for c in node.columns if c in view.cols},
            view.sel,
            view.device,
            view.full_len,
            identity=view.identity,
        )
        stream = key_view.materialize()
        try:
            keep = J.except_mask(stream, dev_index, list(node.columns))
        except MissingColumnError as e:  # backstop; _check_key_cells covers it
            raise DataSourceError(0, e) from e
        # except_ passes rows through 1:1, so keep the original row space
        # (and its scan_base numbering): just narrow the selection
        view.sel = view.sel[jnp.asarray(keep)]
    else:
        raise UnsupportedPlan(f"no device lowering for {type(node).__name__}")

    return view


def _full_len(view: _View) -> int:
    return view.full_len


class _SelView:
    """Lazy column mapping for selection-narrow predicates: behaves like
    the view's column dict but hands out columns GATHERED down to the
    current selection (only for columns the predicate actually
    references)."""

    def __init__(self, cols, sel):
        self._cols = cols
        self._sel = sel
        self._cache: dict = {}

    def __contains__(self, name) -> bool:
        return name in self._cols

    def __getitem__(self, name):
        got = self._cache.get(name)
        if got is None:
            got = self._cache[name] = self._cols[name].gather(self._sel)
        return got


def _sel_mask(view: _View, pred):
    """Boolean mask aligned to ``view.sel`` (one entry per selected row)
    for Filter/Validate/TakeWhile/DropWhile — the single definition of
    predicate lowering against the current selection.

    When the selection is much narrower than the stored columns (chained
    filters narrow progressively), the mask is built over GATHERED
    sub-columns instead of all nrows — measured 15.4ms -> ~0.3ms for a
    second filter keeping 150K of 10M rows.  The gathered length pads to
    a power of two so shape-specialized mask executables (the Pallas
    fused path on TPU backends) see O(log n) distinct shapes, not one
    per selection size."""
    from ..ops.filter import UnsupportedPredicate, build_mask

    import jax.numpy as jnp

    nrows = _full_len(view)
    sel_n = int(view.sel.shape[0])
    if sel_n == 0:
        # an empty selection matches nothing, and the narrow-selection
        # pad below must never run: it would pad with row id 0 and
        # gather row 0 out of columns that may be 0-length placeholders
        # (SelectCols of a missing name over an empty selection installs
        # those) — the round-5 differential crash.  The host path is
        # vacuous on an empty stream; so are we.
        return jnp.zeros(0, dtype=bool)
    try:
        if 4 * sel_n < nrows:
            padded = 1 << max(sel_n - 1, 0).bit_length() if sel_n else 1
            sel = view.sel
            if padded != sel_n:
                # pad with row 0 (any in-range row): the tail is sliced
                # off the mask below, so its values never matter
                sel = jnp.concatenate(
                    [sel, jnp.zeros(padded - sel_n, jnp.int32)]
                )
            mask = build_mask(_SelView(view.cols, sel), padded, pred)
            return mask[:sel_n]
        mask = build_mask(view.cols, nrows, pred)
    except UnsupportedPredicate as e:
        raise UnsupportedPlan(str(e)) from e
    return jnp.take(mask, view.sel, axis=0)


def _check_key_cells(view: _View, columns) -> None:
    """Host-parity key validation for Join/Except: the host probe calls
    ``select_values`` per streamed row (csvplus.go:556,599), so the error
    is the first streamed row lacking a key cell, in the originating
    source's numbering; an empty stream never errors."""
    if view.sel.shape[0] == 0:
        return
    bad = first_missing_cell(view, columns)
    if bad is not None:
        raise DataSourceError(bad[0], MissingColumnError(bad[1]))


def first_missing_cell(view: _View, columns):
    """The first missing cell in streamed **row-major** order — exactly
    where the host path fails: the first streamed row lacking any of
    *columns*, and within that row the first such column in argument
    order.  Returns ``(source row number, column)`` (numbered by the
    originating source, ``scan_base + original row id``) or None.
    """
    import jax.numpy as jnp

    best = None  # (streamed position, column)
    for c in columns:
        col = view.cols.get(c)
        if col is None:
            pos = 0  # missing from the schema: every streamed row lacks it
        elif col.has_absent:
            # error path: syncing scalars here is fine (the pipeline is
            # about to abort with this row number)
            bad = jnp.take(col.codes, view.sel, axis=0) < 0
            if not bool(jnp.any(bad)):
                continue
            pos = int(jnp.argmax(bad))
        else:
            continue
        if best is None or pos < best[0]:
            best = (pos, c)
            if pos == 0:
                break  # nothing can precede streamed row 0
    if best is None:
        return None
    pos, c = best
    return view.scan_base + int(view.sel[pos]), c


def _apply_select(view: _View, columns) -> None:
    """SelectCols with host-parity errors: the host path raises at the
    first *streamed* row lacking the cell (csvplus.go:517-519 via
    Row.Select), so an empty selection never errors, and the error
    carries the originating source's row number of that row."""
    from .table import StringColumn as _SC
    import numpy as _np

    if view.sel.shape[0] == 0:
        view.cols = {
            c: view.cols.get(
                c,
                _SC(_np.empty(0, dtype=_np.str_), jnp_empty_i32(view.device)),
            )
            for c in columns
        }
        return
    bad = first_missing_cell(view, columns)
    if bad is not None:
        raise DataSourceError(bad[0], MissingColumnError(bad[1]))
    view.cols = {c: view.cols[c] for c in columns}


def jnp_empty_i32(device):
    import jax.numpy as jnp

    return jnp.empty(0, dtype=jnp.int32)


def _apply_map(view: _View, expr) -> None:
    from ..exprs import Rename, SetValue, Update

    if isinstance(expr, Update):
        for e in expr.exprs:
            _apply_map(view, e)
        return
    if isinstance(expr, SetValue):
        n = _full_len(view)
        ref = next(iter(view.cols.values()), None)
        if ref is not None and getattr(ref.storage, "sharding", None) is not None:
            # match the existing columns' (possibly mesh-sharded) layout,
            # or mixing the constant into jitted ops crashes on devices
            import jax as _jax

            codes = _jax.device_put(
                np.zeros(n, dtype=np.int32), ref.storage.sharding
            )
            view.cols[expr.column] = StringColumn(
                np.asarray([expr.value.encode("utf-8")], dtype="S"), codes
            )
        else:
            view.cols[expr.column] = StringColumn.constant(
                expr.value, n, view.device
            )
        return
    if isinstance(expr, Rename):
        # sequential pop/overwrite, matching the host expr exactly
        # (exprs.Rename: row[new] = row.pop(old) per mapping entry, so a
        # rename onto an existing name overwrites it, chained renames
        # {'a':'b','b':'c'} cascade, and a row WITHOUT the old cell keeps
        # its existing new-column value)
        from .table import merge_with_fallback

        for old, new in expr.mapping.items():
            if old in view.cols:
                moved = view.cols.pop(old)
                existing = view.cols.pop(new, None)
                if existing is not None and moved.has_absent:
                    moved = merge_with_fallback(moved, existing)
                view.cols[new] = moved
        return
    raise UnsupportedPlan(f"cannot lower map expression {expr!r} to device")


def try_execute_plan(root: Optional[P.PlanNode]) -> Optional[List[Row]]:
    """Execute the plan to host Rows, or None when not device-executable.

    A failing terminal Validate raises here: a full materialization
    consumes every row, so the host stream would always have reached the
    first invalid row."""
    if root is None:
        return None
    try:
        table = execute_plan(root)
    except UnsupportedPlan:
        return None
    de = getattr(table, "deferred_error", None)
    if de is not None:
        raise de[1]
    return table.to_rows()


def device_table_for(src) -> "DeviceTable | None":
    """Execute *src*'s device plan to a table, or None when there is no
    plan / it is unsupported.  An unsupported outcome is remembered on
    the source so sinks and the runner never execute the same device
    prefix twice.  (If an index gains a device copy AFTER the first
    attempt, the source keeps using its host fallback — correct, merely
    un-accelerated.)"""
    plan = getattr(src, "plan", None)
    if plan is None or getattr(src, "_plan_unsupported", False):
        return None
    try:
        table = execute_plan(plan)
    except UnsupportedPlan:
        try:
            src._plan_unsupported = True
        except AttributeError:
            pass
        return None
    if getattr(table, "deferred_error", None) is not None:
        # a failing terminal Validate: sinks must replay the host
        # streaming path for exact write-then-remove semantics.  Data-
        # dependent, so do NOT memoize unsupported.
        return None
    return table


def plan_runner(root: P.PlanNode, fallback=None, owner=None):
    """A DataSource driver that executes *root* on device and streams the
    decoded rows; falls back to *fallback* when the plan is unsupported
    (memoized via *owner*, see :func:`device_table_for`)."""

    def run(fn) -> None:
        if owner is not None and getattr(owner, "_plan_unsupported", False):
            fallback(fn)
            return
        try:
            table = execute_plan(root)
        except UnsupportedPlan:
            if owner is not None:
                try:
                    owner._plan_unsupported = True
                except AttributeError:
                    pass
            if fallback is None:
                raise
            fallback(fn)
            return
        from ..source import iterate

        de = getattr(table, "deferred_error", None)
        if de is not None:
            # stream up to the first invalid row; the error fires only
            # if the consumer is still listening when we reach it
            k, err = de
            delivered = 0

            def counting(row):
                nonlocal delivered
                fn(row)
                delivered += 1

            # decode ONLY the rows before the failure point
            iterate(table.to_rows(np.arange(k)), counting, clone=False)
            if delivered == k:  # consumer did not stop early
                raise err
            return
        # rows are freshly decoded per run, so skip the defensive clone
        iterate(table.to_rows(), fn, clone=False)

    return run
