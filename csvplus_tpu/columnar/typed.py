"""Typed numeric value lanes: the affix-int32 column.

SURVEY §7 M2 calls for "typed columns where parseable"; the reference's
typed getters (ValueAsInt, /root/reference/csvplus.go:151-171) are the
spec anchor for which strings count as numeric.  A column qualifies when
every cell is ``prefix + canonical int32 suffix`` — one constant prefix
for the whole column, suffix in canonical decimal form ("0" or
[1-9][0-9]*, sign only with an empty prefix) so that parse -> format
round-trips BITWISE.  This covers pure integers ("42", "-7") and the
ubiquitous prefixed-id shape ("o123", "c45"); leading zeros simply join
the prefix ("o007" = "o00" + 7).

Why: a 100M-unique id column pays the full dictionary-encode machinery
(device sort-rank or host hash/sort per chunk, lane packing, deferred
union) for values that are really just integers.  As an
:class:`IntColumn` the same column is ONE int32 device array: ingest is
a C++ parse + upload, gathers/joins carry 4 bytes/row, and decode is a
C++ itoa.  The round-4 north star spent 88.2s of 109.2s in ingest on
exactly this (VERDICT r4 next #2).

Representation contract:

* ``values``: int32[n] on device — the *storage* array (the typed
  analogue of ``StringColumn.codes``); row order == source order.
* ``prefix``: bytes, constant for the column.
* typed columns NEVER hold absent cells (CSV cells always exist; ops
  that would introduce absence demote first), so ``has_absent`` is
  always False and sharding pads use :data:`PAD_VALUE` (INT32_MIN —
  pad rows live beyond ``nrows``, outside every selection, and the
  sentinel can never collide with a real cell; see its comment).

Anything that needs dictionary semantics (code order == lex order:
sorts, index builds, packed join keys, persistence, point lookups)
triggers :meth:`_demote` — a one-time conversion to an equivalent
``StringColumn`` (device unique over the values, C++ format of the
UNIQUE set only, lex argsort permutation, device code remap).  Demotion
is the explicit slow path and is telemetry-visible; the hot paths
(ingest, equality probes, payload gathers, decode, checksums, CSV/JSON
encode) never demote.
"""

from __future__ import annotations

import threading
from typing import List, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..obs.recompile import register_kernel


# Sharding-pad sentinel for typed value lanes: INT32_MIN can never be a
# real cell (csv_pack_int32 bounds |v| <= INT32_MAX), so pad rows are
# unambiguous — they translate to -2 (the StringColumn pad identity),
# never enter a demoted dictionary, and can't alias a real "prefix+0"
# key the way a 0-pad would (review r5 finding).
PAD_VALUE = np.int32(np.iinfo(np.int32).min)


class IntColumn:
    """One affix-int32 typed column (see module docstring)."""

    kind = "int"

    def __init__(
        self,
        prefix: bytes,
        values: jax.Array,  # int32[n] on device
        _demoted: "Optional[object]" = None,
    ):
        self.prefix = prefix
        self.values = values
        self._demoted = _demoted  # cached StringColumn after demotion
        self._demote_lock = threading.Lock()

    # ---- kind-agnostic storage protocol (shared with StringColumn) ----

    @property
    def storage(self) -> jax.Array:
        """The row-indexed device array (the typed ``codes`` analogue)."""
        return self.values

    def with_storage(self, values: jax.Array) -> "IntColumn":
        return IntColumn(self.prefix, values)

    def gather(self, sel, codes=None) -> "IntColumn":
        src = self.values if codes is None else codes
        idx = jnp.asarray(sel, dtype=jnp.int32)
        return IntColumn(self.prefix, jnp.take(src, idx, axis=0))

    def __len__(self) -> int:
        return int(self.values.shape[0])

    @property
    def has_absent(self) -> bool:
        return False  # typed columns never hold absent cells (module doc)

    @property
    def dev_dictionary(self):
        return None  # no lane dictionary; value lanes ARE the storage

    def _ensure_sorted_lanes(self) -> None:
        return None  # no deferred lane union to settle

    # ---- decode fast paths (no demotion) ----

    def _prefix_str(self) -> str:
        return self.prefix.decode("utf-8")

    def _format_host(self, values: np.ndarray) -> np.ndarray:
        return format_affix(self.prefix, values)

    def formatted_host(self) -> np.ndarray:
        """All rows formatted to 'S' bytes (sink fast paths)."""
        return self._format_host(np.asarray(self.values))

    def formatted_str(self) -> np.ndarray:
        """All rows formatted as a numpy str array."""
        digits = np.asarray(self.values).astype(np.str_)
        p = self._prefix_str()
        return np.char.add(p, digits) if p else digits

    def decode(self) -> List[Optional[str]]:
        return self.formatted_str().tolist()

    def values_host(self) -> np.ndarray:
        """Host mirror of the value lanes (cached — point-lookup decodes
        then cost zero device dispatches, like codes_host)."""
        got = getattr(self, "_values_host", None)
        if got is None:
            got = self._values_host = np.asarray(self.values)
        return got

    def decode_slice(self, lo: int, hi: int) -> List[Optional[str]]:
        digits = self.values_host()[lo:hi].astype(np.str_)
        p = self._prefix_str()
        return (np.char.add(p, digits) if p else digits).tolist()

    def decode_take(self, idx: np.ndarray) -> List[Optional[str]]:
        """Arbitrary-index decode off the host mirror (the batched
        lookup engine's gather-then-decode path)."""
        digits = self.values_host()[idx].astype(np.str_)
        p = self._prefix_str()
        return (np.char.add(p, digits) if p else digits).tolist()

    def equality_term(self, value: str):
        """The int32 target *value* compares equal to on this column, or
        None when no cell can ever equal it (wrong prefix / non-canonical
        suffix — typed cells only ever hold canonical forms)."""
        try:
            raw = value.encode("utf-8")
        except (UnicodeEncodeError, AttributeError):
            return None
        if not raw.startswith(self.prefix):
            return None
        digits = raw[len(self.prefix) :]
        body = digits[1:] if (not self.prefix and digits[:1] == b"-") else digits
        if not body.isdigit():
            return None
        if body != b"0" and body[:1] == b"0":
            return None  # non-canonical: cells never hold leading zeros
        try:
            v = int(digits)
        except ValueError:
            return None
        if not (-(2**31) < v < 2**31):
            return None
        if digits[:1] == b"-" and v == 0:
            return None  # "-0" never stored
        return v

    # ---- dictionary protocol via demotion (the explicit slow path) ----

    def _demote(self):
        """The equivalent StringColumn (cached; thread-safe).  Cost:
        device unique over the values + host format/argsort of the
        UNIQUE set + one device remap gather."""
        got = self._demoted
        if got is not None:
            return got
        with self._demote_lock:
            if self._demoted is not None:
                return self._demoted
            from ..utils.observe import telemetry
            from .table import StringColumn

            with telemetry.stage("typed:demote", int(self.values.shape[0])):
                u = jnp.unique(self.values)  # device sort+dedup
                uu = np.asarray(u)
                # sharding pads (PAD_VALUE sorts first) never enter the
                # dictionary; their rows code as -2 below
                has_pad = bool(uu.size) and uu[0] == PAD_VALUE
                if has_pad:
                    uu = uu[1:]
                    u = u[1:]
                strs = self._format_host(uu)
                order = np.argsort(strs, kind="stable")  # numeric -> lex
                dictionary = strs[order]
                if uu.size == 0:  # empty (or all-pad) column
                    codes = jnp.full(
                        self.values.shape, -2 if has_pad else -1, jnp.int32
                    )
                else:
                    code_of = np.empty(uu.shape[0], dtype=np.int32)
                    code_of[order] = np.arange(uu.shape[0], dtype=np.int32)
                    # numeric rank per row, then numeric-slot -> lex code
                    pos = jnp.searchsorted(u, self.values)
                    pos = jnp.minimum(pos, int(uu.shape[0]) - 1)
                    codes = jnp.take(jax.device_put(code_of), pos, axis=0)
                    if has_pad:
                        codes = jnp.where(
                            self.values == jnp.int32(PAD_VALUE),
                            jnp.int32(-2),
                            codes,
                        )
                self._demoted = StringColumn(
                    dictionary, codes, _has_absent=False if not has_pad else None
                )
        return self._demoted

    @property
    def codes(self) -> jax.Array:
        return self._demote().codes

    @property
    def dictionary(self) -> np.ndarray:
        return self._demote().dictionary

    def dictionary_str(self) -> np.ndarray:
        return self._demote().dictionary_str()

    @property
    def dict_size(self) -> int:
        return self._demote().dict_size

    def codes_host(self) -> np.ndarray:
        return self._demote().codes_host()

    def find_code(self, value: str) -> int:
        return self._demote().find_code(value)

    def find_codes(self, values) -> np.ndarray:
        return self._demote().find_codes(values)

    def with_codes(self, codes, dev_dict_sorted=None):
        return self._demote().with_codes(codes, dev_dict_sorted)

    def decode_codes(self, codes: np.ndarray) -> List[Optional[str]]:
        return self._demote().decode_codes(codes)

    # dense translation tables are built when the build-side value range
    # is at most this multiple of its distinct count (and > 0 entries):
    # one O(range) int32 array turns the per-row translation into a
    # single gather instead of a ~log2(U)-round searchsorted
    DENSE_RANGE_FACTOR = 16
    DENSE_RANGE_MAX = 1 << 24  # 64MB of int32 at the cap

    @staticmethod
    def _build_translation(vals: np.ndarray, cand: np.ndarray):
        """Device translation state from (values, codes) of the build
        side: ('dense', base, table) when the value range is compact,
        else ('sorted', sorted_vals, code_of)."""
        if vals.size == 0:
            return ("sorted", jax.device_put(vals), jax.device_put(cand))
        lo, hi = int(vals.min()), int(vals.max())
        rng = hi - lo + 1
        if rng <= IntColumn.DENSE_RANGE_MAX and rng <= max(
            vals.size * IntColumn.DENSE_RANGE_FACTOR, 1024
        ):
            table = np.full(rng, -1, dtype=np.int32)
            table[vals - lo] = cand
            return ("dense", lo, jax.device_put(table))
        order = np.argsort(vals, kind="stable")
        return (
            "sorted",
            jax.device_put(vals[order]),
            jax.device_put(cand[order]),
        )

    def _translate_by_values(self, state) -> jax.Array:
        """Rows translated through a :meth:`_build_translation` state;
        miss -> -1, sharding pads -> -2 (the same negative-code identity
        the StringColumn translation preserves).

        Each variant is ONE jitted kernel (r6 warm-join recovery): the
        translation runs on every probe execution, and the previous
        eager form paid ~6 unfused device passes over the full probe
        length per key column per join — measured 76.6ms vs 10.6ms
        fused at 10M rows.  The dense base offset rides as a traced
        scalar so distinct build sides share one executable."""
        if state[0] == "dense":
            _, lo, table = state
            return _translate_dense_kernel(self.values, jnp.int32(lo), table)
        _, sorted_vals, code_of = state
        if int(sorted_vals.shape[0]) == 0:
            return _translate_empty_kernel(self.values)
        return _translate_sorted_kernel(self.values, sorted_vals, code_of)

    def renumbered_to(self, other_dictionary: np.ndarray) -> jax.Array:
        """Translate rows into *other_dictionary*'s code space without
        demoting SELF: parse the (small) dictionary numerically and
        searchsorted the value lanes against it — O(U) host +
        O(n log U) device, vs. the O(n)-format demotion."""
        cand, vals = parse_affix_dictionary(other_dictionary, self.prefix)
        return self._translate_by_values(self._build_translation(vals, cand))

    def renumbered_to_col(self, other) -> jax.Array:
        """Rows translated into *other*'s code space (the probe-side join
        translation).  ``other`` may be a StringColumn (its dictionary is
        parsed numerically — no demotion of SELF, the 100M-row probe
        stays value lanes) or another IntColumn (demoted first: build
        sides are index tables whose key columns already hold code
        semantics).  The parsed translation table is cached on *other*
        per prefix, so repeated probes of the same build side pay the
        host parse once."""
        if isinstance(other, IntColumn):
            other = other._demote()
        cache = getattr(other, "_affix_trans_cache", None)
        if cache is None:
            cache = other._affix_trans_cache = {}
        hit = cache.get(self.prefix)
        if hit is None:
            cand, vals = parse_affix_dictionary(other.dictionary, self.prefix)
            hit = cache[self.prefix] = self._build_translation(vals, cand)
        return self._translate_by_values(hit)


@register_kernel("typed.translate_dense")
@jax.jit
def _translate_dense_kernel(values, lo, table):
    is_pad = values == jnp.int32(PAD_VALUE)
    # pads masked BEFORE the subtraction: PAD_VALUE - lo wraps int32 and
    # could land inside the dense range
    safe = jnp.where(is_pad, lo, values)
    idx = safe - lo
    ok = (idx >= 0) & (idx < table.shape[0]) & ~is_pad
    got = jnp.take(table, jnp.clip(idx, 0, table.shape[0] - 1), axis=0)
    return jnp.where(ok, got, jnp.where(is_pad, jnp.int32(-2), jnp.int32(-1)))


@register_kernel("typed.translate_sorted")
@jax.jit
def _translate_sorted_kernel(values, sorted_vals, code_of):
    is_pad = values == jnp.int32(PAD_VALUE)
    pos = jnp.searchsorted(sorted_vals, values)
    pos = jnp.minimum(pos, sorted_vals.shape[0] - 1)
    hit = (jnp.take(sorted_vals, pos, axis=0) == values) & ~is_pad
    return jnp.where(
        hit,
        jnp.take(code_of, pos, axis=0),
        jnp.where(is_pad, jnp.int32(-2), jnp.int32(-1)),
    )


@register_kernel("typed.translate_empty")
@jax.jit
def _translate_empty_kernel(values):
    return jnp.where(
        values == jnp.int32(PAD_VALUE), jnp.int32(-2), jnp.int32(-1)
    )


def format_affix(prefix: bytes, values: np.ndarray) -> np.ndarray:
    """'S' bytes array of ``prefix + decimal(value)`` per entry — C++
    itoa when available, numpy otherwise; byte-exact either way (the
    inverse of the native csv_pack_int32 parse)."""
    from ..native.scanner import format_i32_native

    values = np.ascontiguousarray(values, dtype=np.int32)
    plen = len(prefix)
    native = format_i32_native(values)
    if native is not None:
        mat, _lens = native
        width = plen + mat.shape[1]
        out = np.zeros((values.shape[0], width), dtype=np.uint8)
        if plen:
            out[:, :plen] = np.frombuffer(prefix, dtype=np.uint8)
        out[:, plen:] = mat
        return np.ascontiguousarray(out).view(f"S{width}").ravel()
    digits = values.astype(np.str_)  # numpy fallback: canonical '%d'
    if plen:
        digits = np.char.add(prefix.decode("utf-8"), digits)
    return np.char.encode(digits, "utf-8")


def parse_affix_dictionary(d: np.ndarray, prefix: bytes):
    """Which entries of the 'S' dictionary *d* have the affix form
    ``prefix + canonical int32``?  Returns (entry indices int32[],
    values int32[]), fully vectorized over the fixed-width byte matrix
    (a Python per-entry loop here would run per join build)."""
    U = d.shape[0]
    plen = len(prefix)
    if U == 0:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    width = d.dtype.itemsize
    lens = np.char.str_len(d).astype(np.int32)
    if width < plen + 1:
        return np.empty(0, np.int32), np.empty(0, np.int32)
    mat = np.frombuffer(
        np.ascontiguousarray(d).tobytes(), dtype=np.uint8
    ).reshape(U, width)
    ok = lens > plen
    if plen:
        pref = np.frombuffer(prefix, dtype=np.uint8)
        ok &= (mat[:, :plen] == pref).all(axis=1)
    # optional sign (empty prefix only)
    neg = np.zeros(U, dtype=bool)
    if plen == 0:
        neg = mat[:, 0] == ord("-")
        ok &= ~neg | (lens > 1)
    digit_start = plen + neg.astype(np.int32)
    sfx_len = lens - digit_start
    ok &= (sfx_len >= 1) & (sfx_len <= 10)
    # suffix region all digits
    colidx = np.arange(width, dtype=np.int32)
    in_sfx = (colidx >= digit_start[:, None]) & (colidx < lens[:, None])
    is_digit = (mat >= ord("0")) & (mat <= ord("9"))
    ok &= np.where(in_sfx, is_digit, True).all(axis=1)
    # canonical: no leading zero unless the suffix IS "0"
    first = mat[np.arange(U), np.minimum(digit_start, width - 1)]
    ok &= (first != ord("0")) | (sfx_len == 1)
    if not ok.any():
        return np.empty(0, np.int32), np.empty(0, np.int32)
    # positional decimal parse over the masked digit region
    exp = (lens[:, None] - 1 - colidx).astype(np.int64)
    w = np.where(in_sfx, 10 ** np.clip(exp, 0, 9), 0)
    vals = ((mat.astype(np.int64) - ord("0")) * w).sum(axis=1)
    vals = np.where(neg, -vals, vals)
    ok &= (vals < 2**31) & (vals > -(2**31)) & ~(neg & (vals == 0))
    cand = np.flatnonzero(ok).astype(np.int32)
    return cand, vals[ok].astype(np.int32)
