"""Native (C++) runtime components.

The reference has zero native code (SURVEY.md §2), so this layer's
obligation comes from the rebuild's own needs: the host side of the
columnar ingest path must keep up with the device side.  ``scanner``
provides a single-pass zero-copy CSV chunk scanner (g++-compiled, loaded
via ctypes) that is differential-tested against the pure-Python
specification in :mod:`csvplus_tpu.csvio`.
"""
