"""ctypes binding + build for the native CSV scanner.

The shared object is compiled on first use with g++ -O3 into the package
directory (cached by source mtime).  If the toolchain is unavailable the
import raises and callers fall back to the Python parser — behavior is
identical either way (differential-tested), only throughput differs.

``read_columns_native`` is the columnar ingest fast path used by
:func:`csvplus_tpu.columnar.ingest.reader_to_device`: it parses the whole
file in one native pass and materializes Python strings ONLY for the
columns the header policy selects.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..csvio import ERR_BARE_QUOTE, ERR_FIELD_COUNT, ERR_QUOTE
from ..errors import DataSourceError, map_error
from ..resilience import faults
from ..utils.env import env_int as _env_int
from ..utils.env import env_str as _env_str

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "scanner.cpp")
# CSVPLUS_NATIVE_SO picks an alternate artifact name so an instrumented
# build (e.g. `make asan`) neither reuses nor clobbers the -O3 cache;
# CSVPLUS_NATIVE_CFLAGS appends extra g++ flags (space-split) to it.
_SO = os.path.join(_HERE, _env_str("CSVPLUS_NATIVE_SO", "_scanner.so"))
_lock = threading.Lock()
_lib = None

_ERR_MSG = {-1: ERR_BARE_QUOTE, -2: ERR_QUOTE, -3: "native scanner overflow"}


def _build() -> str:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    tmp = f"{_SO}.{os.getpid()}.tmp"  # per-process: no concurrent clobber
    extra = (_env_str("CSVPLUS_NATIVE_CFLAGS", "") or "").split()
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17"]
            + extra
            + ["-o", tmp, _SRC],
            check=True,
            capture_output=True,
        )
    except (OSError, subprocess.CalledProcessError) as e:
        # surface as ImportError so ingest falls back to the Python parser
        raise ImportError(f"native scanner build failed: {e}") from e
    os.replace(tmp, _SO)
    return _SO


def _load():
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        try:
            lib = ctypes.CDLL(_build())
        except OSError as e:
            # stale/foreign cached .so (other platform, corrupt build):
            # rebuild once from source, else surface as ImportError so
            # callers fall back to the Python parser
            try:
                os.remove(_SO)
                lib = ctypes.CDLL(_build())
            except (OSError, ImportError) as e2:
                raise ImportError(f"native scanner unusable: {e2}") from e
        lib.csv_count_bounds.restype = ctypes.c_int64
        lib.csv_count_bounds.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.c_char,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_scan.restype = ctypes.c_int64
        lib.csv_scan.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.c_char,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_char_p,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_pack_fields.restype = None
        lib.csv_pack_fields.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.csv_pack_fields_u64.restype = None
        lib.csv_pack_fields_u64.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.csv_encode_hash_u64.restype = ctypes.c_int64
        lib.csv_encode_hash_u64.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.csv_encode_hash_u64x2.restype = ctypes.c_int64
        lib.csv_encode_hash_u64x2.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        lib.csv_scatter_fields.restype = None
        lib.csv_scatter_fields.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.c_void_p,
        ]
        lib.csv_u64_to_bytes.restype = None
        lib.csv_u64_to_bytes.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
        ]
        lib.csv_scan_simple.restype = ctypes.c_int64
        lib.csv_scan_simple.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.csv_pack_int32.restype = ctypes.c_int64
        lib.csv_pack_int32.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.csv_pack_int32_strided.restype = ctypes.c_int64
        lib.csv_pack_int32_strided.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.c_int64,
            ctypes.c_void_p,
        ]
        lib.csv_scan_parse_i32.restype = ctypes.c_int64
        lib.csv_scan_parse_i32.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_char,
            ctypes.c_int64,
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_int64,
        ]
        lib.csv_format_i32.restype = None
        lib.csv_format_i32.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int64,
            ctypes.c_int32,
            ctypes.c_void_p,
            ctypes.c_void_p,
        ]
        _lib = lib
        return lib


def scan_bytes(
    data: bytes,
    delimiter: str = ",",
    comment: Optional[str] = None,
    lazy_quotes: bool = False,
    offset: int = 0,
    length: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, bytes]:
    """Native scan: (field_starts, field_lens, rec_counts, scratch).

    field_starts < 0 index the scratch buffer at -(start+1); record
    ordinals for errors are 1-based like the reference's row numbers.
    ``offset``/``length`` scan a sub-range of *data* with zero copies
    (the parallel chunker's path); returned starts are range-relative.
    """
    lib = _load()
    delim_b = delimiter.encode("utf-8")
    if len(delim_b) != 1:
        # the native scanners take the delimiter as a single C char;
        # callers gate multi-byte delimiters onto the Python path, so
        # reaching here is a programming error — fail loudly instead of
        # letting ctypes raise an opaque TypeError (CTYPES001)
        raise ValueError(f"native scan requires a 1-byte delimiter, got {delimiter!r}")
    n = len(data) - offset if length is None else length
    base = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value + offset
    max_fields = ctypes.c_int64(0)
    max_records = ctypes.c_int64(0)
    flags = ctypes.c_int64(0)
    comment_b = (comment or "\x00").encode("utf-8")[0:1]
    lib.csv_count_bounds(
        base,
        n,
        delim_b,
        comment_b,
        ctypes.byref(max_fields),
        ctypes.byref(max_records),
        ctypes.byref(flags),
    )
    mf, mr = max_fields.value, max_records.value
    starts = np.empty(mf, dtype=np.int64)
    lens = np.empty(mf, dtype=np.int32)
    counts = np.empty(mr, dtype=np.int32)

    # SIMPLE fast path: no quote / CR / comment bytes in range (flags
    # from the same single counting pass) — the SWAR tokenizer applies
    # (~4x the state machine's throughput), no scratch buffer exists,
    # and no parse error is possible
    # a multi-byte comment can't be honored by either native scanner
    # (library callers gate it upstream); keep the old direct-call
    # semantics: it does NOT disqualify the simple path
    no_comment = (
        comment is None
        or len(comment.encode("utf-8")) != 1
        or (flags.value & 4) == 0
    )
    if (flags.value & 3) == 0 and no_comment:
        nrec = ctypes.c_int64(0)
        total = int(
            lib.csv_scan_simple(
                base,
                n,
                delim_b,
                starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                ctypes.byref(nrec),
            )
        )
        return starts[:total], lens[:total], counts[: nrec.value], b""

    # NB: the `data` local keeps the bytes object alive (and its base
    # address valid) for the duration of both native calls below
    scratch = ctypes.create_string_buffer(max(n, 1))
    scratch_used = ctypes.c_int64(0)
    err_record = ctypes.c_int64(0)

    rc = lib.csv_scan(
        base,
        n,
        delim_b,
        (comment or "\x00").encode("utf-8")[0:1],
        # multi-byte comments are ignored CONSISTENTLY across both native
        # paths: the simple tokenizer can't honor them, so the full
        # machine must not honor a truncated first byte either (library
        # callers gate multi-byte comments upstream)
        1 if comment and len(comment.encode("utf-8")) == 1 else 0,
        1 if lazy_quotes else 0,
        0,  # trim handled by the Python fallback (unicode semantics)
        starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        scratch,
        len(scratch),
        ctypes.byref(scratch_used),
        mf,
        mr,
        ctypes.byref(err_record),
    )
    if rc < 0:
        raise DataSourceError(int(err_record.value), _ERR_MSG[int(rc)])
    nrec = int(err_record.value)
    # nfields = rc; trim arrays
    total = int(rc)
    return starts[:total], lens[:total], counts[:nrec], scratch.raw[: scratch_used.value]


_PARALLEL_MIN_BYTES = 8 << 20  # files below this parse fine in one pass


def scan_bytes_parallel(
    data: bytes,
    delimiter: str = ",",
    comment: Optional[str] = None,
    lazy_quotes: bool = False,
    n_threads: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, bytes]:
    """Multi-threaded chunk scan for large QUOTE-FREE files.

    The host-ingest-parallelism component from SURVEY.md §2: the byte
    range is split at newline boundaries and each chunk runs through the
    native scanner concurrently (ctypes releases the GIL).  Chunking at
    newlines is only unambiguous when the file contains no quote
    character — a quoted field could span lines — so quoted files take
    the single-pass scan.  Quote-free chunks cannot raise parse errors
    and never use the scratch buffer, which keeps the merge a pure
    offset-shifted concatenation.
    """
    n = len(data)
    # the thread cap is env-tunable so intra-chunk scan threads and
    # ingest chunk workers (CSVPLUS_INGEST_WORKERS) can be balanced on
    # the bench host instead of both oversubscribing every core
    cap = _env_int("CSVPLUS_SCAN_THREADS", 16)
    k = min(n_threads or os.cpu_count() or 1, cap)
    if n < _PARALLEL_MIN_BYTES or k < 2 or b'"' in data:
        return scan_bytes(data, delimiter, comment, lazy_quotes)

    # newline-aligned chunk bounds
    bounds = [0]
    for i in range(1, k):
        target = i * n // k
        pos = data.find(b"\n", target)
        bounds.append(n if pos < 0 else pos + 1)
    bounds.append(n)
    bounds = sorted(set(bounds))

    from concurrent.futures import ThreadPoolExecutor

    def scan_chunk(lo: int, hi: int):
        # zero-copy: scan [lo, hi) of the shared buffer in place
        return scan_bytes(
            data, delimiter, comment, lazy_quotes, offset=lo, length=hi - lo
        )

    with ThreadPoolExecutor(max_workers=len(bounds) - 1) as pool:
        parts = list(
            pool.map(lambda b: scan_chunk(*b), zip(bounds[:-1], bounds[1:]))
        )

    starts = np.concatenate(
        [p[0] + lo for p, lo in zip(parts, bounds[:-1])]
    ) if parts else np.empty(0, np.int64)
    lens = np.concatenate([p[1] for p in parts]) if parts else np.empty(0, np.int32)
    counts = np.concatenate([p[2] for p in parts]) if parts else np.empty(0, np.int32)
    return starts, lens, counts, b""


def _field_str(data: bytes, scratch: bytes, start: int, length: int) -> str:
    if start < 0:
        s = -start - 1
        return scratch[s : s + length].decode("utf-8")
    return data[start : start + length].decode("utf-8")


_VEC_MAX_FIELD_LEN = 256  # longer fields fall back to per-field strings
_PACK_THREADS_MIN_N = 200_000  # below this a single pack call is faster
_pack_pool = None
_pack_pool_lock = threading.Lock()


def _pack_pool_get():
    """Shared worker pool for the native field pack (row-range slices).
    Distinct from any column-level pool a caller may run, so nested use
    cannot deadlock (pack tasks never submit further pack tasks)."""
    global _pack_pool
    if _pack_pool is None:
        with _pack_pool_lock:
            if _pack_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _pack_pool = ThreadPoolExecutor(
                    max_workers=min(os.cpu_count() or 1, 8),
                    thread_name_prefix="csvplus-pack",
                )
    return _pack_pool


def _pack_fields_native(
    combined: np.ndarray, starts: np.ndarray, lens: np.ndarray, width: int,
    u64: bool = False,
):
    """Gather (start, len) fields into NUL-padded fixed-width rows via the
    C++ pack (one memcpy per field, GIL released, threaded over row
    ranges) — or None when the native library is unavailable.

    ``u64=True`` packs <=8-byte fields big-endian straight into native
    uint64 values (integer order == padded byte order)."""
    try:
        lib = _load()
    except ImportError:
        return None
    n = int(starts.shape[0])
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    out = (
        np.empty(n, dtype=np.uint64) if u64 else np.empty((n, width), np.uint8)
    )
    if n == 0:
        return out
    base = combined.ctypes.data

    def run(lo: int, hi: int) -> None:
        sp = starts[lo:hi].ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        lp = lens[lo:hi].ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
        if u64:
            lib.csv_pack_fields_u64(base, sp, lp, hi - lo, out[lo:hi].ctypes.data)
        else:
            lib.csv_pack_fields(
                base, sp, lp, hi - lo, width, out[lo:hi].ctypes.data
            )

    k = min(os.cpu_count() or 1, 8)
    if n >= _PACK_THREADS_MIN_N and k >= 2:
        # single-core boxes skip straight to one call: pool hops only
        # add GIL churn there
        bounds = [n * i // k for i in range(k + 1)]
        list(
            _pack_pool_get().map(
                lambda b: run(*b), zip(bounds[:-1], bounds[1:])
            )
        )
    else:
        run(0, n)
    return out


_PREFIX_CAP = 24  # affix prefixes longer than this fall back to dictionary


def _prefix_marshal(prefix: "bytes | None"):
    """(ctypes prefix buffer, c_int64 length) for the pack entry points;
    None when the prefix exceeds the cap.  Length -1 = derive."""
    pbuf = ctypes.create_string_buffer(_PREFIX_CAP)
    if prefix is None:
        return pbuf, ctypes.c_int64(-1)
    if len(prefix) > _PREFIX_CAP:
        return None
    pbuf.raw = prefix + b"\x00" * (_PREFIX_CAP - len(prefix))
    return pbuf, ctypes.c_int64(len(prefix))


def pack_int32_native(
    combined: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    prefix: "bytes | None",
):
    """Parse a column's fields as ``prefix + canonical int32`` (typed
    value lanes).  Returns ``(prefix, int32 values)`` when every field
    conforms, else None.  ``prefix=None`` derives the prefix from the
    first field (first chunk); later chunks pass the established prefix
    so a drifting column is rejected.  GIL released in the C++ parse,
    threaded over row ranges like the field pack."""
    try:
        lib = _load()
    except ImportError:
        return None
    n = int(starts.shape[0])
    if n == 0:
        return None  # nothing to derive a prefix from; let dictionary run
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    out = np.empty(n, dtype=np.int32)
    base = combined.ctypes.data
    marshalled = _prefix_marshal(prefix)
    if marshalled is None:
        return None
    pbuf, plen = marshalled

    def run(lo: int, hi: int) -> int:
        return int(
            lib.csv_pack_int32(
                base,
                starts[lo:hi].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                lens[lo:hi].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                hi - lo,
                pbuf,
                ctypes.byref(plen),
                _PREFIX_CAP,
                out[lo:hi].ctypes.data,
            )
        )

    if plen.value < 0:
        # derive the prefix from field 0 alone so the threaded ranges
        # below all verify against one established prefix
        if not run(0, 1):
            return None
    k = min(os.cpu_count() or 1, 8)
    if n >= _PACK_THREADS_MIN_N and k >= 2:
        bounds = [n * i // k for i in range(k + 1)]
        oks = list(
            _pack_pool_get().map(lambda b: run(*b), zip(bounds[:-1], bounds[1:]))
        )
        if not all(oks):
            return None
    else:
        if not run(0, n):
            return None
    return bytes(pbuf.raw[: plen.value]), out


def pack_int32_strided_native(
    combined: np.ndarray,
    starts: np.ndarray,
    lens: np.ndarray,
    n_records: int,
    stride: int,
    off: int,
    prefix: "bytes | None",
):
    """Strided typed-lane parse for RECTANGULAR chunks: column *off* of
    record i is flat field ``off + i*stride`` — no per-column position
    gather.  Same contract as :func:`pack_int32_native`."""
    try:
        lib = _load()
    except ImportError:
        return None
    if n_records == 0:
        return None
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    lens = np.ascontiguousarray(lens, dtype=np.int32)
    out = np.empty(n_records, dtype=np.int32)
    marshalled = _prefix_marshal(prefix)
    if marshalled is None:
        return None
    pbuf, plen = marshalled
    ok = int(
        lib.csv_pack_int32_strided(
            combined.ctypes.data,
            starts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            lens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n_records,
            stride,
            off,
            pbuf,
            ctypes.byref(plen),
            _PREFIX_CAP,
            out.ctypes.data,
        )
    )
    if not ok:
        return None
    return bytes(pbuf.raw[: plen.value]), out


def scan_parse_i32_native(
    data: bytes, delimiter: str, ncols: int, header, typed_state
):
    """FUSED tokenize + typed parse of a fully-typed rectangular chunk:
    one C++ pass emits the selected columns' int32 affix values with no
    (start, len) offset arrays at all.  Requires every selected column
    in typed mode with an established prefix.  Returns
    ``(nrec, {name: ("int", prefix, values)})`` or None to bail (the
    caller reruns the chunk through the generic scan)."""
    try:
        lib = _load()
    except ImportError:
        return None
    delim_b = delimiter.encode("utf-8")
    if len(delim_b) != 1:
        # csv_scan_parse_i32 takes the delimiter as one C char; a
        # multi-byte delimiter must bail to the generic scan (which the
        # streaming caller gates onto the Python path) rather than reach
        # ctypes, which would raise instead of returning None
        return None
    n = len(data)
    if n == 0 or ncols <= 0:
        return None
    # a typed record needs >= 1 digit per selected field; the tightest
    # arity-independent bound is one byte per field + separators
    max_records = n // (2 * ncols) + 2
    outs = {}
    ptrs = (ctypes.c_void_p * ncols)()
    blob = bytearray()
    poff = np.zeros(ncols, dtype=np.int64)
    plen = np.zeros(ncols, dtype=np.int64)
    for name, idx in header.items():
        st = typed_state.get(name)
        if st is None or st[0] is None or idx >= ncols:
            return None
        arr = np.empty(max_records, dtype=np.int32)
        outs[name] = (idx, arr)
        ptrs[idx] = arr.ctypes.data
        poff[idx] = len(blob)
        plen[idx] = len(st[0])
        blob.extend(st[0])
    base = ctypes.cast(ctypes.c_char_p(data), ctypes.c_void_p).value
    rc = int(
        lib.csv_scan_parse_i32(
            base,
            n,
            delim_b,
            ncols,
            bytes(blob),
            poff.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            plen.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ptrs,
            max_records,
        )
    )
    if rc <= 0:
        return None
    # COPY the used slice: a view would pin the full max_records buffer
    # (typically 3-6x the real row count) across the consumer's whole
    # accumulation, breaking the one-chunk host-memory bound
    return rc, {
        name: ("int", typed_state[name][0], np.ascontiguousarray(arr[:rc]))
        for name, (idx, arr) in outs.items()
    }


def format_i32_native(values: np.ndarray, width: int = 12):
    """(NUL-padded (n, width) u8 matrix, int32 lens) of the decimal
    forms of *values* — the typed column's C++ materialize pre-pass; None
    when the native library is unavailable."""
    try:
        lib = _load()
    except ImportError:
        return None
    values = np.ascontiguousarray(values, dtype=np.int32)
    n = int(values.shape[0])
    out = np.empty((n, width), dtype=np.uint8)
    lens = np.empty(n, dtype=np.int32)
    if n == 0:
        return out, lens

    def run(lo: int, hi: int) -> None:
        lib.csv_format_i32(
            values[lo:hi].ctypes.data,
            hi - lo,
            width,
            out[lo:hi].ctypes.data,
            lens[lo:hi].ctypes.data,
        )

    k = min(os.cpu_count() or 1, 8)
    if n >= _PACK_THREADS_MIN_N and k >= 2:
        bounds = [n * i // k for i in range(k + 1)]
        list(_pack_pool_get().map(lambda b: run(*b), zip(bounds[:-1], bounds[1:])))
    else:
        run(0, n)
    return out, lens


def encode_fields_vectorized(
    combined: np.ndarray, starts: np.ndarray, lens: np.ndarray
):
    """Dictionary-encode a column directly from (start, len) offsets with
    zero per-field Python objects.

    Gathers every field into a NUL-padded (n, L) byte matrix — via the
    native C++ pack when available (one memcpy per field, threaded),
    else a numpy index-matrix gather — views rows as fixed-width scalars
    and runs ``np.unique``.  Byte order on padded UTF-8 equals
    code-point order (no field contains NUL; caller checks), so the
    resulting codes are order-preserving exactly like
    :func:`csvplus_tpu.columnar.table.encode_strings`.

    Returns (dictionary of 'S' bytes, int32 codes) or None when a field
    is too long for the padded-matrix approach.
    """
    n = starts.shape[0]
    if n == 0:
        return np.empty(0, dtype="S1"), np.empty(0, dtype=np.int32)
    L = int(lens.max()) if n else 0
    if L > _VEC_MAX_FIELD_LEN:
        return None
    L = max(L, 1)
    if L <= 8:
        packed = _pack_fields_native(combined, starts, lens, 8, u64=True)
        if packed is None:
            mat = _gather_numpy(combined, starts, lens, L)
            shifts = (1 << (8 * np.arange(7, 7 - L, -1, dtype=np.uint64))).astype(
                np.uint64
            )
            packed = mat.astype(np.uint64) @ shifts
            uniq64, codes = np.unique(packed, return_inverse=True)
        else:
            uniq64, codes = _encode_u64(packed)
        dictionary = _u64_dictionary_bytes(uniq64, L)
        return dictionary, codes.ravel().astype(np.int32)
    if L <= 16:
        mat = _pack_fields_native(combined, starts, lens, 16)
        if mat is not None:
            be = mat.view(">u8")
            hi = be[:, 0].astype(np.uint64)
            lo = be[:, 1].astype(np.uint64)
            (uh, ul), codes = _encode_u64x2(hi, lo)
            pair = np.empty((uh.size, 2), dtype=">u8")
            pair[:, 0] = uh
            pair[:, 1] = ul
            dictionary = np.frombuffer(pair.tobytes(), dtype="S16").astype(
                f"S{L}"
            )
            return dictionary, codes.ravel().astype(np.int32)
    mat = _pack_fields_native(combined, starts, lens, L)
    if mat is None:
        mat = _gather_numpy(combined, starts, lens, L)
    as_void = np.ascontiguousarray(mat).view([("v", f"V{L}")])["v"].ravel()
    uniq, codes = np.unique(as_void, return_inverse=True)
    # keep the dictionary as UTF-8 bytes; sinks decode lazily
    dictionary = uniq.view(f"S{L}").ravel()
    return dictionary, codes.ravel().astype(np.int32)


def _encode_u64(packed: np.ndarray):
    """Dictionary-encode packed u64 fields: np.unique output contract.

    Tier order: C++ hash encode (one linear-probe pass; wins whenever
    the distinct count is < n/4 — the common join-key/category shape),
    else np.unique's argsort.  A C++ LSD radix sort was tried for the
    high-cardinality tier and measured SLOWER than np.unique on real
    string-packed keys (their spread bytes defeat the radix digit-skip),
    so the bail path stays numpy."""
    n = packed.shape[0]
    try:
        lib = _load()
    except ImportError:
        return np.unique(packed, return_inverse=True)
    max_k = max(1024, n // 4)
    uniq = np.empty(max_k, dtype=np.uint64)
    prov = np.empty(n, dtype=np.int32)
    k = lib.csv_encode_hash_u64(
        packed.ctypes.data, n, uniq.ctypes.data, prov.ctypes.data, max_k
    )
    if k >= 0:
        d = uniq[:k]
        order = np.argsort(d)
        rank = np.empty(k, dtype=np.int32)
        rank[order] = np.arange(k, dtype=np.int32)
        return d[order], rank[prov]
    return np.unique(packed, return_inverse=True)  # high cardinality


def _encode_u64x2(hi: np.ndarray, lo: np.ndarray):
    """Dictionary-encode (hi, lo) big-endian u64 lane pairs (9-16 byte
    fields): C++ two-lane hash encode first, lexsort on bail — measured
    ~4.5x the void-dtype np.unique this replaces (the round-4 northstar
    profile's order_id-class cost).  Pair order == padded byte order, so
    codes stay order-preserving."""
    n = hi.shape[0]

    def _lex_unique():
        order = np.lexsort((lo, hi))
        sh, sl = hi[order], lo[order]
        new = np.empty(n, dtype=bool)
        new[0] = True
        np.logical_or(sh[1:] != sh[:-1], sl[1:] != sl[:-1], out=new[1:])
        ranks = (np.cumsum(new) - 1).astype(np.int32)
        codes = np.empty(n, dtype=np.int32)
        codes[order] = ranks
        return (sh[new], sl[new]), codes

    try:
        lib = _load()
    except ImportError:
        return _lex_unique()
    max_k = max(1024, n // 4)
    uh = np.empty(max_k, dtype=np.uint64)
    ul = np.empty(max_k, dtype=np.uint64)
    prov = np.empty(n, dtype=np.int32)
    # bind to locals: an inline ascontiguousarray temporary could be
    # freed before the native call runs
    hi_c = np.ascontiguousarray(hi)
    lo_c = np.ascontiguousarray(lo)
    k = lib.csv_encode_hash_u64x2(
        hi_c.ctypes.data,
        lo_c.ctypes.data,
        n,
        uh.ctypes.data,
        ul.ctypes.data,
        prov.ctypes.data,
        max_k,
    )
    if k < 0:
        return _lex_unique()
    dh, dl = uh[:k], ul[:k]
    lex = np.lexsort((dl, dh))
    rank = np.empty(k, dtype=np.int32)
    rank[lex] = np.arange(k, dtype=np.int32)
    return (dh[lex], dl[lex]), rank[prov]


def _u64_dictionary_bytes(uniq64: np.ndarray, L: int) -> np.ndarray:
    """Big-endian-packed u64 dictionary values -> 'S{L}' bytes array
    (native store loop when available; numpy shift-mask otherwise)."""
    k = uniq64.shape[0]
    uniq64 = np.ascontiguousarray(uniq64, dtype=np.uint64)
    try:
        lib = _load()
    except ImportError:
        back = (8 * np.arange(7, 7 - L, -1, dtype=np.int64)).astype(np.uint64)
        ub = ((uniq64[:, None] >> back[None, :]) & np.uint64(0xFF)).astype(np.uint8)
        return np.ascontiguousarray(ub).view(f"S{L}").ravel()
    out = np.empty((k, L), dtype=np.uint8)
    if k:
        lib.csv_u64_to_bytes(uniq64.ctypes.data, k, L, out.ctypes.data)
    return out.view(f"S{L}").ravel()


def _gather_numpy(
    combined: np.ndarray, starts: np.ndarray, lens: np.ndarray, L: int
) -> np.ndarray:
    """The pure-numpy padded gather (fallback when the toolchain is
    absent): index matrix + mask, identical output to the C++ pack."""
    idx = starts[:, None] + np.arange(L, dtype=np.int64)[None, :]
    mask = np.arange(L, dtype=np.int32)[None, :] < lens[:, None]
    return np.where(
        mask, combined[np.minimum(idx, combined.shape[0] - 1)], 0
    ).astype(np.uint8)


def _column_positions(data_counts, field_offset, header, rec_base, pad_allowed):
    """Per-column (positions, ok-mask) into the flat field arrays, with the
    shared column-not-found policy (csvplus.go:1121-1130)."""
    rec_offsets = np.zeros(data_counts.shape[0] + 1, dtype=np.int64)
    np.cumsum(data_counts, out=rec_offsets[1:])
    rec_offsets += field_offset
    for name in header:
        idx = header[name]
        pos = rec_offsets[:-1] + idx
        ok = data_counts > idx
        if not ok.all() and not pad_allowed:
            first_bad = int(np.flatnonzero(~ok)[0]) + rec_base
            raise DataSourceError(first_bad, f'column not found: "{name}" ({idx})')
        yield name, pos, ok


def read_device_parsed_columns(reader, path: str):
    """Device-encode ingest tier (ops/parse.py): separator scan and
    field offsets run in vectorized numpy (the host consumes them
    immediately), the bytes upload once, and the heavy dictionary
    encoding runs as a JAX sort-rank kernel on device; the host touches
    only header fields and unique dictionary values.

    Simple rectangular CSV only (no quotes/CR/comments/blank lines);
    returns (names, {name: (dictionary, codes)}) or None to fall back.
    """
    if (
        reader._trim_leading_space
        or reader._comment is not None
        or len(reader._delimiter.encode("utf-8")) != 1
    ):
        return None
    from ..ops.parse import encode_column_device, parse_simple_csv_device

    with open(path, "rb") as f:
        data = f.read()
    parsed = parse_simple_csv_device(data, reader._delimiter)
    if parsed is None:
        return None
    starts, lens, counts, data_dev = parsed

    header, rec_base, field_offset, data_counts, _ = _resolve_header_from_arrays(
        reader, data, b"", starts, lens, counts
    )

    combined = np.frombuffer(data, dtype=np.uint8)
    out = {}
    pad_allowed = reader._num_fields < 0
    for name, pos, ok in _column_positions(
        data_counts, field_offset, header, rec_base, pad_allowed
    ):
        col_starts = np.where(ok, starts[np.where(ok, pos, 0)], 0)
        col_lens = np.where(ok, lens[np.where(ok, pos, 0)], 0).astype(np.int32)
        enc = encode_column_device(data_dev, data, col_starts, col_lens)
        if enc is None:  # wide fields: vectorized host encode on the same offsets
            enc = encode_fields_vectorized(combined, col_starts, col_lens)
        if enc is None:
            return None
        out[name] = enc
    return list(header), out


def _check_field_counts(data_counts, expected: int, first_record: int) -> int:
    """Field-count policy over data records (csvplus.go:1121-1130),
    shared by the whole-file and streamed tiers: lock *expected* from
    the first record when auto (0), then every record must match.
    Returns the (possibly locked) expected width."""
    if data_counts.shape[0]:
        if expected == 0:
            expected = int(data_counts[0])
        bad = np.flatnonzero(data_counts != expected)
        if bad.size:
            raise DataSourceError(int(bad[0]) + first_record, ERR_FIELD_COUNT)
    return expected


def _resolve_header_from_arrays(reader, data, scratch, starts, lens, counts):
    """Header + field-count policy over pre-scanned offset arrays — the
    single implementation behind _scan_for_reader (native tiers), the
    device-parsed tier and the streamed tier's first chunk.  Raises
    DataSourceError; never returns None."""
    nrec = counts.shape[0]
    expected = reader._num_fields
    if reader._header_from_first_row:
        if nrec == 0:
            raise DataSourceError(1, "EOF")
        first_n = int(counts[0])
        if expected == 0:
            expected = first_n
        elif expected > 0 and first_n != expected:
            raise DataSourceError(1, ERR_FIELD_COUNT)
        first = [
            _field_str(data, scratch, int(starts[i]), int(lens[i]))
            for i in range(first_n)
        ]
        header = reader._make_header(first, 1)
        rec_base = 2
        field_offset = first_n
        data_counts = counts[1:]
    else:
        header = dict(reader._header or {})
        rec_base = 1
        field_offset = 0
        data_counts = counts
    if reader._num_fields >= 0:
        expected = _check_field_counts(data_counts, expected, rec_base)
    return header, rec_base, field_offset, data_counts, expected


def read_encoded_columns_native(reader, path: str):
    """Columnar ingest fast path: parse natively AND dictionary-encode
    each selected column vectorized — no per-cell Python strings.

    Returns (names, {name: (dictionary, codes)}) or None to fall back.
    """
    scanned = _scan_for_reader(reader, path)
    if scanned is None:
        return None
    data, starts, lens, counts, scratch, header, rec_base, field_offset = scanned
    if b"\x00" in data:  # NUL would be ambiguous with padding
        return None

    data_counts = counts[1:] if rec_base == 2 else counts

    # combined buffer: scratch fields get offsets past the input data
    combined = np.frombuffer(data + scratch, dtype=np.uint8)
    base = len(data)
    abs_starts = np.where(starts >= 0, starts, base + (-starts - 1))

    pad_allowed = reader._num_fields < 0
    cols = list(
        _column_positions(data_counts, field_offset, header, rec_base, pad_allowed)
    )

    typed_enabled = _env_str("CSVPLUS_TYPED_LANES", "1") != "0"

    def enc_one(args):
        name, pos, ok = args
        all_present = bool(ok.all())
        if all_present:
            col_starts, col_lens = abs_starts[pos], lens[pos]
        else:
            col_starts = np.where(ok, abs_starts[np.where(ok, pos, 0)], 0)
            col_lens = np.where(ok, lens[np.where(ok, pos, 0)], 0)
        col_lens = col_lens.astype(np.int32)
        if typed_enabled and all_present:
            # typed value lanes (SURVEY §7 M2), same form as the
            # streamed tier: prefix + canonical int32 per cell
            packed = pack_int32_native(combined, col_starts, col_lens, None)
            if packed is not None:
                return name, ("int", packed[0], packed[1])
        enc = encode_fields_vectorized(combined, col_starts, col_lens)
        if enc is None:
            raise _EncodeFallback(name)
        return name, enc

    try:
        out = dict(_map_columns(enc_one, cols))
    except _EncodeFallback:
        return None  # long fields: let the string path handle it
    return list(header), out


class _EncodeFallback(Exception):
    """A column declined the vectorized encode (over-long field); the
    caller abandons the whole encode immediately instead of paying for
    the remaining columns and then discarding everything."""


_col_pool = None
_col_pool_lock = threading.Lock()


def _col_pool_get():
    """Persistent column-encode pool (distinct from the pack pool —
    column tasks submit pack tasks, so they must not share one pool)."""
    global _col_pool
    if _col_pool is None:
        with _col_pool_lock:
            if _col_pool is None:
                from concurrent.futures import ThreadPoolExecutor

                _col_pool = ThreadPoolExecutor(
                    max_workers=max(2, min((os.cpu_count() or 2) // 2, 8)),
                    thread_name_prefix="csvplus-col",
                )
    return _col_pool


def _map_columns(fn, cols):
    """Run *fn* over the columns — concurrently when there are several,
    the rows are many, and more than one core exists (np.unique and the
    native pack both release the GIL).  An exception from any column
    (e.g. :class:`_EncodeFallback`) cancels the not-yet-started rest."""
    if (
        len(cols) < 2
        or (os.cpu_count() or 1) < 2
        or (cols and cols[0][1].shape[0] < _PACK_THREADS_MIN_N)
    ):
        return [fn(c) for c in cols]
    futs = [_col_pool_get().submit(fn, c) for c in cols]
    try:
        return [f.result() for f in futs]
    except BaseException:
        for f in futs:
            f.cancel()
        raise


class StreamFallback(Exception):
    """Raised by the streaming tier when it meets input it cannot handle
    (quotes, NULs, over-long fields); callers fall back to the whole-file
    tiers, which re-read the file from the start."""


_STREAM_CHUNK_BYTES = 64 << 20


def _stream_chunk_bytes() -> int:
    v = _env_str("CSVPLUS_STREAM_CHUNK_BYTES")
    return int(v) if v else _STREAM_CHUNK_BYTES


def _ingest_workers() -> int:
    """K for the staged chunk scan+encode pipeline
    (``CSVPLUS_INGEST_WORKERS``).  0/unset = auto: half the cores — the
    native scan threads *within* a chunk too, so chunk-level and
    intra-chunk parallelism split the machine — capped at 8.  1 is the
    serial degenerate case (same code path, driven inline)."""
    k = _env_int("CSVPLUS_INGEST_WORKERS", 0)
    if k <= 0:
        k = min(max((os.cpu_count() or 1) // 2, 1), 8)
    return max(1, min(k, 32))


def _iter_parity_chunks(reader, f, chunk_bytes: int):
    """Readahead stage: cut the file into newline/quote-parity-aligned
    chunks.  Every chunk starts at a record boundary with closed quote
    state (cumulative-quote-parity cut; the pending tail's parity and
    quote presence carry across reads so each byte is parity-scanned
    once).  Pure byte cutting — no scanning or encoding — so the staged
    pipeline's workers all see boundary-exact chunks regardless of K."""
    pending = b""
    pend_parity = 0
    pend_quote = False
    eof = False
    while not eof:
        faults.inject("ingest:read")  # chaos site: I/O error mid-file
        raw = f.read(chunk_bytes)
        if not raw:
            eof = True
            data, pending = pending, b""
            pend_parity, pend_quote = 0, False
            if not data:
                break
        else:
            raw_quote = b'"' in raw
            if raw_quote or pend_quote:
                if reader._lazy_quotes:
                    # a bare quote inside an unquoted field is legal
                    # under LazyQuotes and breaks the parity cut
                    raise StreamFallback("quote under LazyQuotes")
                # safe cut = last newline whose cumulative quote count
                # is even (strict quoting: odd parity means the newline
                # sits inside an open quoted field); only the NEW bytes
                # are scanned, seeded with the pending tail's parity
                a = np.frombuffer(raw, dtype=np.uint8)
                parity = (
                    np.cumsum(a == ord('"'), dtype=np.int64) + pend_parity
                ) & 1
                safe_nl = np.flatnonzero((a == ord("\n")) & (parity == 0))
                if safe_nl.size == 0:
                    pending += raw  # giant quoted record: read more
                    pend_parity = int(parity[-1])
                    pend_quote = pend_quote or raw_quote
                    continue
                cut = int(safe_nl[-1]) + 1
                data, pending = pending + raw[:cut], raw[cut:]
                pend_parity = int(parity[-1])  # parity at cut is 0
                pend_quote = b'"' in pending
            else:
                cut = raw.rfind(b"\n") + 1
                if cut == 0:
                    pending += raw  # no record boundary yet
                    continue
                data, pending = pending + raw[:cut], raw[cut:]
        yield data


class _StreamCtx:
    """Shared state the chunk workers read, established by the first
    encoded chunk and owned by the ordered reassembler thereafter.

    ``typed`` maps live typed columns to their PINNED prefix (None only
    during the establishment chunk, where the prefix derives from the
    first cell).  The reassembler swaps in a reduced dict when a column
    demotes — workers read the attribute once per chunk, so an in-flight
    worker may still encode a just-demoted column speculatively; the
    reassembler normalizes that result, keeping the emitted stream
    identical for every K."""

    __slots__ = (
        "reader",
        "header",
        "names",
        "expected",
        "pad_allowed",
        "typed",
        "fused_ncols",
        "encoder",
        "delim_b",
        "scan_threads",
    )

    def __init__(self, reader, encoder):
        self.reader = reader
        self.encoder = encoder
        self.header = None
        self.names = []
        self.expected = reader._num_fields
        self.pad_allowed = reader._num_fields < 0
        self.typed = {}
        self.fused_ncols = 0
        self.delim_b = reader._delimiter.encode("utf-8")
        self.scan_threads = None


class _ChunkResult:
    """One chunk's scan+encode outcome, produced by a worker and
    consumed in file order by the reassembler.  Errors are stored
    CHUNK-RELATIVE (``absolute = rel + next_record - 1``) because only
    the reassembler knows the chunk's absolute record base."""

    __slots__ = ("nscanned", "nrec", "cols", "error", "t_scan", "t_encode", "worker")

    def __init__(self):
        self.nscanned = 0  # records scanned (header included on chunk 0)
        self.nrec = 0  # data records
        self.cols = None
        self.error = None  # ("data", rel_record, msg) | ("fallback", reason)
        self.t_scan = 0.0
        self.t_encode = 0.0
        self.worker = ""


def _encode_scanned(
    ctx, res, data, scratch, starts, lens, data_counts, field_offset, rec_base
):
    """Column encode over pre-scanned offset arrays — the single
    implementation behind both the establishment chunk (prefix-derive
    mode, inline) and the staged workers (pinned prefixes).  Fills
    ``res`` in place; never raises for data-shaped problems (they land
    in ``res.error``, chunk-relative)."""
    reader = ctx.reader
    header = ctx.header
    typed = ctx.typed  # one read: the reassembler may swap in a new dict
    # scratch holds unescaped quoted-field content; negative starts
    # index it past the chunk (read_encoded_columns_native layout).
    # Quote-free chunks skip the concatenation.
    enc_data = data + scratch if scratch else data
    combined = np.frombuffer(enc_data, dtype=np.uint8)
    base = len(data)
    abs_starts = (
        np.where(starts >= 0, starts, base + (-starts - 1)) if scratch else starts
    )
    # RECTANGULAR fast path for typed columns: uniform field counts + no
    # scratch means column idx of record r is flat field
    # field_offset + r*nf + idx — the strided C++ parse reads it
    # directly, skipping per-column position construction and gathers
    typed_out = {}
    failed_typed = set()
    nrec = int(data_counts.shape[0])
    res.nrec = nrec
    uniform_nf = 0
    if typed and not scratch and nrec:
        mn, mx = int(data_counts.min()), int(data_counts.max())
        if mn == mx:
            uniform_nf = mn
    if uniform_nf:
        for name, idx in header.items():
            prefix = typed.get(name, _NOT_TYPED)
            if prefix is _NOT_TYPED or idx >= uniform_nf:
                continue
            packed = pack_int32_strided_native(
                combined, starts, lens, nrec, uniform_nf, field_offset + idx, prefix
            )
            if packed is None:
                failed_typed.add(name)  # dictionary from here; driver demotes
                continue
            typed_out[name] = ("int", packed[0], packed[1])

    try:
        cols = (
            list(
                _column_positions(
                    data_counts, field_offset, header, rec_base, ctx.pad_allowed
                )
            )
            if len(typed_out) < len(header)
            else []
        )
    except DataSourceError as e:
        res.error = ("data", int(e.line), e.err)
        return
    cols = [c for c in cols if c[0] not in typed_out]

    def enc_one(args):
        name, pos, ok = args
        all_present = bool(ok.all())
        if all_present:
            col_starts, col_lens = abs_starts[pos], lens[pos].astype(np.int32)
        else:
            col_starts = np.where(ok, abs_starts[np.where(ok, pos, 0)], 0)
            col_lens = np.where(ok, lens[np.where(ok, pos, 0)], 0).astype(np.int32)
        prefix = typed.get(name, _NOT_TYPED)
        if prefix is not _NOT_TYPED and name not in failed_typed:
            # typed value-lane attempt; a padded/absent cell or a
            # non-conforming field drops the column to dictionary mode —
            # PERMANENTLY, but the demotion bookkeeping belongs to the
            # reassembler (file order), not this worker
            packed = (
                pack_int32_native(combined, col_starts, col_lens, prefix)
                if all_present
                else None
            )
            if packed is not None:
                return name, ("int", packed[0], packed[1])
        enc = (
            ctx.encoder(combined, enc_data, col_starts, col_lens)
            if ctx.encoder is not None
            else None
        )
        if enc is None:
            enc = encode_fields_vectorized(combined, col_starts, col_lens)
        if enc is None:
            raise StreamFallback("field too long for vectorized encode")
        return name, enc

    try:
        # device-encode chunks stay serial (one upload stream); host
        # encodes thread across columns
        out = dict(
            [enc_one(c) for c in cols]
            if ctx.encoder is not None
            else _map_columns(enc_one, cols)
        )
    except StreamFallback as e:
        res.error = ("fallback", str(e))
        return
    out.update(typed_out)
    res.cols = out


_NOT_TYPED = object()  # sentinel: None is a valid (derive-mode) prefix


def _scan_encode_chunk(ctx, data):
    """One staged worker's unit of work: scan + encode a single
    post-establishment chunk against the immutable context.  Pure with
    respect to shared state (reads ``ctx``, mutates nothing), so K
    workers run it concurrently and the reassembler's file-order merge
    is the only serialization point.  The native scan/pack/encode
    helpers release the GIL, so the workers genuinely overlap."""
    faults.inject("ingest:worker")  # chaos site: one worker crashes
    res = _ChunkResult()
    res.worker = threading.current_thread().name
    t0 = time.perf_counter()
    reader = ctx.reader
    if b"\x00" in data:
        res.error = ("fallback", "NUL in chunk")
        return res
    typed = ctx.typed
    # FUSED fast path: when every selected column is typed with an
    # established prefix and the chunk is plain (no quotes/CR/comments),
    # ONE C++ pass tokenizes and int-parses the whole chunk without
    # writing field offsets at all — the two-pass scan+parse writes and
    # re-reads ~12 bytes of offsets per field, which dominated the
    # single-core 100M-row ingest profile.  Any bail (record arity,
    # non-conforming cell) reruns the chunk through the generic path
    # below, which owns exact error numbering.
    if (
        ctx.fused_ncols
        and typed
        # the fused C++ pass takes the delimiter as ONE char;
        # multi-byte delimiters must take the generic path
        and len(ctx.delim_b) == 1
        and reader._comment is None
        and len(typed) == len(ctx.header)
        and all(
            p is not None
            # a prefix containing the delimiter or a record terminator
            # (possible via quoted cells in earlier chunks) would let
            # the fused parser's prefix memcmp read across field
            # boundaries and misparse — those columns keep the
            # tokenized path
            and ctx.delim_b not in p
            and b"\n" not in p
            and b"\r" not in p
            for p in typed.values()
        )
        and b'"' not in data
        and b"\r" not in data
    ):
        fused = scan_parse_i32_native(
            data,
            reader._delimiter,
            ctx.fused_ncols,
            ctx.header,
            {n: (p,) for n, p in typed.items()},
        )
        if fused is not None:
            # fused records are structurally exact-arity, so the locked
            # field-count policy holds by construction
            nrec, typed_cols = fused
            res.nscanned = nrec
            res.nrec = nrec
            res.cols = typed_cols
            res.t_scan = time.perf_counter() - t0
            return res
    try:
        # chunks start at record boundaries with closed quote state, so
        # the multi-threaded newline-split scan applies to them exactly
        # as to whole files (quote-bearing chunks fall back to the
        # single-pass state machine inside)
        starts, lens, counts, scratch = scan_bytes_parallel(
            data,
            delimiter=reader._delimiter,
            comment=reader._comment,
            lazy_quotes=reader._lazy_quotes,
            n_threads=ctx.scan_threads,
        )
    except DataSourceError as e:
        res.error = ("data", int(e.line), e.err)
        return res
    res.nscanned = int(counts.shape[0])
    res.t_scan = time.perf_counter() - t0
    if reader._num_fields >= 0:
        try:
            _check_field_counts(counts, ctx.expected, 1)
        except DataSourceError as e:
            res.error = ("data", int(e.line), e.err)
            return res
    _encode_scanned(ctx, res, data, scratch, starts, lens, counts, 0, 1)
    res.t_encode = time.perf_counter() - t0 - res.t_scan
    return res


#: Bounded re-executions of one chunk after transient worker crashes.
_WORKER_RETRIES = 3


def _run_chunk(ctx, data):
    """Run one staged worker unit, re-executing the chunk after a
    transient worker crash (bounded by :data:`_WORKER_RETRIES`).

    Sound by construction: :func:`_scan_encode_chunk` is pure over the
    immutable ``ctx`` snapshot and the chunk bytes, so re-execution is
    idempotent — the reassembler (and therefore the emitted stream)
    cannot observe that a crash happened.  Non-transient failures
    re-raise untouched; recoveries land on the telemetry counter
    ``ingest.worker_recovered``."""
    from ..resilience.retry import TRANSIENT, classify

    attempt = 0
    while True:
        try:
            return _scan_encode_chunk(ctx, data)
        except Exception as err:
            if classify(err) != TRANSIENT or attempt >= _WORKER_RETRIES:
                raise
            attempt += 1
            from ..utils.observe import telemetry

            telemetry.count("ingest.worker_recovered")


def stream_encoded_chunks(
    reader,
    path: str,
    chunk_bytes: Optional[int] = None,
    encoder=None,
    workers: Optional[int] = None,
):
    """Generator over newline-aligned file chunks, each natively scanned
    and dictionary-encoded with zero per-cell Python objects.

    Yields ``(names, {name: (dictionary, codes)}, nrows)`` per chunk; the
    column set is fixed by the first chunk's header resolution.  Host
    memory is bounded by one chunk plus per-chunk dictionaries — the
    monolithic ``f.read()`` of the whole-file tiers never happens
    (VERDICT round-1 weak #4; reference semantics csvplus.go:1080-1146).

    QUOTED files stream too (VERDICT round-2 #4): under strict RFC-4180
    quoting every quoted field contains an even number of quote bytes,
    so a newline is a record boundary iff the cumulative quote count up
    to it is even — chunks are cut at the last such newline (a prefix-
    sum parity scan) and the carry-over tail is prepended to the next
    read.  Each chunk therefore starts at a record boundary with closed
    quote state, and the native scanner's scratch buffer (unescaped
    quoted content) feeds the same vectorized encode.

    Raises :class:`StreamFallback` on input this tier cannot chunk
    safely: quotes under ``LazyQuotes`` (a bare quote inside an
    unquoted field breaks the parity invariant; csvplus.go:1005-1012
    semantics keep the whole-file scanner), a NUL byte (ambiguous with
    encode padding), or a field longer than the vectorized-encode
    limit.  Field-count and header errors raise :class:`DataSourceError`
    with ABSOLUTE 1-based record numbers, identical to the whole-file
    paths.

    *encoder*, when given, is tried first for each column:
    ``encoder(combined_u8, data_bytes, col_starts, col_lens)`` returns
    ``(dictionary, codes)`` or None to decline (then the host vectorized
    encode runs) — the hook the device-encode ingest tier plugs in.

    TYPED VALUE LANES (SURVEY §7 M2 "typed columns where parseable"): a
    column whose every cell so far is ``prefix + canonical int32``
    (native ``csv_pack_int32``) yields ``("int", prefix, int32 values)``
    instead of a dictionary pair — no dictionary encode at all, 4
    bytes/row.  The prefix is derived from the very first cell and
    pinned; the first non-conforming chunk switches the column to
    dictionary encoding permanently (the consumer re-encodes the
    accumulated chunks).  Disable with ``CSVPLUS_TYPED_LANES=0``.

    STAGED PIPELINE (``CSVPLUS_INGEST_WORKERS``, or *workers*): after
    the first chunk establishes the header, field-count policy, and
    typed prefixes, the remaining chunks flow through a readahead stage
    (:func:`_iter_parity_chunks`, parity-aligned byte cutting), a pool
    of K workers running :func:`_scan_encode_chunk` concurrently (the
    native scan/pack release the GIL), and an ordered reassembler that
    emits chunks strictly in file order.  Workers encode typed lanes
    SPECULATIVELY against an immutable prefix snapshot (the C++ parse
    pins the prefix after derivation, so there is no per-chunk prefix
    state to race on); the reassembler owns demotion — the first
    non-conforming chunk IN FILE ORDER demotes a column regardless of
    worker count or completion order, and any in-flight speculative
    typed result for a demoted column is normalized to the identical
    dictionary encoding.  Errors travel chunk-relative and are
    re-numbered to absolute records at emission, so yields, error
    numbers, and demotion points are bitwise-identical for every K;
    K=1 drives the very same worker function inline (degenerate case,
    no separate code path).  Host memory stays bounded: at most K
    chunks in flight plus one being cut (plus the consumer's
    ``CSVPLUS_STREAM_PREFETCH`` depth).
    """
    if reader._trim_leading_space:
        raise StreamFallback("trim")
    if len(reader._delimiter.encode("utf-8")) != 1:
        raise StreamFallback("delimiter")
    if reader._comment is not None and len(reader._comment.encode("utf-8")) != 1:
        raise StreamFallback("comment")
    chunk_bytes = chunk_bytes or _stream_chunk_bytes()
    k_workers = max(1, workers if workers is not None else _ingest_workers())
    if encoder is not None:
        k_workers = 1  # device-encode hook: one upload stream, stays inline

    typed_enabled = _env_str("CSVPLUS_TYPED_LANES", "1") != "0"
    next_record = 1  # absolute 1-based ordinal of the next record scanned
    typed_live: set = set()  # columns still typed, in FILE order
    _pc = time.perf_counter
    stats = {
        "cut": 0.0,  # readahead: file read + parity cut
        "stall": 0.0,  # reassembler blocked on the head-of-line chunk
        "scan": 0.0,
        "encode": 0.0,
        "rows": 0,
        "chunks": 0,
        "per_worker": {},
    }

    def account(res):
        stats["chunks"] += 1
        stats["rows"] += res.nrec
        stats["scan"] += res.t_scan
        stats["encode"] += res.t_encode
        w = stats["per_worker"]
        w[res.worker] = w.get(res.worker, 0.0) + res.t_scan + res.t_encode

    try:
        f = open(path, "rb")
    except OSError as e:
        # same typed shape as Reader._open: the source failed before
        # row 1 (nonexistent file, permission denied, directory, ...)
        raise DataSourceError(1, f"open: {e.strerror or e}") from e
    with f:
        chunks_iter = _iter_parity_chunks(reader, f, chunk_bytes)
        ctx = None

        # ---- establishment: inline until the first encoded chunk.
        # Header resolution, field-count locking, and typed-prefix
        # derivation all happen here, exactly as the whole-file tiers do;
        # afterwards the context is immutable to workers. ----
        while True:
            try:
                data = next(chunks_iter, None)
            except OSError as e:
                # a read failure before the first encoded chunk: typed
                # and numbered at the next unread record, per the
                # reference error contract
                raise map_error(e, next_record) from e
            if data is None:
                break
            t0 = _pc()
            if b"\x00" in data:
                raise StreamFallback("NUL in chunk")
            try:
                starts, lens, counts, scratch = scan_bytes_parallel(
                    data,
                    delimiter=reader._delimiter,
                    comment=reader._comment,
                    lazy_quotes=reader._lazy_quotes,
                )
            except DataSourceError as e:
                raise DataSourceError(e.line + next_record - 1, e.err)
            if counts.shape[0] == 0:
                continue  # comment-only chunk before the first record
            # first chunk with records: header + field-count policy
            # resolve exactly as the whole-file tiers do
            header, rec_base, field_offset, data_counts, expected = (
                _resolve_header_from_arrays(reader, data, scratch, starts, lens, counts)
            )
            ctx = _StreamCtx(reader, encoder)
            ctx.header = header
            ctx.names = list(header)
            ctx.expected = expected
            if typed_enabled:
                ctx.typed = {n: None for n in ctx.names}  # derive mode
                if expected and expected > 0:
                    ctx.fused_ncols = int(expected)
                elif data_counts.size and int(data_counts.min()) == int(
                    data_counts.max()
                ):
                    ctx.fused_ncols = int(data_counts[0])
            if k_workers > 1:
                # chunk-level and intra-chunk scan parallelism split the
                # cores (each still subject to CSVPLUS_SCAN_THREADS)
                ctx.scan_threads = max(1, (os.cpu_count() or 1) // k_workers)
            res = _ChunkResult()
            res.worker = threading.current_thread().name
            res.nscanned = int(counts.shape[0])
            res.t_scan = _pc() - t0
            _encode_scanned(
                ctx, res, data, scratch, starts, lens, data_counts,
                field_offset, rec_base,
            )
            res.t_encode = _pc() - t0 - res.t_scan
            if res.error is not None:
                if res.error[0] == "fallback":
                    raise StreamFallback(res.error[1])
                raise DataSourceError(res.error[1], res.error[2])  # next_record==1
            # pin the derived prefixes; columns that came back as
            # dictionaries left typed mode on their very first chunk
            ctx.typed = {
                c: enc[1]
                for c, enc in res.cols.items()
                if len(enc) == 3 and enc[0] == "int"
            }
            typed_live = set(ctx.typed)
            account(res)
            next_record += res.nscanned
            yield ctx.names, res.cols, res.nrec
            break
        if ctx is None:
            return  # no records at all: the consumer falls back

        def emit(res):
            """Ordered reassembly of one chunk result: translate errors
            to absolute record numbers, apply demotions in file order,
            normalize stale speculative typed results."""
            nonlocal next_record
            if res.error is not None:
                if res.error[0] == "fallback":
                    raise StreamFallback(res.error[1])
                raise DataSourceError(res.error[1] + next_record - 1, res.error[2])
            out = res.cols
            demoted_now = False
            for c in ctx.names:
                enc = out[c]
                if len(enc) == 3 and enc[0] == "int":
                    if c not in typed_live:
                        # speculative typed result from a worker whose
                        # snapshot predates this column's demotion:
                        # re-encode exactly as the consumer's late-typed
                        # path does (format_affix is the exact inverse
                        # of the native parse, so values — and therefore
                        # the sorted dictionary and codes — are
                        # bitwise-identical to a direct dictionary
                        # encode of the raw bytes)
                        from ..columnar.typed import format_affix

                        strs = format_affix(enc[1], np.asarray(enc[2], np.int32))
                        dd, cc = np.unique(strs, return_inverse=True)
                        out[c] = (dd, cc.astype(np.int32))
                elif c in typed_live:
                    # first non-conforming chunk IN FILE ORDER: the
                    # column leaves typed mode permanently, at the same
                    # chunk index for every worker count
                    typed_live.discard(c)
                    demoted_now = True
            if demoted_now:
                # shrink the workers' snapshot so NEW chunks skip the
                # dead speculative work (in-flight ones normalize above)
                ctx.typed = {c: p for c, p in ctx.typed.items() if c in typed_live}
            account(res)
            next_record += res.nscanned
            return ctx.names, out, res.nrec

        # ---- staged phase: readahead -> K workers -> ordered emit ----
        cut_error = None
        read_error = None
        if k_workers == 1:
            # degenerate case: the same worker function, driven inline
            while True:
                t0 = _pc()
                try:
                    data = next(chunks_iter, None)
                except StreamFallback as e:
                    cut_error = e
                    data = None
                except OSError as e:
                    read_error = e
                    data = None
                stats["cut"] += _pc() - t0
                if data is None:
                    break
                yield emit(_run_chunk(ctx, data))
        else:
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            pool = ThreadPoolExecutor(
                max_workers=k_workers, thread_name_prefix="csvplus-ingest"
            )
            try:
                pending: deque = deque()
                exhausted = False
                while True:
                    # keep at most K chunks in flight: the host-memory
                    # bound is K encodes + one chunk being cut
                    while not exhausted and len(pending) < k_workers:
                        t0 = _pc()
                        try:
                            data = next(chunks_iter, None)
                        except StreamFallback as e:
                            # the cutter hit input this tier cannot
                            # chunk (quote under LazyQuotes): chunks
                            # already cut still emit first, exactly as
                            # the serial loop ordered them
                            cut_error = e
                            data = None
                        except OSError as e:
                            # a failed readahead: already-cut chunks
                            # still emit first (same drain order as the
                            # serial loop) before the error surfaces
                            read_error = e
                            data = None
                        stats["cut"] += _pc() - t0
                        if data is None:
                            exhausted = True
                            break
                        pending.append(
                            (pool.submit(_scan_encode_chunk, ctx, data), data)
                        )
                    if not pending:
                        break
                    t0 = _pc()
                    fut, chunk_data = pending.popleft()
                    try:
                        res = fut.result()
                    except Exception as err:
                        from ..resilience.retry import TRANSIENT, classify

                        if classify(err) != TRANSIENT:
                            raise
                        # a crashed worker: re-execute its chunk inline
                        # on the reassembler (pure + immutable ctx, so
                        # idempotent; it slots into the same head-of-
                        # line position, keeping K unobservable)
                        from ..utils.observe import telemetry

                        telemetry.count("ingest.worker_recovered")
                        res = _run_chunk(ctx, chunk_data)
                    stats["stall"] += _pc() - t0
                    yield emit(res)
            finally:
                pool.shutdown(wait=False, cancel_futures=True)
        if read_error is not None:
            # every record already cut has emitted, so next_record is
            # the absolute 1-based ordinal the failed read would have
            # produced next — typed, reference numbering
            raise map_error(read_error, next_record) from read_error
        if cut_error is not None:
            raise cut_error

    # per-stage attribution (collection-gated, pure accumulation — no
    # barriers): cut = readahead read+parity, encode = worker busy time
    # (sums across workers, so > wall clock when they overlap), stall =
    # reassembler head-of-line waits
    from ..utils.observe import telemetry

    rows = stats["rows"]
    telemetry.add_stage(
        "ingest:cut", rows, rows, stats["cut"], chunks=stats["chunks"]
    )
    telemetry.add_stage(
        "ingest:encode",
        rows,
        rows,
        stats["scan"] + stats["encode"],
        workers=k_workers,
        scan_s=round(stats["scan"], 4),
        encode_s=round(stats["encode"], 4),
        per_worker_busy_s={
            k: round(v, 4) for k, v in sorted(stats["per_worker"].items())
        },
    )
    if k_workers > 1:
        telemetry.add_stage(
            "ingest:reorder-stall", rows, rows, stats["stall"], workers=k_workers
        )
    # per-worker lane spans: when a trace is active, each worker's total
    # busy time becomes one span on its own lane, so Perfetto shows the
    # staged scan+encode overlap instead of one summed bar
    from ..obs.span import tracer

    if tracer.active():
        for worker, busy_s in sorted(stats["per_worker"].items()):
            tracer.add_span(
                "ingest:encode-worker",
                float(busy_s),
                lane=f"ingest-w{worker}",
                worker=worker,
            )


def _scan_for_reader(reader, path: str):
    """Shared native-scan + header-policy resolution for both fast paths."""
    if reader._trim_leading_space:
        return None
    if len(reader._delimiter.encode("utf-8")) != 1:
        return None
    if reader._comment is not None and len(reader._comment.encode("utf-8")) != 1:
        return None

    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError as e:
        raise DataSourceError(1, f"open: {e.strerror or e}") from e

    starts, lens, counts, scratch = scan_bytes_parallel(
        data,
        delimiter=reader._delimiter,
        comment=reader._comment,
        lazy_quotes=reader._lazy_quotes,
    )
    header, rec_base, field_offset, _counts, _ = _resolve_header_from_arrays(
        reader, data, scratch, starts, lens, counts
    )
    return data, starts, lens, counts, scratch, header, rec_base, field_offset


def read_columns_native(reader, path: str):
    """Columnar read honoring the Reader's header/field-count policies.

    Returns (names, {name: [values]}) like Reader.read_columns, or None
    when this reader's configuration needs the Python path.  Only the
    columns the header policy selects are ever materialized as strings.
    """
    scanned = _scan_for_reader(reader, path)
    if scanned is None:
        return None
    data, starts, lens, counts, scratch, header, rec_base, field_offset = scanned

    data_counts = counts[1:] if rec_base == 2 else counts
    out: Dict[str, List[str]] = {}
    pad_allowed = reader._num_fields < 0
    for name, pos, ok in _column_positions(
        data_counts, field_offset, header, rec_base, pad_allowed
    ):
        col_starts = starts[np.where(ok, pos, 0)]
        col_lens = lens[np.where(ok, pos, 0)]
        ok_list = ok.tolist()
        values = [
            _field_str(data, scratch, int(s), int(l)) if o else ""
            for s, l, o in zip(col_starts.tolist(), col_lens.tolist(), ok_list)
        ]
        out[name] = values
    return list(header), out
