// Native CSV chunk scanner.
//
// Single-pass byte-level state machine with the same semantics as the
// Python specification in csvplus_tpu/csvio.py (which mirrors the
// reference's use of Go encoding/csv, csvplus.go:1091-1097):
//   - records end at '\n' or "\r\n"; quoted fields may span lines;
//   - blank lines and comment-prefixed lines are skipped at record start;
//   - RFC-4180 quoting with "" doubling; without lazy_quotes a bare '"'
//     in an unquoted field or a stray '"' in a quoted field is an error;
//   - a trailing delimiter yields an empty last field.
//
// Output is COLUMNAR-friendly: no per-record allocations, just flat
// arrays of field (start, length) into the input buffer.  Fields that
// need transformation (escaped quotes, normalized line breaks inside
// quotes) are materialized into a caller-provided scratch buffer and
// flagged with a negative start: start = -(scratch_offset + 1).
//
// Returns the total number of fields parsed, or a negative error code
// with *err_record set to the 1-based record ordinal.

#include <cstdint>
#include <cstring>

extern "C" {

enum {
  CSV_ERR_BARE_QUOTE = -1,  // bare " in non-quoted field
  CSV_ERR_QUOTE = -2,       // extraneous or missing " in quoted-field
  CSV_ERR_OVERFLOW = -3,    // caller's arrays too small (should not happen)
};

int64_t csv_scan(const char* buf, int64_t len, char delim, char comment,
                 int has_comment, int lazy_quotes, int trim_space,
                 int64_t* field_starts, int32_t* field_lens,
                 int32_t* rec_counts, char* scratch, int64_t scratch_cap,
                 int64_t* scratch_used, int64_t max_fields,
                 int64_t max_records, int64_t* err_record) {
  int64_t pos = 0;
  int64_t nfields = 0;
  int64_t nrecords = 0;
  int64_t scr = 0;

  while (pos < len) {
    // ---- record start: skip blank lines and comment lines ----
    if (buf[pos] == '\n') { pos += 1; continue; }
    if (buf[pos] == '\r' && pos + 1 < len && buf[pos + 1] == '\n') {
      pos += 2; continue;
    }
    if (has_comment && buf[pos] == comment) {
      while (pos < len && buf[pos] != '\n') pos++;
      if (pos < len) pos++;  // consume '\n'
      continue;
    }

    if (nrecords >= max_records) { *err_record = nrecords; return CSV_ERR_OVERFLOW; }
    int32_t fields_in_rec = 0;
    bool record_done = false;

    while (!record_done) {
      // ---- one field ----
      if (nfields >= max_fields) { *err_record = nrecords + 1; return CSV_ERR_OVERFLOW; }
      if (trim_space) {
        while (pos < len && (buf[pos] == ' ' || buf[pos] == '\t')) pos++;
      }

      if (pos < len && buf[pos] == '"') {
        // ---- quoted field ----
        pos++;
        int64_t seg_start = pos;   // current contiguous segment
        bool needs_scratch = false;
        int64_t scr_start = scr;   // scratch offset if transformed
        int64_t plain_start = pos; // zero-copy range when !needs_scratch
        int64_t plain_len = 0;

        auto flush_segment = [&](int64_t upto) {
          // append [seg_start, upto) to scratch
          int64_t n = upto - seg_start;
          if (n > 0) {
            if (scr + n > scratch_cap) n = scratch_cap - scr;  // defensive
            std::memcpy(scratch + scr, buf + seg_start, n);
            scr += n;
          }
        };
        auto to_scratch_mode = [&](int64_t upto) {
          if (!needs_scratch) {
            needs_scratch = true;
            scr_start = scr;
            seg_start = plain_start;
            flush_segment(upto);
            seg_start = upto;
          }
        };

        for (;;) {
          if (pos >= len) {
            // EOF inside quotes
            if (!lazy_quotes) { *err_record = nrecords + 1; return CSV_ERR_QUOTE; }
            // the Python spec strips each line's terminator before
            // scanning, so a terminator right at EOF is not field data
            int64_t end = pos;
            if (end > seg_start && buf[end - 1] == '\n') {
              end--;
              if (end > seg_start && buf[end - 1] == '\r') end--;
            }
            if (needs_scratch) {
              flush_segment(end);
              field_starts[nfields] = -(scr_start + 1);
              field_lens[nfields] = (int32_t)(scr - scr_start);
            } else {
              field_starts[nfields] = plain_start;
              field_lens[nfields] = (int32_t)(end - plain_start);
            }
            nfields++; fields_in_rec++;
            record_done = true;
            break;
          }
          char c = buf[pos];
          if (c == '"') {
            if (pos + 1 < len && buf[pos + 1] == '"') {
              // doubled quote -> literal "
              to_scratch_mode(pos);
              flush_segment(pos);  // seg_start..pos (content before quote)
              if (scr < scratch_cap) scratch[scr++] = '"';
              pos += 2;
              seg_start = pos;
              continue;
            }
            // closing quote
            int64_t content_end = pos;
            pos++;
            // NOTE: a lone '\r' at EOF is NOT a terminator (the Python
            // spec only strips "\r\n" pairs), so '"..."\r<EOF>' is a
            // stray-quote situation, matching csvio.py.
            bool at_delim = pos < len && buf[pos] == delim;
            bool at_lf = pos < len && buf[pos] == '\n';
            bool at_crlf = pos + 1 < len && buf[pos] == '\r' && buf[pos + 1] == '\n';
            bool at_eof = pos >= len;
            if (at_delim || at_lf || at_crlf || at_eof) {
              if (needs_scratch) {
                flush_segment(content_end);
                field_starts[nfields] = -(scr_start + 1);
                field_lens[nfields] = (int32_t)(scr - scr_start);
              } else {
                field_starts[nfields] = plain_start;
                field_lens[nfields] = (int32_t)(content_end - plain_start);
              }
              nfields++; fields_in_rec++;
              if (at_delim) { pos++; break; }            // next field
              if (at_lf) { pos++; record_done = true; break; }
              if (at_crlf) { pos += 2; record_done = true; break; }
              record_done = true; break;                 // EOF
            }
            if (lazy_quotes) {
              // stray quote kept literally, stay inside quotes
              to_scratch_mode(content_end);
              flush_segment(content_end);
              if (scr < scratch_cap) scratch[scr++] = '"';
              seg_start = pos;
              continue;
            }
            *err_record = nrecords + 1;
            return CSV_ERR_QUOTE;
          }
          if (c == '\r' && pos + 1 < len && buf[pos + 1] == '\n') {
            if (pos + 2 >= len) {
              // CRLF directly at EOF is a record terminator, not field
              // data (csvio.py strips each line's terminator before
              // scanning) — defer to the EOF-inside-quotes handler,
              // which strips it from the segment
              pos += 2;
              continue;
            }
            // line break inside quotes normalizes to '\n'
            to_scratch_mode(pos);
            flush_segment(pos);
            if (scr < scratch_cap) scratch[scr++] = '\n';
            pos += 2;
            seg_start = pos;
            continue;
          }
          pos++;
        }
      } else {
        // ---- unquoted field ----
        int64_t start = pos;
        while (pos < len && buf[pos] != delim && buf[pos] != '\n') {
          if (buf[pos] == '"' && !lazy_quotes) {
            *err_record = nrecords + 1;
            return CSV_ERR_BARE_QUOTE;
          }
          pos++;
        }
        int64_t end = pos;
        // strip the '\r' of a "\r\n" terminator only — a lone trailing
        // '\r' at EOF is field data (csvio._strip_eol semantics)
        bool at_nl = pos < len && buf[pos] == '\n';
        if (at_nl && end > start && buf[end - 1] == '\r') end--;
        field_starts[nfields] = start;
        field_lens[nfields] = (int32_t)(end - start);
        nfields++; fields_in_rec++;
        if (pos < len && buf[pos] == delim) { pos++; continue; }  // next field
        if (pos < len) pos++;  // consume '\n'
        record_done = true;
      }
    }

    rec_counts[nrecords++] = fields_in_rec;
  }

  *scratch_used = scr;
  *err_record = nrecords;
  return nfields;
}

// how many records were produced before an error / at success is carried
// via err_record; a second entry point reports the record count for
// convenience when pre-sizing is needed.  flags_out also reports byte
// presence in the same single pass (bit0 quote, bit1 CR, bit2 comment
// char) so the simple-scan gate needs no extra full-buffer scans.
int64_t csv_count_bounds(const char* buf, int64_t len, char delim,
                         char comment, int64_t* max_fields_out,
                         int64_t* max_records_out, int64_t* flags_out) {
  int64_t d = 0, nl = 0;
  int64_t flags = 0;
  for (int64_t i = 0; i < len; i++) {
    const char c = buf[i];
    if (c == delim) d++;
    else if (c == '\n') nl++;
    else if (c == '"') flags |= 1;
    else if (c == '\r') flags |= 2;
    if (c == comment) flags |= 4;
  }
  *max_fields_out = d + nl + 2;
  *max_records_out = nl + 2;
  *flags_out = flags;
  return 0;
}

// Gather n (start, len) fields into NUL-padded fixed-width rows of
// `width` bytes — the dictionary-encode pre-pass.  Replaces a numpy
// index-matrix gather that allocated an (n, width) int64 index array;
// here it is one memcpy+memset per field.  Caller guarantees
// lens[i] <= width and starts[i] + lens[i] <= buffer length.
void csv_pack_fields(const char* buf, const int64_t* starts,
                     const int32_t* lens, int64_t n, int32_t width,
                     char* out) {
  for (int64_t i = 0; i < n; ++i) {
    char* dst = out + i * (int64_t)width;
    int32_t l = lens[i];
    memcpy(dst, buf + starts[i], (size_t)l);
    memset(dst + l, 0, (size_t)(width - l));
  }
}

// Same gather for fields of <= 8 bytes, packed big-endian (first byte
// most significant, NUL padding in the low bytes) straight into native
// uint64 values: integer order == byte order, and np.unique on a
// native scalar dtype is the fastest encode sort available.
void csv_pack_fields_u64(const char* buf, const int64_t* starts,
                         const int32_t* lens, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = 0;
    memcpy(&v, buf + starts[i], (size_t)lens[i]);
    out[i] = __builtin_bswap64(v);
  }
}

// Typed value lanes: parse n (start, len) fields as `prefix + canonical
// int32 suffix` — the affix form covering pure integers (empty prefix,
// sign allowed) and prefixed ids ("o123", "c45").  Canonical means the
// suffix round-trips bitwise through int->decimal formatting: "0" or
// [1-9][0-9]*, value <= INT32_MAX (negatives only with an empty prefix,
// no "-0", value >= -INT32_MAX so |v| always formats).  On the first
// call *prefix_len is -1 and the prefix derives from field 0 (longest
// canonical suffix; leading zeros join the prefix); later calls verify
// the caller's prefix.  Returns 1 when every field conforms (out[] is
// filled), 0 otherwise — a failed chunk costs one pass and the column
// falls back to dictionary encoding.
static inline int parse_canon_i32(const char* p, int32_t l, int allow_sign,
                                  int32_t* out) {
  if (l <= 0) return 0;
  int neg = 0;
  if (allow_sign && p[0] == '-') {
    neg = 1;
    p++;
    l--;
    if (l <= 0 || p[0] == '0') return 0;  // "-" / "-0" / "-0..." invalid
  }
  if (l > 10) return 0;
  if (l > 1 && p[0] == '0') return 0;  // leading zero
  int64_t v = 0;
  for (int32_t i = 0; i < l; ++i) {
    const char c = p[i];
    if (c < '0' || c > '9') return 0;
    v = v * 10 + (c - '0');
  }
  if (v > 2147483647) return 0;  // also rejects INT32_MIN via |v| bound
  *out = neg ? (int32_t)-v : (int32_t)v;
  return 1;
}

// ONE pack core shared by the contiguous and strided entry points
// (field i of the parse is flat field off + i*stride).
static int64_t pack_i32_core(const char* buf, const int64_t* starts,
                             const int32_t* lens, int64_t n, int64_t stride,
                             int64_t off, char* prefix_buf,
                             int64_t* prefix_len, int64_t prefix_cap,
                             int32_t* out) {
  if (n == 0) return 1;
  if (*prefix_len < 0) {
    // derive from the first field: whole-cell signed canonical -> empty
    // prefix; else prefix = cell minus its longest canonical suffix
    const char* f0 = buf + starts[off];
    const int32_t l0 = lens[off];
    if (parse_canon_i32(f0, l0, 1, out)) {
      *prefix_len = 0;
    } else {
      int32_t d0 = l0;  // start of the trailing digit run
      while (d0 > 0 && f0[d0 - 1] >= '0' && f0[d0 - 1] <= '9') d0--;
      int32_t s = d0;
      // shrink until the suffix is canonical AND fits int32
      while (s < l0 && !parse_canon_i32(f0 + s, l0 - s, 0, out)) s++;
      if (s >= l0) return 0;  // no usable numeric suffix
      if (s > prefix_cap) return 0;
      memcpy(prefix_buf, f0, (size_t)s);
      *prefix_len = s;
    }
  }
  const int64_t plen = *prefix_len;
  const int allow_sign = plen == 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t fi = off + i * stride;
    const char* f = buf + starts[fi];
    const int32_t l = lens[fi];
    if (l < plen || (plen && memcmp(f, prefix_buf, (size_t)plen) != 0))
      return 0;
    if (!parse_canon_i32(f + plen, l - (int32_t)plen, allow_sign, &out[i]))
      return 0;
  }
  return 1;
}

int64_t csv_pack_int32(const char* buf, const int64_t* starts,
                       const int32_t* lens, int64_t n, char* prefix_buf,
                       int64_t* prefix_len, int64_t prefix_cap,
                       int32_t* out) {
  return pack_i32_core(buf, starts, lens, n, 1, 0, prefix_buf, prefix_len,
                       prefix_cap, out);
}

// Strided variant for RECTANGULAR chunks: column `off` of record i sits
// at flat field index off + i*stride, so the per-column position-array
// gather (and its Python-side construction) disappears entirely — the
// single-core ingest profile's second-largest cost after the scan.
int64_t csv_pack_int32_strided(const char* buf, const int64_t* starts,
                               const int32_t* lens, int64_t n_records,
                               int64_t stride, int64_t off,
                               char* prefix_buf, int64_t* prefix_len,
                               int64_t prefix_cap, int32_t* out) {
  return pack_i32_core(buf, starts, lens, n_records, stride, off,
                       prefix_buf, prefix_len, prefix_cap, out);
}

// FUSED tokenize + typed parse for fully-typed rectangular chunks: one
// pass over the bytes, emitting int32 affix values per selected column
// and NOTHING else — no (start, len) offset arrays at all.  At 100M
// rows the two-pass path writes ~4.8GB of field offsets that the typed
// parse then re-reads; this replaces both with a single streaming pass.
//
// Contract (caller pre-checks): no quote/CR/comment bytes in the chunk,
// every selected column already in typed mode with an ESTABLISHED
// prefix, records end at '\n' (a final record may end at EOF), blank
// lines skip at record start.  `outs[c]` is the output array for field
// c, or NULL for unselected fields (skipped without typed constraints).
// Returns the record count on success, 0 to bail (any non-conforming
// cell, field-count mismatch, overflow past max_records) — the caller
// then reruns the chunk through the generic scan, which also owns the
// exact row-numbered error reporting.
int64_t csv_scan_parse_i32(const char* buf, int64_t len, char delim,
                           int64_t ncols, const char* prefix_blob,
                           const int64_t* prefix_off,
                           const int64_t* prefix_len, int32_t** outs,
                           int64_t max_records) {
  int64_t pos = 0;
  int64_t nrec = 0;
  while (pos < len) {
    if (buf[pos] == '\n') { pos++; continue; }  // blank line at record start
    if (nrec >= max_records) return 0;
    for (int64_t c = 0; c < ncols; ++c) {
      const char term = (c == ncols - 1) ? '\n' : delim;
      if (outs[c] == nullptr) {
        // unselected field: raw skip to terminator
        while (pos < len && buf[pos] != delim && buf[pos] != '\n') pos++;
      } else {
        const int64_t plen = prefix_len[c];
        const char* pfx = prefix_blob + prefix_off[c];
        if (pos + plen > len || memcmp(buf + pos, pfx, (size_t)plen) != 0)
          return 0;
        pos += plen;
        int neg = 0;
        if (plen == 0 && pos < len && buf[pos] == '-') { neg = 1; pos++; }
        if (pos >= len || buf[pos] < '0' || buf[pos] > '9') return 0;
        if (buf[pos] == '0') {
          // canonical: "0" must be the whole suffix
          outs[c][nrec] = 0;
          pos++;
          if (neg) return 0;  // "-0" never stored
          if (pos < len && buf[pos] >= '0' && buf[pos] <= '9') return 0;
        } else {
          int64_t v = 0;
          int digits = 0;
          while (pos < len && buf[pos] >= '0' && buf[pos] <= '9') {
            v = v * 10 + (buf[pos] - '0');
            if (++digits > 10) return 0;
            pos++;
          }
          if (v > 2147483647) return 0;
          outs[c][nrec] = neg ? (int32_t)-v : (int32_t)v;
        }
      }
      // terminator
      if (pos >= len) {
        // EOF terminates the LAST field of a record only
        if (c != ncols - 1) return 0;
      } else if (buf[pos] == term) {
        pos++;
      } else {
        return 0;  // wrong arity / stray byte
      }
    }
    nrec++;
  }
  return nrec;
}

// Format n int32 values as decimal into a fixed-width (n, width) byte
// matrix, NUL-padded — the typed column's demote/materialize pre-pass
// (the inverse of csv_pack_int32's parse).  Caller guarantees width >=
// 11 (sign + 10 digits).  lens_out gets each value's decimal length.
void csv_format_i32(const int32_t* values, int64_t n, int32_t width,
                    char* out, int32_t* lens_out) {
  for (int64_t i = 0; i < n; ++i) {
    char tmp[12];
    int32_t v = values[i];
    int p = 12;
    uint32_t a = v < 0 ? (uint32_t)(-(int64_t)v) : (uint32_t)v;
    do {
      tmp[--p] = (char)('0' + a % 10);
      a /= 10;
    } while (a);
    if (v < 0) tmp[--p] = '-';
    const int32_t l = 12 - p;
    char* dst = out + i * (int64_t)width;
    memcpy(dst, tmp + p, (size_t)l);
    memset(dst + l, 0, (size_t)(width - l));
    lens_out[i] = l;
  }
}

// CSV body assembly: scatter one column's escaped dictionary entries
// into a pre-sized row-major output buffer, appending `sep` after each
// field (',' mid-row, '\n' for the last column).  The caller computes
// per-row byte starts vectorized (dictionary entry lengths gathered by
// code + exclusive scan across columns); this loop is one memcpy per
// cell with zero Python objects.
void csv_scatter_fields(const char* blob, const int64_t* dict_off,
                        const int32_t* dict_len, const int32_t* codes,
                        const int64_t* starts, int64_t n, char sep,
                        char* out) {
  for (int64_t i = 0; i < n; ++i) {
    const int32_t c = codes[i];
    const int32_t l = dict_len[c];
    memcpy(out + starts[i], blob + dict_off[c], (size_t)l);
    out[starts[i] + l] = sep;
  }
}

// Unpack k big-endian-packed u64 dictionary values into NUL-padded
// fixed-width byte rows (the 'S{width}' dictionary array) — replaces a
// numpy (k, width) shift-and-mask broadcast that dominated the encode
// of high-cardinality columns.
void csv_u64_to_bytes(const uint64_t* uniq, int64_t k, int32_t width,
                      char* out) {
  for (int64_t i = 0; i < k; ++i) {
    const uint64_t be = __builtin_bswap64(uniq[i]);  // memory order = byte order
    memcpy(out + i * (int64_t)width, &be, (size_t)width);
  }
}

// Branchless-ish SWAR tokenizer for SIMPLE chunks: no quote bytes, no
// CR, no comment lines (caller prechecks with memchr).  Only field
// boundaries exist, so each record is delimiter-split text ending at
// '\n'; blank lines are skipped at record start like the full state
// machine.  Emits the same (starts, lens, counts) layout as csv_scan
// with nothing in scratch.  Returns total fields.
int64_t csv_scan_simple(const char* buf, int64_t len, char delim,
                        int64_t* field_starts, int32_t* field_lens,
                        int32_t* rec_counts, int64_t* nrec_out) {
  constexpr uint64_t kOnes = 0x0101010101010101ull;
  constexpr uint64_t kHighs = 0x8080808080808080ull;
  const uint64_t dmask = kOnes * (uint8_t)delim;
  const uint64_t nmask = kOnes * (uint8_t)'\n';
  int64_t nfields = 0;
  int64_t nrec = 0;
  int64_t pos = 0;
  while (pos < len) {
    if (buf[pos] == '\n') {  // blank line at record start: skip
      pos++;
      continue;
    }
    int32_t fields_in_rec = 0;
    int64_t field_start = pos;
    for (;;) {
      // scan 8 bytes at a time for delim or newline
      uint64_t hit = 0;
      while (pos + 8 <= len) {
        uint64_t w;
        memcpy(&w, buf + pos, 8);
        const uint64_t dx = w ^ dmask;
        const uint64_t nx = w ^ nmask;
        hit = ((dx - kOnes) & ~dx & kHighs) | ((nx - kOnes) & ~nx & kHighs);
        if (hit) break;
        pos += 8;
      }
      if (hit) {
        pos += __builtin_ctzll(hit) >> 3;
      } else {
        while (pos < len && buf[pos] != delim && buf[pos] != '\n') pos++;
      }
      field_starts[nfields] = field_start;
      field_lens[nfields] = (int32_t)(pos - field_start);
      nfields++;
      fields_in_rec++;
      if (pos >= len) break;            // EOF ends the record
      const char c = buf[pos++];
      if (c == '\n') break;             // record done
      field_start = pos;                // c == delim: next field
      if (pos >= len) {                 // trailing delimiter at EOF:
        field_starts[nfields] = pos;    // empty last field
        field_lens[nfields] = 0;
        nfields++;
        fields_in_rec++;
        break;
      }
    }
    rec_counts[nrec++] = fields_in_rec;
  }
  *nrec_out = nrec;
  return nfields;
}

// Hash-based dictionary encode for u64-packed fields: one linear-probe
// pass assigns provisional codes in first-seen order (uniq_out gets the
// distinct values unsorted; the caller sorts the small distinct set and
// rank-remaps the codes).  Returns the distinct count, or -1 when it
// exceeds max_k — high-cardinality columns bail to the sort path, so
// the probe table stays small and cache-resident for the low-
// cardinality columns this exists for.
}  // extern "C" — reopened below for the hash-encode wrappers

// splitmix64-style finalizer: every input bit affects every output bit.
// Packed fields carry their bytes big-endian (short values vary ONLY in
// the high bits), so a plain multiply-shift hash would drop exactly the
// bits that differ and collapse whole columns into one probe chain.
static inline uint64_t mix64(uint64_t h) {
  h ^= h >> 33;
  h *= 0xFF51AFD7ED558CCDull;
  h ^= h >> 33;
  h *= 0xC4CEB9FE1A85EC53ull;
  h ^= h >> 33;
  return h;
}

namespace {

// ONE open-addressing hash-encode core shared by the 1-lane and 2-lane
// entry points (a review found the two hand-copied variants drifting).
// Starts at a cache-resident 64K-slot table and rehash-doubles with the
// load kept <= 1/2; returns the distinct count, or -1 once max_k
// distinct values have been seen (the caller bails to a sort encode).
// `load(i)` yields row i's key; `store(k, key)` records distinct #k in
// first-seen order; prov_codes[i] gets row i's provisional code.
template <typename K, typename Load, typename Store>
int64_t hash_encode_core(int64_t n, int64_t max_k, Load load, Store store,
                         int32_t* prov_codes) {
  int64_t limit = 1 << 16;  // never below the starting capacity
  while (limit < 2 * max_k) limit <<= 1;
  int64_t cap = 1 << 16;
  K* keys = new K[cap];
  int32_t* slots = new int32_t[cap];
  memset(slots, 0xFF, (size_t)cap * sizeof(int32_t));  // -1 = empty
  uint64_t mask = (uint64_t)cap - 1;
  int64_t grow_at = cap >> 1;
  int64_t k = 0;
  for (int64_t i = 0; i < n; ++i) {
    const K v = load(i);
    uint64_t j = v.hash() & mask;
    for (;;) {
      const int32_t s = slots[j];
      if (s < 0) {
        if (k >= max_k) {
          delete[] keys;
          delete[] slots;
          return -1;
        }
        slots[j] = (int32_t)k;
        keys[j] = v;
        store(k, v);
        prov_codes[i] = (int32_t)k;
        k++;
        break;
      }
      if (keys[j] == v) {
        prov_codes[i] = s;
        break;
      }
      j = (j + 1) & mask;
    }
    if (k >= grow_at && cap < limit) {  // rehash-double
      const int64_t ncap = cap << 1;
      K* nkeys = new K[ncap];
      int32_t* nslots = new int32_t[ncap];
      memset(nslots, 0xFF, (size_t)ncap * sizeof(int32_t));
      const uint64_t nmask = (uint64_t)ncap - 1;
      for (int64_t o = 0; o < cap; ++o) {
        if (slots[o] < 0) continue;
        uint64_t j2 = keys[o].hash() & nmask;
        while (nslots[j2] >= 0) j2 = (j2 + 1) & nmask;
        nslots[j2] = slots[o];
        nkeys[j2] = keys[o];
      }
      delete[] keys;
      delete[] slots;
      keys = nkeys;
      slots = nslots;
      cap = ncap;
      mask = nmask;
      grow_at = cap >> 1;
    }
  }
  delete[] keys;
  delete[] slots;
  return k;
}

struct Key1 {
  uint64_t v;
  bool operator==(const Key1& o) const { return v == o.v; }
  uint64_t hash() const { return mix64(v); }
};

struct Key2 {
  uint64_t h, l;
  bool operator==(const Key2& o) const { return h == o.h && l == o.l; }
  uint64_t hash() const { return mix64(h ^ mix64(l)); }
};

}  // namespace

extern "C" {

// Hash-based dictionary encode for u64-packed (<= 8 byte) fields:
// provisional codes in first-seen order; the caller sorts the distinct
// set and rank-remaps.  -1 = bailed past max_k distinct.
int64_t csv_encode_hash_u64(const uint64_t* packed, int64_t n,
                            uint64_t* uniq_out, int32_t* prov_codes,
                            int64_t max_k) {
  return hash_encode_core<Key1>(
      n, max_k, [&](int64_t i) { return Key1{packed[i]}; },
      [&](int64_t k, const Key1& v) { uniq_out[k] = v.v; }, prov_codes);
}

// Two-lane variant for 9..16-byte fields packed as big-endian (hi, lo)
// u64 pairs.
int64_t csv_encode_hash_u64x2(const uint64_t* hi, const uint64_t* lo,
                              int64_t n, uint64_t* uniq_hi,
                              uint64_t* uniq_lo, int32_t* prov_codes,
                              int64_t max_k) {
  return hash_encode_core<Key2>(
      n, max_k, [&](int64_t i) { return Key2{hi[i], lo[i]}; },
      [&](int64_t k, const Key2& v) {
        uniq_hi[k] = v.h;
        uniq_lo[k] = v.l;
      },
      prov_codes);
}

}  // extern "C"
