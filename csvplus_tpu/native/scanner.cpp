// Native CSV chunk scanner.
//
// Single-pass byte-level state machine with the same semantics as the
// Python specification in csvplus_tpu/csvio.py (which mirrors the
// reference's use of Go encoding/csv, csvplus.go:1091-1097):
//   - records end at '\n' or "\r\n"; quoted fields may span lines;
//   - blank lines and comment-prefixed lines are skipped at record start;
//   - RFC-4180 quoting with "" doubling; without lazy_quotes a bare '"'
//     in an unquoted field or a stray '"' in a quoted field is an error;
//   - a trailing delimiter yields an empty last field.
//
// Output is COLUMNAR-friendly: no per-record allocations, just flat
// arrays of field (start, length) into the input buffer.  Fields that
// need transformation (escaped quotes, normalized line breaks inside
// quotes) are materialized into a caller-provided scratch buffer and
// flagged with a negative start: start = -(scratch_offset + 1).
//
// Returns the total number of fields parsed, or a negative error code
// with *err_record set to the 1-based record ordinal.

#include <cstdint>
#include <cstring>

extern "C" {

enum {
  CSV_ERR_BARE_QUOTE = -1,  // bare " in non-quoted field
  CSV_ERR_QUOTE = -2,       // extraneous or missing " in quoted-field
  CSV_ERR_OVERFLOW = -3,    // caller's arrays too small (should not happen)
};

int64_t csv_scan(const char* buf, int64_t len, char delim, char comment,
                 int has_comment, int lazy_quotes, int trim_space,
                 int64_t* field_starts, int32_t* field_lens,
                 int32_t* rec_counts, char* scratch, int64_t scratch_cap,
                 int64_t* scratch_used, int64_t max_fields,
                 int64_t max_records, int64_t* err_record) {
  int64_t pos = 0;
  int64_t nfields = 0;
  int64_t nrecords = 0;
  int64_t scr = 0;

  while (pos < len) {
    // ---- record start: skip blank lines and comment lines ----
    if (buf[pos] == '\n') { pos += 1; continue; }
    if (buf[pos] == '\r' && pos + 1 < len && buf[pos + 1] == '\n') {
      pos += 2; continue;
    }
    if (has_comment && buf[pos] == comment) {
      while (pos < len && buf[pos] != '\n') pos++;
      if (pos < len) pos++;  // consume '\n'
      continue;
    }

    if (nrecords >= max_records) { *err_record = nrecords; return CSV_ERR_OVERFLOW; }
    int32_t fields_in_rec = 0;
    bool record_done = false;

    while (!record_done) {
      // ---- one field ----
      if (nfields >= max_fields) { *err_record = nrecords + 1; return CSV_ERR_OVERFLOW; }
      if (trim_space) {
        while (pos < len && (buf[pos] == ' ' || buf[pos] == '\t')) pos++;
      }

      if (pos < len && buf[pos] == '"') {
        // ---- quoted field ----
        pos++;
        int64_t seg_start = pos;   // current contiguous segment
        bool needs_scratch = false;
        int64_t scr_start = scr;   // scratch offset if transformed
        int64_t plain_start = pos; // zero-copy range when !needs_scratch
        int64_t plain_len = 0;

        auto flush_segment = [&](int64_t upto) {
          // append [seg_start, upto) to scratch
          int64_t n = upto - seg_start;
          if (n > 0) {
            if (scr + n > scratch_cap) n = scratch_cap - scr;  // defensive
            std::memcpy(scratch + scr, buf + seg_start, n);
            scr += n;
          }
        };
        auto to_scratch_mode = [&](int64_t upto) {
          if (!needs_scratch) {
            needs_scratch = true;
            scr_start = scr;
            seg_start = plain_start;
            flush_segment(upto);
            seg_start = upto;
          }
        };

        for (;;) {
          if (pos >= len) {
            // EOF inside quotes
            if (!lazy_quotes) { *err_record = nrecords + 1; return CSV_ERR_QUOTE; }
            // the Python spec strips each line's terminator before
            // scanning, so a terminator right at EOF is not field data
            int64_t end = pos;
            if (end > seg_start && buf[end - 1] == '\n') {
              end--;
              if (end > seg_start && buf[end - 1] == '\r') end--;
            }
            if (needs_scratch) {
              flush_segment(end);
              field_starts[nfields] = -(scr_start + 1);
              field_lens[nfields] = (int32_t)(scr - scr_start);
            } else {
              field_starts[nfields] = plain_start;
              field_lens[nfields] = (int32_t)(end - plain_start);
            }
            nfields++; fields_in_rec++;
            record_done = true;
            break;
          }
          char c = buf[pos];
          if (c == '"') {
            if (pos + 1 < len && buf[pos + 1] == '"') {
              // doubled quote -> literal "
              to_scratch_mode(pos);
              flush_segment(pos);  // seg_start..pos (content before quote)
              if (scr < scratch_cap) scratch[scr++] = '"';
              pos += 2;
              seg_start = pos;
              continue;
            }
            // closing quote
            int64_t content_end = pos;
            pos++;
            // NOTE: a lone '\r' at EOF is NOT a terminator (the Python
            // spec only strips "\r\n" pairs), so '"..."\r<EOF>' is a
            // stray-quote situation, matching csvio.py.
            bool at_delim = pos < len && buf[pos] == delim;
            bool at_lf = pos < len && buf[pos] == '\n';
            bool at_crlf = pos + 1 < len && buf[pos] == '\r' && buf[pos + 1] == '\n';
            bool at_eof = pos >= len;
            if (at_delim || at_lf || at_crlf || at_eof) {
              if (needs_scratch) {
                flush_segment(content_end);
                field_starts[nfields] = -(scr_start + 1);
                field_lens[nfields] = (int32_t)(scr - scr_start);
              } else {
                field_starts[nfields] = plain_start;
                field_lens[nfields] = (int32_t)(content_end - plain_start);
              }
              nfields++; fields_in_rec++;
              if (at_delim) { pos++; break; }            // next field
              if (at_lf) { pos++; record_done = true; break; }
              if (at_crlf) { pos += 2; record_done = true; break; }
              record_done = true; break;                 // EOF
            }
            if (lazy_quotes) {
              // stray quote kept literally, stay inside quotes
              to_scratch_mode(content_end);
              flush_segment(content_end);
              if (scr < scratch_cap) scratch[scr++] = '"';
              seg_start = pos;
              continue;
            }
            *err_record = nrecords + 1;
            return CSV_ERR_QUOTE;
          }
          if (c == '\r' && pos + 1 < len && buf[pos + 1] == '\n') {
            // line break inside quotes normalizes to '\n'
            to_scratch_mode(pos);
            flush_segment(pos);
            if (scr < scratch_cap) scratch[scr++] = '\n';
            pos += 2;
            seg_start = pos;
            continue;
          }
          pos++;
        }
      } else {
        // ---- unquoted field ----
        int64_t start = pos;
        while (pos < len && buf[pos] != delim && buf[pos] != '\n') {
          if (buf[pos] == '"' && !lazy_quotes) {
            *err_record = nrecords + 1;
            return CSV_ERR_BARE_QUOTE;
          }
          pos++;
        }
        int64_t end = pos;
        // strip the '\r' of a "\r\n" terminator only — a lone trailing
        // '\r' at EOF is field data (csvio._strip_eol semantics)
        bool at_nl = pos < len && buf[pos] == '\n';
        if (at_nl && end > start && buf[end - 1] == '\r') end--;
        field_starts[nfields] = start;
        field_lens[nfields] = (int32_t)(end - start);
        nfields++; fields_in_rec++;
        if (pos < len && buf[pos] == delim) { pos++; continue; }  // next field
        if (pos < len) pos++;  // consume '\n'
        record_done = true;
      }
    }

    rec_counts[nrecords++] = fields_in_rec;
  }

  *scratch_used = scr;
  *err_record = nrecords;
  return nfields;
}

// how many records were produced before an error / at success is carried
// via err_record; a second entry point reports the record count for
// convenience when pre-sizing is needed.
int64_t csv_count_bounds(const char* buf, int64_t len, char delim,
                         int64_t* max_fields_out, int64_t* max_records_out) {
  int64_t d = 0, nl = 0;
  for (int64_t i = 0; i < len; i++) {
    if (buf[i] == delim) d++;
    else if (buf[i] == '\n') nl++;
  }
  *max_fields_out = d + nl + 2;
  *max_records_out = nl + 2;
  return 0;
}

// Gather n (start, len) fields into NUL-padded fixed-width rows of
// `width` bytes — the dictionary-encode pre-pass.  Replaces a numpy
// index-matrix gather that allocated an (n, width) int64 index array;
// here it is one memcpy+memset per field.  Caller guarantees
// lens[i] <= width and starts[i] + lens[i] <= buffer length.
void csv_pack_fields(const char* buf, const int64_t* starts,
                     const int32_t* lens, int64_t n, int32_t width,
                     char* out) {
  for (int64_t i = 0; i < n; ++i) {
    char* dst = out + i * (int64_t)width;
    int32_t l = lens[i];
    memcpy(dst, buf + starts[i], (size_t)l);
    memset(dst + l, 0, (size_t)(width - l));
  }
}

// Same gather for fields of <= 8 bytes, packed big-endian (first byte
// most significant, NUL padding in the low bytes) straight into native
// uint64 values: integer order == byte order, and np.unique on a
// native scalar dtype is the fastest encode sort available.
void csv_pack_fields_u64(const char* buf, const int64_t* starts,
                         const int32_t* lens, int64_t n, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) {
    uint64_t v = 0;
    memcpy(&v, buf + starts[i], (size_t)lens[i]);
    out[i] = __builtin_bswap64(v);
  }
}

}  // extern "C"
