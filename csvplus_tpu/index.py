"""Index: a sorted, materialized collection of Rows with O(log n) search.

Reference: csvplus.go:610-920.  Rows are sorted lexicographically by the
key columns (byte order — Python's str comparison equals Go's
``strings.Compare`` on UTF-8 because UTF-8 byte order preserves code-point
order), searched by binary search, and optionally persisted.

Semantics preserved:

* building an index fully materializes the source (csvplus.go:722-733) and
  validates every row has all key columns, with the reference's exact
  error message;
* ``find``/``sub_index`` accept a *prefix* of the key values and return
  zero-copy row ranges (csvplus.go:869-891);
* joins never mutate the index (pinned by csvplus_test.go:325-365);
* ``resolve_duplicates`` calls the user back once per duplicate group; the
  returned row replaces the group when it has at least as many cells as
  there are key columns, an empty row drops the group (csvplus.go:643-653,
  809-867).

**Known divergence from the reference (intentional):** the reference's
in-place compaction drops the final row of the index whenever the last
row is a *singleton* following a duplicate group (``dedup``
csvplus.go:842,851-859 never flushes the trailing pending row; its own
tests never check the index contents afterwards, so the data loss is
invisible upstream).  This implementation keeps that row.

The optional ``device_table`` attribute carries an HBM-resident columnar
copy of the index (built by ``on_device()``), used by the device join/
search kernels in M3+.
"""

from __future__ import annotations

import bisect
import json
from typing import Callable, List, Optional, Sequence, Tuple

from .errors import CsvPlusError
from .row import Row, all_columns_unique, equal_rows
from .source import DataSource, RowFunc, iterate, take_rows

_MAGIC = "csvplus-tpu-index"
_VERSION = 1


class IndexImpl:
    """Sorted rows + key column list (reference ``indexImpl``
    csvplus.go:785-788)."""

    __slots__ = ("rows", "columns", "_keys")

    def __init__(self, rows: List[Row], columns: Sequence[str]):
        self.rows = rows
        self.columns = list(columns)
        self._keys: Optional[List[Tuple[str, ...]]] = None

    # -- key cache ---------------------------------------------------------

    @property
    def keys(self) -> List[Tuple[str, ...]]:
        """Per-row key tuples, built lazily and invalidated on mutation."""
        if self._keys is None:
            cols = self.columns
            self._keys = [tuple(r[c] for c in cols) for r in self.rows]
        return self._keys

    def _invalidate(self) -> None:
        self._keys = None

    def sort(self) -> None:
        """Sort rows by the key columns (csvplus.go:794-807).  Stable —
        a deterministic refinement of the reference's unstable sort."""
        cols = self.columns
        self.rows.sort(key=lambda r: tuple(r[c] for c in cols))
        self._invalidate()

    # -- binary search (csvplus.go:869-920) --------------------------------

    def bounds(self, values: Sequence[str]) -> Tuple[int, int]:
        """[lower, upper) range of rows whose key prefix equals *values*."""
        if not values:
            return 0, len(self.rows)
        if len(values) > len(self.columns):
            raise ValueError("too many columns in Index.find()")
        k = len(values)
        v = tuple(values)
        keys = self.keys
        lower = bisect.bisect_left(keys, v, key=lambda kt: kt[:k])
        upper = bisect.bisect_right(keys, v, lo=lower, key=lambda kt: kt[:k])
        return lower, upper

    def find_rows(self, values: Sequence[str]) -> List[Row]:
        """Zero-copy row range matching the key prefix (csvplus.go:870-891)."""
        lower, upper = self.bounds(values)
        return self.rows[lower:upper]

    def has(self, values: Sequence[str]) -> bool:
        """True when any row matches the key prefix (csvplus.go:899-905)."""
        lower, upper = self.bounds(values)
        return lower < upper

    # -- deduplication (csvplus.go:809-867) --------------------------------

    def dedup(self, resolve: Callable[[List[Row]], Optional[Row]]) -> None:
        rows, cols = self.rows, self.columns
        out: List[Row] = []
        i, n = 0, len(rows)
        changed = False
        while i < n:
            j = i + 1
            while j < n and equal_rows(cols, rows[i], rows[j]):
                j += 1
            if j - i == 1:
                out.append(rows[i])
            else:
                changed = True
                chosen = resolve(rows[i:j])
                # keep the chosen row unless it is 'empty' — the reference's
                # emptiness test is len(row) >= len(key columns)
                # (csvplus.go:845-848)
                if chosen is not None and len(chosen) >= len(cols):
                    out.append(chosen if isinstance(chosen, Row) else Row(chosen))
            i = j
        if changed:
            self.rows = out
            self._invalidate()


class Index:
    """Sorted collection of Rows; see module docstring.

    Reference: ``Index`` csvplus.go:610-653.
    """

    def __init__(self, impl: IndexImpl):
        self._impl = impl
        self.device_table = None  # set by on_device(); used by device kernels

    # -- iteration ---------------------------------------------------------

    def iterate(self, fn: RowFunc) -> None:
        """Iterate rows in key order, cloning each (csvplus.go:618-620)."""
        iterate(self._impl.rows, fn)

    Iterate = iterate

    def __iter__(self):
        return iter(take_rows(self._impl.rows))

    def __len__(self) -> int:
        return len(self._impl.rows)

    @property
    def columns(self) -> List[str]:
        return list(self._impl.columns)

    # -- queries -----------------------------------------------------------

    def find(self, *values: str) -> DataSource:
        """Lazy source over all rows matching the key-value prefix
        (csvplus.go:625-627)."""
        return take_rows(self._impl.find_rows(values))

    def sub_index(self, *values: str) -> "Index":
        """Index of the rows matching the key prefix, keyed on the
        remaining columns (csvplus.go:632-641)."""
        if len(values) >= len(self._impl.columns):
            raise ValueError("too many values in SubIndex()")
        return Index(
            IndexImpl(
                self._impl.find_rows(values),
                self._impl.columns[len(values):],
            )
        )

    def resolve_duplicates(
        self, resolve: Callable[[List[Row]], Optional[Row]]
    ) -> None:
        """Resolve groups of rows with duplicate keys (csvplus.go:643-653).

        *resolve* receives each duplicate group and returns the single row
        to keep, an empty row/None to drop the group, or raises to abort.
        """
        self._impl.dedup(resolve)
        self.device_table = None  # stale after mutation

    # -- persistence (csvplus.go:655-705) ----------------------------------

    def write_to(self, file_name: str) -> None:
        """Persist the index; the file is removed on any write error, like
        the reference's gob writer (csvplus.go:656-680).

        Format: versioned JSON-lines — a header object, then one row per
        line.  (A gob-compatible shim is a non-goal; SURVEY.md §5.)
        """
        from .sinks import _write_file

        def dump(f):
            f.write(
                json.dumps(
                    {
                        "magic": _MAGIC,
                        "version": _VERSION,
                        "columns": self._impl.columns,
                        "count": len(self._impl.rows),
                    }
                )
            )
            f.write("\n")
            for row in self._impl.rows:
                f.write(json.dumps(row, sort_keys=True, separators=(",", ":")))
                f.write("\n")

        _write_file(file_name, dump)

    WriteTo = write_to

    # -- device hook (M3) --------------------------------------------------

    def on_device(self, device: str = "tpu") -> "Index":
        """Attach an HBM-resident columnar copy of this index so joins and
        finds against it run as device kernels."""
        from .columnar.ingest import index_to_device

        self.device_table = index_to_device(self, device=device)
        return self

    OnDevice = on_device

    # Go-style aliases
    Find = find
    SubIndex = sub_index
    ResolveDuplicates = resolve_duplicates


def load_index(file_name: str) -> Index:
    """Load an index persisted by :meth:`Index.write_to`
    (csvplus.go:683-705)."""
    with open(file_name, "r", encoding="utf-8") as f:
        header = json.loads(f.readline())
        if header.get("magic") != _MAGIC:
            raise ValueError(f"{file_name}: not a csvplus-tpu index file")
        if header.get("version") != _VERSION:
            raise ValueError(
                f"{file_name}: unsupported index version {header.get('version')}"
            )
        rows = [Row(json.loads(line)) for line in f if line.strip()]
    if len(rows) != header.get("count"):
        raise ValueError(
            f"{file_name}: truncated index file "
            f"({len(rows)} rows, expected {header.get('count')})"
        )
    return Index(IndexImpl(rows, header["columns"]))


def create_index(src, columns: Sequence[str]) -> Index:
    """Materialize and sort an index (csvplus.go:707-738)."""
    columns = tuple(columns)
    if len(columns) == 0:
        raise ValueError("empty column list in CreateIndex()")
    if len(columns) > 1 and not all_columns_unique(columns):
        raise ValueError("duplicate column name(s) in CreateIndex()")

    rows: List[Row] = []

    def collect(row: Row) -> None:
        for col in columns:
            if col not in row:
                raise ValueError(f'missing column "{col}" while creating an index')
        rows.append(row)

    src(collect)

    impl = IndexImpl(rows, columns)
    impl.sort()
    return Index(impl)


def create_unique_index(src, columns: Sequence[str]) -> Index:
    """Index build + duplicate-key check (csvplus.go:740-756)."""
    index = create_index(src, columns)
    rows = index._impl.rows
    cols = index._impl.columns
    for i in range(1, len(rows)):
        if equal_rows(cols, rows[i - 1], rows[i]):
            raise CsvPlusError(
                "duplicate value while creating unique index: "
                + str(rows[i].select_existing(*cols))
            )
    return index
