"""Index: a sorted, materialized collection of Rows with O(log n) search.

Reference: csvplus.go:610-920.  Rows are sorted lexicographically by the
key columns (byte order — Python's str comparison equals Go's
``strings.Compare`` on UTF-8 because UTF-8 byte order preserves code-point
order), searched by binary search, and optionally persisted.

Semantics preserved:

* building an index fully materializes the source (csvplus.go:722-733) and
  validates every row has all key columns, with the reference's exact
  error message;
* ``find``/``sub_index`` accept a *prefix* of the key values and return
  zero-copy row ranges (csvplus.go:869-891);
* joins never mutate the index (pinned by csvplus_test.go:325-365);
* ``resolve_duplicates`` calls the user back once per duplicate group; the
  returned row replaces the group when it has at least as many cells as
  there are key columns, an empty row drops the group (csvplus.go:643-653,
  809-867).

**Known divergence from the reference (intentional):** the reference's
in-place compaction drops the final row of the index whenever the last
row is a *singleton* following a duplicate group (``dedup``
csvplus.go:842,851-859 never flushes the trailing pending row; its own
tests never check the index contents afterwards, so the data loss is
invisible upstream).  This implementation keeps that row.

TPU-native execution: an index built from a device-planned source is
**device-resident and lazy** — the sort runs as a fused multi-key
``lax.sort`` over dictionary codes (:mod:`..ops.sort`), the uniqueness
check is one adjacent-equality reduction, ``find``/``sub_index`` binary-
search the packed key array and decode *only the matching range*, and
``resolve_duplicates`` with a named policy ("first"/"last") compacts via
a run-boundary mask without ever materializing host rows.  Host rows are
decoded on demand the first time a host-only operation (arbitrary
callback, persistence, host join) needs them.
"""

from __future__ import annotations

import bisect
import json
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from .errors import CsvPlusError, DataSourceError
from .row import Row, all_columns_unique, equal_rows
from .source import DataSource, RowFunc, iterate, take_rows

_MAGIC = "csvplus-tpu-index"
_VERSION = 1

Resolver = Union[str, Callable[[List[Row]], Optional[Row]]]


class IndexImpl:
    """Sorted rows + key column list (reference ``indexImpl``
    csvplus.go:785-788).  ``rows`` may be lazily backed by a sorted
    device table (``dev``), decoded on first host access."""

    __slots__ = ("_rows", "columns", "_keys", "_probe_map", "dev", "_lock")

    def __init__(self, rows: Optional[List[Row]], columns: Sequence[str], dev=None):
        self._rows = rows
        self.columns = list(columns)
        self._keys: Optional[List[Tuple[str, ...]]] = None
        # full-width key tuple -> (lower, upper); built lazily for the
        # host join's per-row probes (hash beats bisect like the Go
        # baseline's map would); prefix probes still bisect
        self._probe_map: "Optional[dict]" = None
        self.dev = dev  # ops.join.DeviceIndex over the sorted columnar copy
        # serializes the lazy builds (row materialization, key cache,
        # probe map) under concurrent readers — without it two serving
        # threads each pay the O(n) build and one result is discarded.
        # RLock because keys->rows nest.  Probes against an index while
        # a writer MUTATES it (rows setter / sort / dedup) remain a
        # caller error; the lock makes concurrent READS safe.
        self._lock = threading.RLock()

    # -- lazy materialization ---------------------------------------------

    @property
    def is_lazy(self) -> bool:
        return self._rows is None

    @property
    def rows(self) -> List[Row]:
        if self._rows is None:
            with self._lock:
                if self._rows is None:  # double-checked under the lock
                    assert self.dev is not None
                    self._rows = self.dev.table.to_rows()
        return self._rows

    @rows.setter
    def rows(self, value: List[Row]) -> None:
        self._rows = value
        self._invalidate()

    def __len__(self) -> int:
        if self._rows is None and self.dev is not None:
            return self.dev.table.nrows
        return len(self.rows)

    # -- key cache ---------------------------------------------------------

    @property
    def keys(self) -> List[Tuple[str, ...]]:
        """Per-row key tuples, built lazily and invalidated on mutation.
        Concurrent first reads build once under ``_lock``."""
        if self._keys is None:
            with self._lock:
                if self._keys is None:
                    cols = self.columns
                    self._keys = [tuple(r[c] for c in cols) for r in self.rows]
        return self._keys

    def _invalidate(self) -> None:
        self._keys = None
        self._probe_map = None

    def sort(self) -> None:
        """Sort rows by the key columns (csvplus.go:794-807).  Stable —
        a deterministic refinement of the reference's unstable sort."""
        cols = self.columns
        self.rows.sort(key=lambda r: tuple(r[c] for c in cols))
        self._invalidate()

    # -- binary search (csvplus.go:869-920) --------------------------------

    def bounds(self, values: Sequence[str]) -> Tuple[int, int]:
        """[lower, upper) range of rows whose key prefix equals *values*.

        Device-lazy indexes search the packed key array; materialized ones
        bisect the host key tuples.
        """
        if len(values) > len(self.columns):
            raise ValueError("too many columns in Index.find()")
        if self._rows is None and self.dev is not None and self.dev.supported:
            return self.dev.point_bounds(list(values))
        if not values:
            return 0, len(self.rows)
        k = len(values)
        v = tuple(values)
        if k == len(self.columns):
            return self._ensure_probe_map().get(v, (0, 0))
        keys = self.keys
        lower = bisect.bisect_left(keys, v, key=lambda kt: kt[:k])
        upper = bisect.bisect_right(keys, v, lo=lower, key=lambda kt: kt[:k])
        return lower, upper

    def _ensure_probe_map(self) -> Dict[Tuple[str, ...], Tuple[int, int]]:
        """Full-width key tuple -> [lower, upper), built lazily in one
        O(n) sweep (once, under ``_lock``) and invalidated on mutation."""
        pm = self._probe_map
        if pm is None:
            with self._lock:
                pm = self._probe_map
                if pm is None:
                    pm = {}
                    keys = self.keys
                    i, n = 0, len(keys)
                    while i < n:
                        j = i + 1
                        while j < n and keys[j] == keys[i]:
                            j += 1
                        pm[keys[i]] = (i, j)
                        i = j
                    self._probe_map = pm
        return pm

    def bounds_many(
        self, probes: Sequence[Sequence[str]]
    ) -> List[Tuple[int, int]]:
        """Batched :meth:`bounds` — the host half of the lookup engine.

        Device-lazy indexes take ONE vectorized pass over the packed key
        array (``DeviceIndex.point_bounds_many``).  Host indexes answer
        full-width probes from the probe map and sweep each prefix width
        in sorted probe order, so the bisect window only ever narrows —
        a single forward pass over the key tuples instead of a fresh
        full-range binary search per probe.
        """
        for p in probes:
            if len(p) > len(self.columns):
                raise ValueError("too many columns in Index.find()")
        if self._rows is None and self.dev is not None and self.dev.supported:
            return self.dev.point_bounds_many(probes)
        n = len(self.rows)
        full = len(self.columns)
        out: List[Optional[Tuple[int, int]]] = [None] * len(probes)
        by_k: Dict[int, List[int]] = {}
        for i, p in enumerate(probes):
            k = len(p)
            if k == 0:
                out[i] = (0, n)
            elif k == full:
                out[i] = self._ensure_probe_map().get(tuple(p), (0, 0))
            else:
                by_k.setdefault(k, []).append(i)
        if by_k:
            keys = self.keys
            for k, idxs in by_k.items():
                idxs.sort(key=lambda i: tuple(probes[i]))
                lo = 0
                prev: Optional[Tuple[str, ...]] = None
                prev_bounds = (0, 0)
                for i in idxs:
                    v = tuple(probes[i])
                    if v == prev:
                        out[i] = prev_bounds  # duplicate probe: memoized
                        continue
                    lower = bisect.bisect_left(
                        keys, v, lo=lo, key=lambda kt: kt[:k]
                    )
                    upper = bisect.bisect_right(
                        keys, v, lo=lower, key=lambda kt: kt[:k]
                    )
                    out[i] = prev_bounds = (lower, upper)
                    prev, lo = v, lower
        return out  # type: ignore[return-value]

    def find_rows(self, values: Sequence[str]) -> List[Row]:
        """Row range matching the key prefix (csvplus.go:870-891).

        On a device-lazy index only the matching range is decoded.
        Routed through the batched engine so the fast path is the only
        path.
        """
        return self.find_rows_many([values])[0]

    def find_rows_many(
        self, probes: Sequence[Sequence[str]]
    ) -> List[List[Row]]:
        """Batched :meth:`find_rows`: all bounds in one vectorized pass
        (:meth:`bounds_many`), then ONE amortized decode over the union
        of matched ranges (:meth:`rows_for_bounds`)."""
        return self.rows_for_bounds(self.bounds_many(probes))

    def rows_for_bounds(
        self, bounds: Sequence[Tuple[int, int]]
    ) -> List[List[Row]]:
        """Decode one row block per [lower, upper) range.

        On a device-lazy index the matched ranges decode together: the
        mirror tier batches through the LRU-cached
        :meth:`~csvplus_tpu.columnar.table.DeviceTable.rows_from_mirror_many`,
        the above-cap tier pays ONE device gather + decode for the whole
        batch instead of a transfer per probe.
        """
        if self._rows is None and self.dev is not None:
            from .ops.join import DeviceIndex

            table = self.dev.table
            # gate on total CELLS, not rows: the mirror transfers every
            # column, so a wide table must not blow the transfer budget
            cells = table.nrows * max(len(table.columns), 1)
            if cells <= DeviceIndex.POINT_MIRROR_MAX_KEYS:
                # small index: decode from host code mirrors (one O(n)
                # transfer on the first find, then pure numpy per lookup
                # — no device round trip)
                return table.rows_from_mirror_many(bounds)
            out: List[List[Row]] = [[] for _ in bounds]
            hit = [
                (i, int(lo), int(hi))
                for i, (lo, hi) in enumerate(bounds)
                if hi > lo
            ]
            if hit:
                idx = np.concatenate(
                    [np.arange(lo, hi, dtype=np.int64) for _, lo, hi in hit]
                )
                rows = table.to_rows(idx)
                off = 0
                for i, lo, hi in hit:
                    out[i] = rows[off : off + (hi - lo)]
                    off += hi - lo
            return out
        rows = self.rows
        return [rows[lo:hi] for lo, hi in bounds]

    def has(self, values: Sequence[str]) -> bool:
        """True when any row matches the key prefix (csvplus.go:899-905)."""
        lower, upper = self.bounds(values)
        return lower < upper

    # -- deduplication (csvplus.go:809-867) --------------------------------

    def dedup(self, resolve: Callable[[List[Row]], Optional[Row]]) -> None:
        rows, cols = self.rows, self.columns
        out: List[Row] = []
        i, n = 0, len(rows)
        changed = False
        while i < n:
            j = i + 1
            while j < n and equal_rows(cols, rows[i], rows[j]):
                j += 1
            if j - i == 1:
                out.append(rows[i])
            else:
                changed = True
                chosen = resolve(rows[i:j])
                # keep the chosen row unless it is 'empty' — the reference's
                # emptiness test is len(row) >= len(key columns)
                # (csvplus.go:845-848)
                if chosen is not None and len(chosen) >= len(cols):
                    out.append(chosen if isinstance(chosen, Row) else Row(chosen))
            i = j
        if changed:
            self.rows = out


class Index:
    """Sorted collection of Rows; see module docstring.

    Reference: ``Index`` csvplus.go:610-653.
    """

    def __init__(self, impl: IndexImpl):
        self._impl = impl
        # DeviceIndex over the sorted columnar copy (None = host-only);
        # used by device joins/finds.  Kept in sync with impl.dev.
        self.device_table = impl.dev

    # -- iteration ---------------------------------------------------------

    def materialize(self) -> "Index":
        """Decode a device-lazy index into host rows (idempotent).  Host
        row-at-a-time consumers call this once instead of paying a device
        round-trip per lookup."""
        _ = self._impl.rows
        return self

    def sync(self) -> "Index":
        """Block until the device-side build (sort + gathers) has actually
        executed; no-op for host indexes.  Without this, the async build
        completes under whatever operation first touches the index —
        misattributing build time to e.g. the first ``find`` (the round-3
        bench's "device find" tier measured exactly that)."""
        if self._impl.dev is not None:
            self._impl.dev.table.sync()
        return self

    def iterate(self, fn: RowFunc) -> None:
        """Iterate rows in key order, cloning each (csvplus.go:618-620)."""
        iterate(self._impl.rows, fn)

    Iterate = iterate

    def __iter__(self):
        return iter(take_rows(self._impl.rows))

    def __len__(self) -> int:
        return len(self._impl)

    @property
    def columns(self) -> List[str]:
        return list(self._impl.columns)

    # -- queries -----------------------------------------------------------

    def find(self, *values: str) -> DataSource:
        """Lazy source over all Rows matching the key-value prefix
        (csvplus.go:625-627); on a device index only the matching range
        is ever decoded.  Routed through :meth:`find_many` so the
        batched engine is the only lookup path."""
        return self.find_many([values])[0]

    def find_many(self, probes: Sequence) -> List[DataSource]:
        """Batched :meth:`find`: one DataSource per key-prefix probe.

        Each probe is a sequence of key values (a bare string means a
        one-column prefix).  The whole batch runs through one vectorized
        bounds search and one amortized decode — on the 1M-row big-index
        shape this is the difference between ~19K and >100K lookups/s —
        and each result is byte-identical to the matching single
        ``find`` call.  On a supported device index every result also
        carries a :class:`~csvplus_tpu.plan.Lookup` leaf plan, so
        downstream symbolic stages keep lowering to the device.
        """
        impl = self._impl
        norm = [
            (p,) if isinstance(p, str) else tuple(p) for p in probes
        ]
        bounds = impl.bounds_many(norm)
        groups = impl.rows_for_bounds(bounds)
        device_tier = (
            impl._rows is None and impl.dev is not None and impl.dev.supported
        )
        if device_tier:
            from .plan import Lookup

            dev_table = impl.dev.table
            out = []
            # hand-inlined take_rows: per-probe cost is what separates
            # ~90K from >100K lookups/s on the 1M-row micro shape.  The
            # decoded blocks may be shared with the mirror LRU — safe
            # because every delivery path clones (iterate / _rows_hint).
            for rows, (lo, hi) in zip(groups, bounds):
                src = DataSource(
                    lambda fn, _rows=rows: iterate(_rows, fn)
                )
                src._rows_hint = rows
                src.plan = Lookup(dev_table, lo, hi)
                out.append(src)
            return out
        return [take_rows(rows) for rows in groups]

    def sub_index(self, *values: str) -> "Index":
        """Index of the rows matching the key prefix, keyed on the
        remaining columns (csvplus.go:632-641)."""
        impl = self._impl
        if len(values) >= len(impl.columns):
            raise ValueError("too many values in SubIndex()")
        rest = impl.columns[len(values):]
        if impl.is_lazy and impl.dev is not None and impl.dev.supported:
            from .ops.join import DeviceIndex

            lower, upper = impl.dev.point_bounds(list(values))
            sub_table = impl.dev.table.gather(
                np.arange(lower, upper, dtype=np.int64)
            )
            return Index(IndexImpl(None, rest, dev=DeviceIndex.build(sub_table, rest)))
        return Index(IndexImpl(impl.find_rows(values), rest))

    def resolve_duplicates(self, resolve: Resolver) -> None:
        """Resolve groups of rows with duplicate keys (csvplus.go:643-653).

        *resolve* is either a callback receiving each duplicate group and
        returning the single row to keep (empty row/None drops the group,
        raising aborts) — or a named device-friendly policy:

        * ``"first"`` — keep the first row of each duplicate group (in
          index order), equivalent to ``lambda g: g[0]``;
        * ``"last"`` — keep the last row, equivalent to ``lambda g: g[-1]``.

        Named policies on a device-lazy index compact via a run-boundary
        mask on device without materializing host rows.
        """
        impl = self._impl
        if isinstance(resolve, str):
            if resolve not in ("first", "last"):
                raise ValueError(f"unknown duplicate-resolution policy {resolve!r}")
            if impl.is_lazy and impl.dev is not None:
                self._device_policy_dedup(resolve)
                return
            resolve = (lambda g: g[0]) if resolve == "first" else (lambda g: g[-1])
        elif impl.is_lazy and impl.dev is not None:
            if self._device_callback_dedup(resolve):
                return
        impl.dedup(resolve)
        self.device_table = None  # columnar copy is stale after mutation
        impl.dev = None

    def _device_policy_dedup(self, policy: str) -> None:
        from .ops.join import DeviceIndex
        from .ops.sort import run_starts

        impl = self._impl
        table = impl.dev.table
        starts = run_starts(table, impl.columns)
        if policy == "first":
            keep = starts
        else:  # "last": a row is kept when the NEXT row starts a new run
            keep = np.roll(starts, -1)
            if keep.size:
                keep[-1] = True
        if keep.all():
            return  # no duplicates; nothing to do
        sel = np.flatnonzero(keep).astype(np.int64)
        new_table = table.gather(sel)
        impl.dev = DeviceIndex.build(new_table, impl.columns)
        impl._rows = None
        impl._invalidate()
        self.device_table = impl.dev

    def _device_callback_dedup(self, resolve: Resolver) -> bool:
        """Callback dedup on a device-lazy index decoding ONLY the
        duplicate groups' rows (csvplus.go:809-867 semantics; VERDICT r3
        #10): group boundaries come from the device run-starts kernel,
        O(dup) rows stream to host for the callback, and when every
        chosen row is a member of its group (the typical callback) the
        compaction is a pure columnar gather.  A callback that returns a
        BRAND-NEW row forces a full materialization — but the callback
        has already been invoked exactly once per group either way.

        Returns True when the dedup was completed here; False when this
        index has no supported device form (caller falls back)."""
        impl = self._impl
        if impl.dev is None:
            return False
        from .ops.join import DeviceIndex
        from .ops.sort import run_starts

        table = impl.dev.table
        starts = run_starts(table, impl.columns)
        if starts.size == 0:
            return True
        idx_starts = np.flatnonzero(starts)
        lengths = np.diff(np.append(idx_starts, table.nrows))
        dup = lengths > 1
        if not dup.any():
            return True  # no duplicate keys: nothing to resolve
        groups = list(zip(idx_starts[dup].tolist(), lengths[dup].tolist()))
        dup_row_idx = np.concatenate(
            [np.arange(s, s + l, dtype=np.int64) for s, l in groups]
        )
        decoded = table.to_rows(dup_row_idx)  # O(dup) decode, group order

        # one callback invocation per group, exactly like impl.dedup.
        # `off` is found by comparing against PRISTINE clones: a callback
        # that mutates a group row and returns it must keep the mutation
        # (host-path semantics), so a mutated member counts as a new row
        decisions: "list[tuple[int, int, object]]" = []
        replaced: "list[Row]" = []
        pos = 0
        for s, l in groups:
            group = decoded[pos : pos + l]
            pos += l
            pristine = [Row(r) for r in group]
            chosen = resolve(list(group))
            if chosen is None or len(chosen) < len(impl.columns):
                decisions.append((s, l, None))  # drop the whole group
                continue
            off = next((i for i, r in enumerate(pristine) if r == chosen), None)
            if off is None:
                chosen = chosen if isinstance(chosen, Row) else Row(chosen)
                replaced.append(chosen)
            decisions.append((s, l, off if off is not None else chosen))

        if not replaced:
            # pure columnar compaction: keep all singleton rows plus the
            # chosen member of each duplicate group
            keep = np.ones(table.nrows, dtype=bool)
            for s, l, d in decisions:
                keep[s : s + l] = False
                if d is not None:
                    keep[s + int(d)] = True
            sel = np.flatnonzero(keep).astype(np.int64)
            new_table = table.gather(sel)
            impl.dev = DeviceIndex.build(new_table, impl.columns)
            impl._rows = None
            impl._invalidate()
            self.device_table = impl.dev
            return True

        # a callback produced a new row: materialize once and splice the
        # recorded decisions (the callback is NOT re-invoked)
        rows = table.to_rows()
        out: List[Row] = []
        cursor = 0
        for s, l, d in decisions:
            out.extend(rows[cursor:s])
            if d is not None:
                out.append(rows[s + d] if isinstance(d, int) else d)
            cursor = s + l
        out.extend(rows[cursor:])
        impl.rows = out
        impl._invalidate()
        self.device_table = None
        impl.dev = None
        return True

    # -- persistence (csvplus.go:655-705) ----------------------------------

    def write_to(self, file_name: str) -> None:
        """Persist the index; the file is removed on any write error, like
        the reference's gob writer (csvplus.go:656-680).

        Two formats behind one loader (SURVEY.md §7 M5):

        * a device-backed index saves **columnar** (v2): one npz with the
          key list plus each column's dictionary and code array — no host
          rows are ever materialized, and loading restores a lazy
          device index;
        * a host index (possibly heterogeneous rows) saves versioned
          JSON-lines (v1).  (A gob-compat shim is a non-goal, SURVEY §5.)
        """
        impl = self._impl
        if impl.is_lazy and impl.dev is not None:
            self._write_columnar(file_name)
            return
        from .sinks import _write_file

        def dump(f):
            f.write(
                json.dumps(
                    {
                        "magic": _MAGIC,
                        "version": _VERSION,
                        "columns": impl.columns,
                        "count": len(impl.rows),
                    }
                )
            )
            f.write("\n")
            for row in impl.rows:
                f.write(json.dumps(row, sort_keys=True, separators=(",", ":")))
                f.write("\n")

        _write_file(file_name, dump)

    WriteTo = write_to

    def _write_columnar(self, file_name: str) -> None:
        """v2 npz write.  Device-lane columns persist their packed int32
        lane arrays AS lanes (``l{i}:name``): persisting the exact index
        the lane feature exists for (a unique 100M-row key) must not
        reinstate the unbounded host dictionary materialization that
        ``col.dictionary`` would force (VERDICT r3 weak #6 / next #8)."""
        table = self._impl.dev.table
        lane_columns: "dict[str, int]" = {}
        arrays: "dict[str, np.ndarray]" = {}
        for name, col in table.columns.items():
            if col.dev_dictionary is not None and col._dictionary is None:
                col._ensure_sorted_lanes()  # v3 stores SORTED lane arrays
                lane_columns[name] = len(col.dev_dictionary)
                for i, lane in enumerate(col.dev_dictionary):
                    arrays[f"l{i}:{name}"] = np.asarray(lane)
            else:
                arrays[f"d:{name}"] = col.dictionary
            arrays[f"c:{name}"] = np.asarray(col.codes)
        arrays["__meta__"] = np.frombuffer(
            json.dumps(
                {
                    "magic": _MAGIC,
                    # v3 = lane columns present; pre-lane readers then get
                    # the pinned unsupported-version message instead of a
                    # misleading KeyError-driven "not an index file"
                    "version": 3 if lane_columns else 2,
                    "key_columns": self._impl.columns,
                    "columns": list(table.columns),
                    "lane_columns": lane_columns,
                    "count": table.nrows,
                }
            ).encode("utf-8"),
            dtype=np.uint8,
        )
        from .sinks import _write_file

        _write_file(file_name, lambda f: np.savez(f, **arrays), mode="wb")

    # -- device hook -------------------------------------------------------

    def on_device(self, device: str = "tpu") -> "Index":
        """Attach an HBM-resident columnar copy of this index so joins and
        finds against it run as device kernels."""
        from .columnar.ingest import index_to_device

        self.device_table = index_to_device(self, device=device)
        self._impl.dev = self.device_table
        return self

    OnDevice = on_device

    # Go-style aliases
    Find = find
    FindMany = find_many
    SubIndex = sub_index
    ResolveDuplicates = resolve_duplicates


def load_index(file_name: str, device: "str | None" = None) -> Index:
    """Load an index persisted by :meth:`Index.write_to`
    (csvplus.go:683-705).  Columnar (v2) files restore a device-lazy
    index (*device* selects placement, like ``on_device``); JSONL (v1)
    files restore a host index."""
    with open(file_name, "rb") as fb:
        magic2 = fb.read(2)
    if magic2 == b"PK":  # npz container -> columnar v2
        return _load_columnar(file_name, device)
    with open(file_name, "r", encoding="utf-8") as f:
        try:
            header = json.loads(f.readline())
        except json.JSONDecodeError:
            raise ValueError(f"{file_name}: not a csvplus-tpu index file") from None
        if header.get("magic") != _MAGIC:
            raise ValueError(f"{file_name}: not a csvplus-tpu index file")
        if header.get("version") != _VERSION:
            raise ValueError(
                f"{file_name}: unsupported index version {header.get('version')}"
            )
        rows = [Row(json.loads(line)) for line in f if line.strip()]
    if len(rows) != header.get("count"):
        raise ValueError(
            f"{file_name}: truncated index file "
            f"({len(rows)} rows, expected {header.get('count')})"
        )
    return Index(IndexImpl(rows, header["columns"]))


def _load_columnar(file_name: str, device: "str | None" = None) -> Index:
    import zipfile

    import jax

    from .columnar.table import DeviceTable, StringColumn, default_device
    from .ops.join import DeviceIndex

    try:
        with np.load(file_name) as z:
            meta = json.loads(bytes(z["__meta__"]).decode("utf-8"))
            if meta.get("magic") != _MAGIC:
                raise ValueError(f"{file_name}: not a csvplus-tpu index file")
            if meta.get("version") not in (2, 3):
                raise ValueError(
                    f"{file_name}: unsupported columnar index version "
                    f"{meta.get('version')}"
                )
            dev = default_device(device)
            lane_columns = meta.get("lane_columns", {})
            cols = {}
            for name in meta["columns"]:
                codes = jax.device_put(z[f"c:{name}"], dev)
                if name in lane_columns:
                    # restore packed lanes straight to device: the host
                    # dictionary is never built (round-trip keeps the
                    # lane columns' bounded-RSS contract)
                    lanes = tuple(
                        jax.device_put(z[f"l{i}:{name}"], dev)
                        for i in range(int(lane_columns[name]))
                    )
                    cols[name] = StringColumn(None, codes, dev_dictionary=lanes)
                else:
                    cols[name] = StringColumn(z[f"d:{name}"], codes)
            count = meta["count"]
            key_columns = meta["key_columns"]
    except (KeyError, zipfile.BadZipFile, json.JSONDecodeError) as e:
        raise ValueError(f"{file_name}: not a csvplus-tpu index file") from e
    table = DeviceTable(cols, count, dev)
    return Index(IndexImpl(None, key_columns, dev=DeviceIndex.build(table, key_columns)))


def _validate_index_columns(columns: Sequence[str]) -> Tuple[str, ...]:
    columns = tuple(columns)
    if len(columns) == 0:
        raise ValueError("empty column list in CreateIndex()")
    if len(columns) > 1 and not all_columns_unique(columns):
        raise ValueError("duplicate column name(s) in CreateIndex()")
    return columns


def create_index(src, columns: Sequence[str]) -> Index:
    """Materialize and sort an index (csvplus.go:707-738).

    A device-planned source builds the index entirely on device: fused
    multi-key ``lax.sort`` over dictionary codes, no host rows.
    """
    columns = _validate_index_columns(columns)

    if getattr(src, "plan", None) is not None:
        from .columnar.exec import UnsupportedPlan

        try:
            return _create_index_device(src.plan, columns)
        except UnsupportedPlan:
            pass  # fall through to the host build

    rows: List[Row] = []

    def collect(row: Row) -> None:
        for col in columns:
            if col not in row:
                raise ValueError(f'missing column "{col}" while creating an index')
        rows.append(row)

    src(collect)

    impl = IndexImpl(rows, columns)
    impl.sort()
    return Index(impl)


def _create_index_device(plan, columns: Tuple[str, ...]) -> Index:
    from .columnar.exec import execute_plan_view
    from .ops.join import DeviceIndex
    from .ops.sort import sort_table

    view = execute_plan_view(plan)
    if view.deferred_error is not None:
        # index build consumes every row, so the host stream always
        # reaches the first row failing a terminal Validate
        raise view.deferred_error[1]
    if view.sel.shape[0] == 0:
        # the host build validates per-row (csvplus.go:722-733), so an
        # empty source yields an empty index without any column check
        return Index(IndexImpl([], columns))
    # the host build raises at the first streamed row lacking a key cell
    # (row-major, columns in argument order within the row), numbered by
    # the ORIGINATING source (reader record numbers / 0-based slice
    # positions) — first_missing_cell reproduces exactly that
    from .columnar.exec import first_missing_cell

    bad = first_missing_cell(view, columns)
    if bad is not None:
        raise DataSourceError(
            bad[0], f'missing column "{bad[1]}" while creating an index'
        )
    table = view.materialize()
    sorted_table = sort_table(table, list(columns))
    dev = DeviceIndex.build(sorted_table, list(columns))
    return Index(IndexImpl(None, columns, dev=dev))


def create_unique_index(src, columns: Sequence[str]) -> Index:
    """Index build + duplicate-key check (csvplus.go:740-756).

    On a device index the check is a single adjacent-equality reduction
    over the sorted key codes; only the offending row (if any) is decoded.
    """
    index = create_index(src, columns)
    impl = index._impl
    cols = impl.columns

    if impl.is_lazy and impl.dev is not None:
        from .ops.sort import find_adjacent_duplicate

        i = find_adjacent_duplicate(impl.dev.table, cols)
        if i is not None:
            row = impl.dev.table.to_rows(np.array([i], dtype=np.int64))[0]
            raise CsvPlusError(
                "duplicate value while creating unique index: "
                + str(row.select_existing(*cols))
            )
        return index

    rows = impl.rows
    for i in range(1, len(rows)):
        if equal_rows(cols, rows[i - 1], rows[i]):
            raise CsvPlusError(
                "duplicate value while creating unique index: "
                + str(rows[i].select_existing(*cols))
            )
    return index
