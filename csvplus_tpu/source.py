"""DataSource: the lazy, composable iteration protocol.

The load-bearing abstraction of the reference (csvplus.go:207-256): a data
source *is a function* — invoking it pushes rows one at a time into a
callback.  Here :class:`DataSource` is a callable object so the Go-style
usage ``src(row_fn)`` works verbatim, while combinators are methods that
return new lazy sources.  Nothing executes until a sink (or direct call)
drives the chain.

Semantics preserved from the reference:

* rows yielded from materialized sources are **cloned** before delivery, so
  consumers may mutate them freely (csvplus.go:225-249, clone at :230);
* a callback may raise :class:`StopPipeline` (Go: return ``io.EOF``) to stop
  early without error (csvplus.go:212-214);
* errors are annotated with row numbers at the *source* level, exactly where
  the reference wraps them (``iterate`` csvplus.go:242-245 uses the 0-based
  slice position; the CSV reader uses 1-based file lines, csvplus.go:1102);
* ``Transform`` drops empty result rows (csvplus.go:265);
* ``Top`` stops via the EOF mechanism (csvplus.go:319) so upstream readers
  treat it as a clean stop.

Device execution: each DataSource optionally carries a symbolic ``plan``
(see :mod:`csvplus_tpu.plan`).  When every stage of a chain is symbolic and
the origin is a columnar device table, sinks execute the fused device plan
instead of streaming host rows.  Any opaque Python callback keeps full API
parity by falling back to the host streaming path.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence

from .errors import CsvPlusError, DataSourceError, StopPipeline
from .row import Row, merge_rows

#: A row callback: called once per row; raise :class:`StopPipeline` to
#: stop cleanly, any other exception to fail (Go: ``func(Row) error``,
#: csvplus.go:208).
RowFunc = Callable[[Row], None]


def iterate(rows: Sequence[Row], fn: RowFunc, clone: bool = True) -> None:
    """Drive *fn* over a row slice, cloning each row (csvplus.go:225-249).

    Errors raised by *fn* are wrapped in :class:`DataSourceError` with the
    0-based position of the offending row, matching the reference's
    ``Line: uint64(i)``.  ``clone=False`` skips the defensive copy for
    callers whose rows are already single-use (freshly decoded).
    """
    i = 0
    try:
        for i, row in enumerate(rows):
            fn(Row(row) if clone else row)  # Row(row) is a fresh copy
    except StopPipeline:
        return
    except DataSourceError:
        raise
    except Exception as e:
        raise DataSourceError(i, e) from e


class DataSource:
    """A lazy stream of Rows; call it with a row callback to execute.

    Construct from a driver function ``run(fn)`` (Go's ``DataSource`` type,
    csvplus.go:215) — or use :func:`take_rows` / :func:`take` /
    :func:`csvplus_tpu.reader.from_file`.
    """

    __slots__ = ("_run", "plan", "_plan_unsupported", "plan_note", "_rows_hint")

    def __init__(self, run: Callable[[RowFunc], None], plan: Any = None):
        self._run = run
        self.plan = plan  # symbolic plan IR node, or None (host-only chain)
        self._plan_unsupported = False  # memo: device plan known-unsupported
        self.plan_note = None  # why device execution stopped, if it did
        # already-materialized backing rows (take_rows sources): sinks
        # may clone straight off this list instead of driving the
        # callback machinery per row — the point-lookup hot path
        self._rows_hint = None

    def explain(self) -> str:
        """Human-readable execution plan: the device plan when the chain
        is symbolic, or where (and why) it falls to the host path —
        the 'plan printer' from SURVEY.md §7's callback-escape-hatch
        requirement."""
        from .plan import explain as _explain

        base = _explain(self.plan)
        if self.plan is None and self.plan_note:
            return f"{base}\n  device execution stopped at: {self.plan_note}"
        return base

    # -- execution ---------------------------------------------------------

    def __call__(self, fn: RowFunc) -> None:
        """Push every row into *fn*.  *fn* may raise StopPipeline to stop
        cleanly; any other exception propagates (annotated with a row
        number by the originating source)."""
        try:
            self._run(fn)
        except StopPipeline:
            return

    def __iter__(self) -> Iterator[Row]:
        """Pythonic pull iteration (streaming, bounded buffer).

        The push-based pipeline runs in a helper thread; rows cross through
        a bounded queue (:func:`csvplus_tpu.utils.relay.relay_iter`), so
        memory use stays constant for long streams.  Abandoning the
        iterator stops the producer.
        """
        from .utils.relay import RelayStopped, relay_iter

        def run(emit) -> None:
            def fn(row: Row) -> None:
                try:
                    emit(row)
                except RelayStopped:
                    raise StopPipeline from None

            self(fn)

        return relay_iter(run, maxsize=1024)

    # -- per-row lazy combinators (csvplus.go:258-310) ---------------------

    def transform(self, trans: Callable[[Row], Optional[Row]]) -> "DataSource":
        """Most generic per-row stage (csvplus.go:262-272).

        *trans* returns the replacement row; an empty dict or ``None`` drops
        the row; raising stops the iteration.
        """

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                out = trans(row)
                if out:
                    fn(out if isinstance(out, Row) else Row(out))

            self._run(step)

        from .plan import transform_plan
        return _make(run, transform_plan(self.plan, trans), self, "transform", trans)

    def filter(self, pred: Callable[[Row], bool]) -> "DataSource":
        """Keep rows for which *pred* is true (csvplus.go:276-286)."""

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                if pred(row):
                    fn(row)

            self._run(step)

        from .plan import filter_plan
        return _make(run, filter_plan(self.plan, pred), self, "filter", pred)

    def map(self, mf: Callable[[Row], Row]) -> "DataSource":
        """Apply *mf* to every row (csvplus.go:290-296)."""

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                out = mf(row)
                fn(out if isinstance(out, Row) else Row(out))

            self._run(step)

        from .plan import map_plan
        return _make(run, map_plan(self.plan, mf), self, "map", mf)

    def validate(
        self, vf: Callable[[Row], "None | bool"], message: str = "validation failed"
    ) -> "DataSource":
        """Check every row; *vf* raises to fail the pipeline at that row
        (csvplus.go:300-310).

        Passing a symbolic predicate (``Like``/``All``/``Any``/``Not``)
        instead of a raising callback keeps the check on device: the
        fused mask is reduced and the pipeline aborts with *message* —
        wrapped with the first failing row's source number — exactly
        like the host path.
        """
        from .predicates import Predicate

        if isinstance(vf, Predicate):
            pred = vf

            def run(fn: RowFunc) -> None:
                def step(row: Row) -> None:
                    if not pred(row):
                        raise CsvPlusError(message)
                    fn(row)

                self._run(step)

            from .plan import validate_plan
            return _make(
                run, validate_plan(self.plan, pred, message), self, "validate", pred
            )

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                vf(row)
                fn(row)

            self._run(step)

        return _make(run, None, self, "validate", vf)

    # -- windowing combinators (csvplus.go:312-374) ------------------------

    def top(self, n: int) -> "DataSource":
        """Pass down at most *n* rows, then stop cleanly (csvplus.go:313-326)."""

        def run(fn: RowFunc) -> None:
            counter = n

            def step(row: Row) -> None:
                nonlocal counter
                if counter == 0:
                    raise StopPipeline
                counter -= 1
                fn(row)

            self._run(step)

        from .plan import top_plan
        return _make(run, top_plan(self.plan, n), self)

    def drop(self, n: int) -> "DataSource":
        """Skip the first *n* rows (csvplus.go:329-342)."""

        def run(fn: RowFunc) -> None:
            counter = n

            def step(row: Row) -> None:
                nonlocal counter
                if counter == 0:
                    fn(row)
                else:
                    counter -= 1

            self._run(step)

        from .plan import drop_plan
        return _make(run, drop_plan(self.plan, n), self)

    def take_while(self, pred: Callable[[Row], bool]) -> "DataSource":
        """Pass rows until *pred* is first false, then stop (csvplus.go:346-358)."""

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                if not pred(row):
                    raise StopPipeline
                fn(row)

            self._run(step)

        from .plan import take_while_plan
        return _make(run, take_while_plan(self.plan, pred), self, "take_while", pred)

    def drop_while(self, pred: Callable[[Row], bool]) -> "DataSource":
        """Skip rows while *pred* holds, then pass everything (csvplus.go:362-374)."""

        def run(fn: RowFunc) -> None:
            yielding = False

            def step(row: Row) -> None:
                nonlocal yielding
                if not yielding and pred(row):
                    return
                yielding = True
                fn(row)

            self._run(step)

        from .plan import drop_while_plan
        return _make(run, drop_while_plan(self.plan, pred), self, "drop_while", pred)

    # -- column projection (csvplus.go:492-525) ----------------------------

    def drop_columns(self, *columns: str) -> "DataSource":
        """Remove the listed columns from each row (csvplus.go:493-507)."""
        if not columns:
            raise ValueError("no columns specified in DropColumns()")

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                for c in columns:
                    row.pop(c, None)
                fn(row)

            self._run(step)

        from .plan import drop_columns_plan
        return _make(run, drop_columns_plan(self.plan, columns), self)

    def select_columns(self, *columns: str) -> "DataSource":
        """Keep exactly the listed columns; error if any is missing
        (csvplus.go:511-525)."""
        if not columns:
            raise ValueError("no columns specified in SelectColumns()")

        def run(fn: RowFunc) -> None:
            def step(row: Row) -> None:
                fn(row.select(*columns))

            self._run(step)

        from .plan import select_columns_plan
        return _make(run, select_columns_plan(self.plan, columns), self)

    # -- index / join entry points (implemented in index.py) ---------------

    def index_on(self, *columns: str):
        """Materialize a sorted :class:`~csvplus_tpu.index.Index` on the
        listed key columns (csvplus.go:529-531)."""
        from .index import create_index

        return create_index(self, columns)

    def unique_index_on(self, *columns: str):
        """Like :meth:`index_on` but errors on duplicate keys
        (csvplus.go:535-537)."""
        from .index import create_unique_index

        return create_unique_index(self, columns)

    def join(self, index, *columns: str) -> "DataSource":
        """Lazy lookup join against *index* (csvplus.go:539-569).

        The listed stream columns match the index's key columns left to
        right; with no columns given, the index's own key column names are
        used ("natural join").  Merged rows contain all columns from both
        sides; on a name collision the **stream row's value wins**
        (csvplus.go:560, 571-583).
        """
        cols = _resolve_join_columns(index, columns, "Join()")

        def run(fn: RowFunc) -> None:
            index.materialize()  # host probe loop: decode a lazy index once

            def step(row: Row) -> None:
                values = row.select_values(*cols)
                for index_row in index._impl.find_rows(values):
                    fn(merge_rows(index_row, row))

            self._run(step)

        from .plan import join_plan
        return _make(run, join_plan(self.plan, index, cols), self, "join")

    def except_(self, index, *columns: str) -> "DataSource":
        """Anti-join: pass through rows whose key is NOT in *index*
        (csvplus.go:585-608)."""
        cols = _resolve_join_columns(index, columns, "Except()")

        def run(fn: RowFunc) -> None:
            index.materialize()  # host probe loop: decode a lazy index once

            def step(row: Row) -> None:
                values = row.select_values(*cols)
                if not index._impl.has(values):
                    fn(row)

            self._run(step)

        from .plan import except_plan
        return _make(run, except_plan(self.plan, index, cols), self, "except")

    # -- device migration --------------------------------------------------

    def on_device(
        self, device: str = "tpu", shards: "int | None" = None, mesh=None
    ) -> "DataSource":
        """Materialize this source into an HBM-resident columnar table and
        return a plan-capable DataSource over it.

        The device-native entry point is ``FromFile(...).OnDevice()``
        (which parses straight into columns); this method is the general
        form for any host source — it streams the rows once, columnarizes
        (heterogeneous schemas allowed; missing cells stay absent), and
        subsequent symbolic stages run as device kernels.

        Error row numbers downstream of this route count streamed rows
        from 0 (the stream is anonymous here — any upstream numbering is
        not recoverable); ``FromFile(...).OnDevice()`` preserves the
        reader's record numbering instead.
        """
        from .columnar.ingest import _maybe_shard, source_from_table
        from .columnar.table import DeviceTable

        table = DeviceTable.from_rows(self.to_rows(), device=device)
        return source_from_table(_maybe_shard(table, shards, mesh))

    OnDevice = on_device

    # -- sinks (implemented in sinks.py) -----------------------------------

    def to_csv(self, out, *columns: str) -> None:
        """Drive the chain, writing selected columns as canonical CSV to
        *out* (csvplus.go:379-406; see :func:`csvplus_tpu.sinks.to_csv`)."""
        from .sinks import to_csv

        to_csv(self, out, *columns)

    def to_csv_file(self, name: str, *columns: str) -> None:
        """CSV sink to a named file; the file is removed on any error
        (csvplus.go:411-443)."""
        from .sinks import to_csv_file

        to_csv_file(self, name, *columns)

    def to_json(self, out) -> None:
        """Drive the chain, writing a JSON array of row objects to *out*
        (csvplus.go:446-475, byte-compatible with Go's json.Encoder)."""
        from .sinks import to_json

        to_json(self, out)

    def to_json_file(self, name: str) -> None:
        """JSON sink to a named file; the file is removed on any error
        (csvplus.go:478-480)."""
        from .sinks import to_json_file

        to_json_file(self, name)

    def to_rows(self) -> List[Row]:
        """Drive the chain and collect every row (csvplus.go:483-490)."""
        from .sinks import to_rows

        return to_rows(self)

    def to_device_table(self):
        """Execute the pipeline into a device-resident columnar table.

        The device-native terminal: runs this source's symbolic plan with
        the device executor and returns the materialized
        :class:`~csvplus_tpu.columnar.table.DeviceTable` — codes stay in
        HBM, nothing is decoded to host rows (that is what
        :meth:`to_rows` / the CSV/JSON sinks are for).  A source without
        a device plan (or with a stage the executor cannot lower, e.g. an
        opaque Python callback) columnarizes its streamed rows instead,
        so the call always succeeds with reference semantics.
        """
        from .columnar.table import DeviceTable

        device = None
        if self.plan is not None:
            from .columnar.exec import UnsupportedPlan, execute_plan

            try:
                table = execute_plan(self.plan)
            except UnsupportedPlan:
                table = None
            if table is not None:
                de = getattr(table, "deferred_error", None)
                if de is not None:
                    # a full materialization consumes every row, so a
                    # terminal validate failure always fires (parity with
                    # streaming the whole table)
                    raise de[1]
                return table
            # fallback stays on the device the pipeline was pinned to
            from . import plan as P

            node = self.plan
            while not isinstance(node, (P.Scan, P.Lookup)):
                node = node.child
            device = node.table.device
        return DeviceTable.from_rows(self.to_rows(), device=device)

    # -- Go-style aliases --------------------------------------------------
    Transform = transform
    Filter = filter
    Map = map
    Validate = validate
    Top = top
    Drop = drop
    TakeWhile = take_while
    DropWhile = drop_while
    DropColumns = drop_columns
    SelectColumns = select_columns
    IndexOn = index_on
    UniqueIndexOn = unique_index_on
    Join = join
    Except = except_
    ToCsv = to_csv
    ToCsvFile = to_csv_file
    ToJSON = to_json
    ToJSONFile = to_json_file
    ToRows = to_rows


_STAGE_BREAK_NOTES = {
    "join": "join() against an index with no device copy "
    "(call index.on_device() to keep the chain on device)",
    "except": "except_() against an index with no device copy "
    "(call index.on_device() to keep the chain on device)",
    "validate": "validate() callbacks have no symbolic form",
}


def _make(run, plan, parent=None, stage: str = "", arg: Any = None) -> "DataSource":
    """Build a combinator result: device plan execution when the chain is
    symbolic, with *run* (the host streaming closure) as fallback.  When
    the stage BREAKS an existing device plan (opaque argument / host-only
    index), the reason is recorded — and carried through later stages —
    for :meth:`DataSource.explain`."""
    if plan is None:
        ds = DataSource(run)
        if parent is not None:
            if parent.plan is not None and stage:
                ds.plan_note = _STAGE_BREAK_NOTES.get(
                    stage, f"{stage}({_describe_arg(arg)}) is not symbolic"
                )
            else:
                ds.plan_note = parent.plan_note  # keep the original reason
        return ds
    from .columnar.exec import plan_runner

    ds = DataSource(run, plan=plan)
    ds._run = plan_runner(plan, fallback=run, owner=ds)
    return ds


def _describe_arg(arg: Any) -> str:
    if arg is None:
        return ""
    return getattr(arg, "__name__", None) or type(arg).__name__


def _resolve_join_columns(index, columns: Sequence[str], what: str) -> List[str]:
    """Shared Join/Except column-list resolution (csvplus.go:546-550, 589-593)."""
    if not columns:
        return list(index._impl.columns)
    if len(columns) > len(index._impl.columns):
        raise ValueError(f"too many source columns in {what}")
    return list(columns)


def take_rows(rows: Iterable[Row]) -> DataSource:
    """Convert a list of Rows to a DataSource (csvplus.go:218-222).

    Rows are cloned on every iteration, so consumers may mutate them.
    """
    rows = list(rows)

    def run(fn: RowFunc) -> None:
        iterate(rows, fn)

    ds = DataSource(run)
    ds._rows_hint = rows
    return ds


def take(src: Any) -> DataSource:
    """Lift anything with an ``iterate(fn)``/``Iterate(fn)`` method — a
    Reader, an Index, a DeviceTable — into a DataSource (csvplus.go:252-256)."""
    if isinstance(src, DataSource):
        return src
    it = getattr(src, "iterate", None) or getattr(src, "Iterate", None)
    if it is None:
        raise TypeError(f"take(): {type(src).__name__} has no iterate() method")
    return DataSource(it, plan=getattr(src, "plan", None))
