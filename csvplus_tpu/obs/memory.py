"""Memory watermark sampling + host facts for bench artifacts.

The r06 mesh-RSS regression (7.2 -> 11.8GB under whole-program fusion)
was only caught because one bench script happened to probe
``ru_maxrss``.  This module makes that probe a subsystem:

* :func:`rss_mb` — CURRENT resident set (``/proc/self/statm``), the
  sampler's input;
* :func:`peak_rss_mb` — process-lifetime high watermark (``VmHWM``,
  falling back to ``ru_maxrss``), the number the artifacts record;
* :func:`device_memory_stats` — per-device ``bytes_in_use`` /
  ``peak_bytes_in_use`` from jax where the backend reports them (CPU
  returns nothing; the call degrades to ``{}``);
* :func:`watch_memory` — a background sampler attachable to any span:
  it polls current RSS (and device peaks) while the body runs and
  writes the observed watermark into the span's attrs on exit, so a
  per-stage RSS column appears in the same tables/traces as the wall
  times — exactly the per-stage cost accounting fusion decisions need;
* :func:`host_header` — the (host_cpus, device_count, platform) triple
  every bench artifact must carry (the r07/r08 postmortems both needed
  them and only some artifacts had them).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Any, Dict, Iterator, Optional

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def rss_mb() -> float:
    """Current resident set size in MB (0.0 when unreadable)."""
    try:
        with open("/proc/self/statm") as f:
            pages = int(f.read().split()[1])
        return pages * _PAGE_SIZE / 1e6
    except (OSError, ValueError, IndexError):
        return 0.0


def peak_rss_mb() -> float:
    """Process-lifetime peak RSS in MB: ``VmHWM`` when procfs is
    available, else ``ru_maxrss`` (which Linux reports in KB)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmHWM:"):
                    return int(line.split()[1]) / 1e3
    except (OSError, ValueError, IndexError):
        pass
    try:
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e3
    except Exception:
        return 0.0


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """``{device: {bytes_in_use, peak_bytes_in_use, ...}}`` for devices
    whose backend exposes ``memory_stats()`` (TPU/GPU); ``{}`` on CPU
    and on any failure — callers must treat device stats as optional."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = d.memory_stats()
            if stats:
                out[str(d)] = {
                    k: int(v)
                    for k, v in stats.items()
                    if isinstance(v, (int, float))
                }
    except Exception:
        return {}
    return out


def host_header() -> Dict[str, Any]:
    """The artifact header facts every bench record must carry."""
    try:
        import jax

        devices = jax.device_count()
        platform = jax.default_backend()
    except Exception:
        devices, platform = None, None
    return {
        "host_cpus": os.cpu_count() or 1,
        "jax_device_count": devices,
        "platform": platform,
    }


class MemoryWatermark:
    """Background RSS/device-memory sampler.

    One daemon thread polls :func:`rss_mb` (and, when requested, the
    device allocator peaks) every *interval_s*; the observed maxima are
    readable at any time and summarized by :meth:`attrs`.  The sampler
    is a monitor: the sampling loop and readers share ``self._lock``.
    """

    def __init__(self, interval_s: float = 0.05, devices: bool = False):
        self.interval_s = max(0.001, float(interval_s))
        self.devices = devices
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rss_start = rss_mb()
        self._rss_peak = self._rss_start
        self._samples = 0
        self._device_peak_bytes = 0

    def _sample_once(self) -> None:
        cur = rss_mb()
        dev = 0
        if self.devices:
            for stats in device_memory_stats().values():
                dev += stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0))
        with self._lock:
            self._samples += 1
            if cur > self._rss_peak:
                self._rss_peak = cur
            if dev > self._device_peak_bytes:
                self._device_peak_bytes = dev

    def _sample_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._sample_once()

    def start(self) -> "MemoryWatermark":
        if self._thread is None:
            self._stop.clear()
            t = threading.Thread(
                target=self._sample_loop,
                name="csvplus-obs-memwatch",
                daemon=True,
            )
            self._thread = t
            t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None
        self._sample_once()  # final sample so short regions see an update

    @property
    def rss_peak_mb(self) -> float:
        with self._lock:
            return self._rss_peak

    def attrs(self) -> Dict[str, Any]:
        """JSON-safe summary for span/stage attrs."""
        with self._lock:
            out: Dict[str, Any] = {
                "rss_start_mb": round(self._rss_start, 1),
                "rss_peak_mb": round(self._rss_peak, 1),
                "rss_samples": self._samples,
            }
            if self.devices and self._device_peak_bytes:
                out["device_peak_mb"] = round(self._device_peak_bytes / 1e6, 1)
        return out


@contextlib.contextmanager
def watch_memory(
    attrs: Optional[Dict[str, Any]] = None,
    *,
    interval_s: float = 0.05,
    devices: bool = False,
) -> Iterator[MemoryWatermark]:
    """Sample memory while the body runs; on exit, write the watermark
    summary into *attrs* (pass the dict a ``tracer.span(...)`` or
    ``telemetry.stage(...)`` yielded, and the RSS column lands on that
    span/stage).  Yields the live :class:`MemoryWatermark`."""
    wm = MemoryWatermark(interval_s=interval_s, devices=devices).start()
    t0 = time.perf_counter()
    try:
        yield wm
    finally:
        wm.stop()
        summary = wm.attrs()
        summary["watched_s"] = round(time.perf_counter() - t0, 4)
        if attrs is not None:
            attrs.update(summary)
