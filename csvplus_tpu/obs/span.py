"""Hierarchical host-side spans with ``contextvars`` trace propagation.

The process-global :data:`telemetry` singleton
(:mod:`csvplus_tpu.utils.observe`) records a flat per-stage table — the
right shape for one pipeline run, and exactly the wrong shape for the
serving tier, where N concurrent queries interleave their stages into
one list and per-query attribution is lost.  This module adds the
missing structure:

* a :class:`Span` is one timed region with a ``trace_id`` / ``span_id``
  / ``parent_id`` triple, so spans form a tree;
* the *current* span rides a :mod:`contextvars` ``ContextVar`` — every
  thread (and every ``contextvars.Context``) sees its own current span,
  so concurrent queries each grow an isolated tree with zero locking on
  the hot path;
* worker threads that must contribute to a parent's trace adopt an
  explicitly captured context (:meth:`Tracer.capture` /
  :meth:`Tracer.adopt`) — the r07 rule that cross-thread state flows by
  explicit handoff, never ambient sharing;
* finished traces land in a bounded list the exporters
  (:mod:`csvplus_tpu.obs.export`) serialize to Chrome-trace JSON or
  span JSON-lines.

The existing ``telemetry.stage()`` API keeps working unchanged: it is
now a compatibility shim that ALSO opens a span whenever a trace is
active in the calling context (see ``utils/observe.py``), so every
already-instrumented stage (exec nodes, ingest, joins, serve dispatch)
shows up in span trees without touching its call site.

Disabled-path cost: with no active trace, :meth:`Tracer.span` is one
``ContextVar.get`` and one generator frame — the ``make trace-smoke``
gate holds this under 2% on the micro lookup shape.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple

#: Finished traces kept for export before the oldest are dropped.
MAX_FINISHED_TRACES = 512

#: The current (trace, open span_id) — per-thread / per-context by
#: ``contextvars`` semantics, which is what isolates concurrent queries.
_CURRENT: "contextvars.ContextVar[Optional[Tuple[Trace, int]]]" = (
    contextvars.ContextVar("csvplus_obs_current", default=None)
)


@dataclass
class Span:
    """One timed region inside a trace."""

    trace_id: int
    span_id: int
    parent_id: Optional[int]
    name: str
    t_start: float  # perf_counter seconds (trace-relative on export)
    t_end: float
    lane: str  # thread name or explicit worker lane
    attrs: Dict[str, Any] = field(default_factory=dict)

    @property
    def seconds(self) -> float:
        return self.t_end - self.t_start

    def to_json(self) -> Dict[str, Any]:
        return {
            "trace": self.trace_id,
            "span": self.span_id,
            "parent": self.parent_id,
            "name": self.name,
            "t_start": round(self.t_start, 6),
            "ms": round(self.seconds * 1e3, 4),
            "lane": self.lane,
            "attrs": self.attrs,
        }


class Trace:
    """One span tree (one query / one pipeline run).

    Spans append under the trace's own lock: workers adopted into the
    trace may close spans concurrently with the owner, and the finished
    list must never interleave-corrupt (the exact failure mode of the
    flat telemetry list this module replaces).
    """

    __slots__ = ("trace_id", "name", "spans", "t_anchor", "_lock")

    def __init__(self, trace_id: int, name: str):
        self.trace_id = trace_id
        self.name = name
        self.spans: List[Span] = []
        self.t_anchor = time.perf_counter()
        self._lock = threading.Lock()

    def add(self, span: Span) -> None:
        with self._lock:
            self.spans.append(span)

    def root(self) -> Optional[Span]:
        with self._lock:
            for s in self.spans:
                if s.parent_id is None:
                    return s
        return None

    def span_ids(self) -> set:
        with self._lock:
            return {s.span_id for s in self.spans}

    def snapshot(self) -> List[Span]:
        """Consistent copy of the span list (safe while workers append)."""
        with self._lock:
            return list(self.spans)

    def to_json(self) -> Dict[str, Any]:
        with self._lock:
            spans = list(self.spans)
        return {
            "trace_id": self.trace_id,
            "name": self.name,
            "spans": [s.to_json() for s in spans],
        }


class _OpenSpan:
    """Handle for a span opened via the low-level open/close API."""

    __slots__ = ("trace", "span", "token")

    def __init__(self, trace: Trace, span: Span, token):
        self.trace = trace
        self.span = span
        self.token = token


class Tracer:
    """Process-global span collector (one instance: :data:`tracer`)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._finished: List[Trace] = []
        self._dropped = 0

    # -- context -----------------------------------------------------------

    def active(self) -> bool:
        """True when a trace is open in the calling context."""
        return _CURRENT.get() is not None

    def capture(self) -> Optional[Tuple[Trace, int]]:
        """Snapshot of the current (trace, span) for explicit handoff to
        another thread; ``None`` when no trace is active."""
        return _CURRENT.get()

    @contextlib.contextmanager
    def adopt(self, ctx: Optional[Tuple[Trace, int]]) -> Iterator[None]:
        """Run the body inside a context captured elsewhere (a worker
        lane contributing spans to its coordinator's trace).  ``None``
        adopts nothing and the body runs untraced."""
        if ctx is None:
            yield
            return
        token = _CURRENT.set(ctx)
        try:
            yield
        finally:
            _CURRENT.reset(token)

    # -- tracing -----------------------------------------------------------

    @contextlib.contextmanager
    def trace(self, name: str, **attrs) -> Iterator[Trace]:
        """Open a new root trace in this context; yields the
        :class:`Trace` and registers it in the finished list on exit."""
        t = Trace(next(self._ids), name)
        root = Span(
            trace_id=t.trace_id,
            span_id=next(self._ids),
            parent_id=None,
            name=name,
            t_start=time.perf_counter(),
            t_end=0.0,
            lane=threading.current_thread().name,
            attrs=dict(attrs),
        )
        token = _CURRENT.set((t, root.span_id))
        try:
            yield t
        finally:
            _CURRENT.reset(token)
            root.t_end = time.perf_counter()
            t.add(root)
            with self._lock:
                self._finished.append(t)
                while len(self._finished) > MAX_FINISHED_TRACES:
                    self._finished.pop(0)
                    self._dropped += 1

    def open_span(self, name: str, **attrs) -> Optional[_OpenSpan]:
        """Low-level span open: returns ``None`` (and records nothing)
        when no trace is active — the disabled fast path."""
        ctx = _CURRENT.get()
        if ctx is None:
            return None
        t, parent = ctx
        span = Span(
            trace_id=t.trace_id,
            span_id=next(self._ids),
            parent_id=parent,
            name=name,
            t_start=time.perf_counter(),
            t_end=0.0,
            lane=threading.current_thread().name,
            attrs=dict(attrs) if attrs else {},
        )
        token = _CURRENT.set((t, span.span_id))
        return _OpenSpan(t, span, token)

    def close_span(self, handle: Optional[_OpenSpan], **attrs) -> None:
        if handle is None:
            return
        _CURRENT.reset(handle.token)
        handle.span.t_end = time.perf_counter()
        if attrs:
            handle.span.attrs.update(attrs)
        handle.trace.add(handle.span)

    @contextlib.contextmanager
    def span(self, name: str, **attrs) -> Iterator[Dict[str, Any]]:
        """Child span under the current context.  Yields the span's
        attrs dict (the body may annotate it); a no-op yielding a
        throwaway dict when no trace is active."""
        handle = self.open_span(name, **attrs)
        if handle is None:
            yield {}
            return
        try:
            yield handle.span.attrs
        except BaseException as e:
            handle.span.attrs["error"] = type(e).__name__
            raise
        finally:
            self.close_span(handle)

    def add_span(
        self,
        name: str,
        seconds: float,
        *,
        lane: Optional[str] = None,
        t_end: Optional[float] = None,
        **attrs,
    ) -> Optional[Span]:
        """Pre-measured span under the current context (the
        ``add_stage`` analogue: work accumulated across many slices,
        e.g. a worker lane's total busy time).  ``t_end`` defaults to
        now, so the span covers [now - seconds, now]."""
        ctx = _CURRENT.get()
        if ctx is None:
            return None
        t, parent = ctx
        end = time.perf_counter() if t_end is None else t_end
        return self.record_span(
            t, parent, name, end - float(seconds), end, lane=lane, **attrs
        )

    def record_span(
        self,
        trace: Trace,
        parent_id: Optional[int],
        name: str,
        t_start: float,
        t_end: float,
        *,
        lane: Optional[str] = None,
        **attrs,
    ) -> Span:
        """Record a fully-specified span into *trace* from any thread —
        the serving dispatcher uses this to attribute batch-shared work
        back to each request's own trace."""
        span = Span(
            trace_id=trace.trace_id,
            span_id=next(self._ids),
            parent_id=parent_id,
            name=name,
            t_start=t_start,
            t_end=t_end,
            lane=lane or threading.current_thread().name,
            attrs=dict(attrs) if attrs else {},
        )
        trace.add(span)
        return span

    # -- export ------------------------------------------------------------

    def finished(self) -> List[Trace]:
        """Snapshot copy of the finished traces (oldest first)."""
        with self._lock:
            return list(self._finished)

    def drain(self) -> List[Trace]:
        """Finished traces, removing them from the tracer."""
        with self._lock:
            out = list(self._finished)
            self._finished.clear()
            return out

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._dropped = 0

    @property
    def dropped(self) -> int:
        return self._dropped


#: Process-global tracer (mirrors the ``telemetry`` singleton pattern).
tracer = Tracer()
