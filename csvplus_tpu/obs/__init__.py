"""First-class observability subsystem (docs/OBSERVABILITY.md).

What grew out of ``utils/observe.py``'s 211-line helper once every hard
diagnosis (r05 warm join, r06 mesh RSS) turned out to need it:

* :mod:`~csvplus_tpu.obs.span` — hierarchical per-query spans with
  ``contextvars`` trace isolation (:data:`tracer`);
* :mod:`~csvplus_tpu.obs.export` — Chrome-trace/Perfetto JSON +
  span JSON-lines exporters and the trace-smoke schema validator;
* :mod:`~csvplus_tpu.obs.recompile` — jit-lowering accounting for the
  registered module-level kernels (:class:`RecompileWatch`);
* :mod:`~csvplus_tpu.obs.memory` — RSS/device-memory watermark
  sampling attachable to any span, plus the bench-artifact host header;
* :mod:`~csvplus_tpu.obs.diff` — the stage-table AND bench-record
  regression differs behind ``python -m csvplus_tpu.obs diff``;
* :mod:`~csvplus_tpu.obs.metrics` — the production telemetry plane
  (ISSUE 13): typed metric registry, Prometheus text exposition +
  optional HTTP endpoint, the JSONL metrics pump, tail-sampled request
  tracing, and the :class:`TelemetryPlane` bundle the serving tier
  carries;
* :mod:`~csvplus_tpu.obs.flight` — the crash flight recorder: a
  bounded process-global event ring dumped atomically on terminal
  failure paths;
* :mod:`~csvplus_tpu.obs.sketch` — the Space-Saving top-K heavy-hitter
  sketch behind ``python -m csvplus_tpu.obs skew``.

The legacy ``telemetry`` singleton keeps its API and feeds the same
machinery: ``telemetry.stage()`` opens a span whenever a trace is
active in the calling context.
"""

from .diff import (
    diff_bench_files,
    diff_bench_records,
    diff_files,
    diff_stage_tables,
    load_stage_table,
)
from .flight import FlightRecorder, recorder
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    MetricsPump,
    PromHttpEndpoint,
    TailSampler,
    TelemetryPlane,
)
from .sketch import SpaceSaving, skew_report
from .export import (
    SpanJsonlSink,
    chrome_trace_events,
    export_chrome_trace,
    spans_to_json,
    validate_chrome_trace,
    write_chrome_trace,
    write_spans_jsonl,
)
from .memory import (
    MemoryWatermark,
    device_memory_stats,
    host_header,
    peak_rss_mb,
    rss_mb,
    watch_memory,
)
from .recompile import (
    RecompileWatch,
    compile_counts,
    register_kernel,
    registered_kernels,
)
from .span import Span, Trace, Tracer, tracer

__all__ = [
    "Span",
    "Trace",
    "Tracer",
    "tracer",
    "SpanJsonlSink",
    "chrome_trace_events",
    "export_chrome_trace",
    "spans_to_json",
    "validate_chrome_trace",
    "write_chrome_trace",
    "write_spans_jsonl",
    "MemoryWatermark",
    "device_memory_stats",
    "host_header",
    "peak_rss_mb",
    "rss_mb",
    "watch_memory",
    "RecompileWatch",
    "compile_counts",
    "register_kernel",
    "registered_kernels",
    "diff_bench_files",
    "diff_bench_records",
    "diff_files",
    "diff_stage_tables",
    "load_stage_table",
    "FlightRecorder",
    "recorder",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "MetricsPump",
    "PromHttpEndpoint",
    "TailSampler",
    "TelemetryPlane",
    "SpaceSaving",
    "skew_report",
]
