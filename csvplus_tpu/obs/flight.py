"""Crash flight recorder: a bounded in-memory ring of recent events,
dumped atomically as a post-mortem artifact (ISSUE 13).

The serving tier's terminal failure paths — ``_on_dispatcher_crash``,
fatal fault classification in the retry ladder, a ``views:refresh``
crash — each get one ``dump()`` call: the last N dispatch-cycle
summaries, fault firings, compaction/WAL events, plus whatever the
attached context providers report at dump time (metric registry
samples, the server snapshot), written tmp → fsync → ``os.replace`` so
a dump is either complete and parseable or absent (the IO001 rule).

The ring is process-global by default (:data:`recorder`): storage seal
/compaction events, armed fault firings, and serve cycle summaries all
land in ONE timeline, so a dump answers "what was the process doing in
the seconds before it died" without cross-referencing.

Thread model: a monitor — ``note`` is a deque append under the
instance lock (cheap enough for per-dispatch-cycle and per-delta-seal
call sites).  ``dump`` snapshots the ring under the lock, then calls
providers and writes the file OUTSIDE it; a provider that raises is
recorded in the dump, never propagated — a flight recorder must not
take the crashing process down a second way.

Dump directory resolution: explicit ``dir`` argument, else
``CSVPLUS_FLIGHT_DIR``, else the system temp dir.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.env import env_str

__all__ = ["FlightRecorder", "recorder", "note", "attach", "dump"]

#: Ring capacity: enough to cover several seconds of dispatch cycles
#: plus the storage events between them, small enough that a dump stays
#: a few-hundred-KB artifact.
DEFAULT_CAPACITY = 512

#: Dump schema version, bumped on shape changes (same contract as the
#: serving-metrics snapshot).
DUMP_SCHEMA_VERSION = 1


def _default_dir() -> str:
    return env_str("CSVPLUS_FLIGHT_DIR") or tempfile.gettempdir()


class FlightRecorder:
    """Bounded event ring + attached context providers + atomic dump."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity)
        self._seq = 0
        self._dumps = 0
        self._providers: Tuple[Tuple[str, Callable[[], object]], ...] = ()

    # -- ingest ------------------------------------------------------------

    def note(self, kind: str, **fields: object) -> None:
        """Append one event to the ring: ``kind`` plus JSON-safe
        fields, stamped with a sequence number and wall/monotonic
        clocks.  One lock round, O(1)."""
        t_wall = time.time()
        t_mono = time.monotonic()
        with self._lock:
            self._seq += 1
            self._ring.append((self._seq, t_wall, t_mono, kind, fields))

    def attach(self, name: str, provider: Callable[[], object]) -> None:
        """Register a zero-arg *provider* polled at dump time; its
        return value lands under ``context[name]``.  Re-attaching a
        name replaces the previous provider."""
        with self._lock:
            kept = tuple(p for p in self._providers if p[0] != name)
            self._providers = kept + ((name, provider),)

    # -- export ------------------------------------------------------------

    def events(self) -> List[Dict[str, object]]:
        """The ring as JSON-safe dicts, oldest first."""
        with self._lock:
            items = list(self._ring)
        return [
            {"seq": seq, "ts": ts, "mono": mono, "kind": kind, **fields}
            for seq, ts, mono, kind, fields in items
        ]

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "capacity": self.capacity,
                "events": len(self._ring),
                "seq": self._seq,
                "dumps": self._dumps,
            }

    def dump(
        self,
        reason: str,
        error: Optional[BaseException] = None,
        *,
        dir: Optional[str] = None,
    ) -> str:
        """Write the post-mortem artifact atomically and return its
        path.  The payload carries the ring, the dump reason/error, and
        every attached provider's context (a failing provider becomes a
        ``{"error": ...}`` stub in place of its context)."""
        with self._lock:
            self._dumps += 1
            n = self._dumps
            items = list(self._ring)
            providers = self._providers
        context: Dict[str, object] = {}
        for name, provider in providers:
            try:
                context[name] = provider()
            except Exception as perr:
                context[name] = {
                    "error": f"{type(perr).__name__}: {perr}"
                }
        payload = {
            "schema_version": DUMP_SCHEMA_VERSION,
            "reason": reason,
            "error": (
                {"type": type(error).__name__, "message": str(error)}
                if error is not None
                else None
            ),
            "ts": time.time(),
            "pid": os.getpid(),
            "events": [
                {"seq": seq, "ts": ts, "mono": mono, "kind": kind, **fields}
                for seq, ts, mono, kind, fields in items
            ],
            "context": context,
        }
        out_dir = dir if dir is not None else _default_dir()
        path = os.path.join(
            out_dir, f"csvplus_flight.{os.getpid()}.{n}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(payload, f, default=str)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path


#: The process-global recorder every built-in call site notes into.
recorder = FlightRecorder()


def note(kind: str, **fields: object) -> None:
    """Append one event to the process-global ring."""
    recorder.note(kind, **fields)


def attach(name: str, provider: Callable[[], object]) -> None:
    """Attach a dump-time context provider to the global recorder."""
    recorder.attach(name, provider)


def dump(
    reason: str,
    error: Optional[BaseException] = None,
    *,
    dir: Optional[str] = None,
) -> str:
    """Dump the process-global ring; returns the artifact path."""
    return recorder.dump(reason, error, dir=dir)
