"""Span exporters: Chrome-trace/Perfetto JSON + span JSON-lines.

Two consumers, two formats:

* :func:`write_chrome_trace` / :func:`export_chrome_trace` emit the
  Chrome Trace Event format (``{"traceEvents": [...]}``, complete
  ``"ph": "X"`` events) that Perfetto and ``chrome://tracing`` open
  directly.  :func:`export_chrome_trace` takes the same ``log_dir``
  convention as :func:`csvplus_tpu.utils.observe.profile_to`, so the
  host-side span trace and the JAX device trace of one run land side by
  side and open in the same Perfetto session.
* :func:`spans_to_json` / :func:`write_spans_jsonl` emit one flat JSON
  object per span — the shape the bench artifacts embed and the
  ``obs diff`` tooling consumes.

:func:`validate_chrome_trace` is the schema check the ``make
trace-smoke`` gate runs over the emitted file: it returns a list of
problems (empty = valid) rather than raising, so the gate can print
every violation at once.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Union

from .span import Span, Trace, tracer

#: Keys every trace event must carry; "ts" is additionally required for
#: "X" events but NOT for "M" metadata (per the Trace Event spec).
_REQUIRED_EVENT_KEYS = ("name", "ph", "pid", "tid")


def _iter_spans(traces: Iterable[Trace]) -> Iterable[Span]:
    for t in traces:
        yield from t.snapshot()


def chrome_trace_events(traces: Sequence[Trace]) -> List[Dict[str, Any]]:
    """Chrome Trace Event list for *traces*: one ``"X"`` (complete)
    event per span plus ``"M"`` metadata naming the process and each
    lane.  ``tid`` is a dense integer per distinct lane; timestamps are
    microseconds relative to the earliest span so the viewer opens at
    t=0."""
    pid = os.getpid()
    spans = list(_iter_spans(traces))
    if not spans:
        return []
    t0 = min(s.t_start for s in spans)
    lanes: Dict[str, int] = {}
    events: List[Dict[str, Any]] = [
        {
            "name": "process_name",
            "ph": "M",
            "pid": pid,
            "tid": 0,
            "args": {"name": "csvplus-host"},
        }
    ]
    for s in spans:
        tid = lanes.get(s.lane)
        if tid is None:
            tid = lanes[s.lane] = len(lanes) + 1
            events.append(
                {
                    "name": "thread_name",
                    "ph": "M",
                    "pid": pid,
                    "tid": tid,
                    "args": {"name": s.lane},
                }
            )
        args: Dict[str, Any] = {
            "trace_id": s.trace_id,
            "span_id": s.span_id,
        }
        if s.parent_id is not None:
            args["parent_id"] = s.parent_id
        for k, v in s.attrs.items():
            args[k] = v if isinstance(v, (int, float, str, bool)) else repr(v)
        events.append(
            {
                "name": s.name,
                "cat": "csvplus",
                "ph": "X",
                "ts": round((s.t_start - t0) * 1e6, 3),
                "dur": round(s.seconds * 1e6, 3),
                "pid": pid,
                "tid": tid,
                "args": args,
            }
        )
    return events


def write_chrome_trace(
    path: str, traces: Optional[Sequence[Trace]] = None
) -> str:
    """Write *traces* (default: every finished trace in the global
    tracer) as one Chrome-trace JSON file; returns the path."""
    if traces is None:
        traces = tracer.finished()
    payload = {
        "traceEvents": chrome_trace_events(traces),
        "displayTimeUnit": "ms",
        "metadata": {"producer": "csvplus_tpu.obs"},
    }
    with open(path, "w") as f:
        json.dump(payload, f)
        f.write("\n")
    return path


def export_chrome_trace(
    log_dir: str, traces: Optional[Sequence[Trace]] = None
) -> str:
    """Write the host span trace under *log_dir* — the same directory
    ``profile_to(log_dir)`` fills with the JAX device trace — as
    ``csvplus_host_trace.<pid>.json``; returns the file path."""
    os.makedirs(log_dir, exist_ok=True)
    path = os.path.join(log_dir, f"csvplus_host_trace.{os.getpid()}.json")
    return write_chrome_trace(path, traces)


def validate_chrome_trace(obj: Union[dict, list]) -> List[str]:
    """Schema check for a Chrome-trace payload: returns every problem
    found (empty list = valid).  Accepts both the object form
    (``{"traceEvents": [...]}``) and the bare array form."""
    problems: List[str] = []
    if isinstance(obj, dict):
        events = obj.get("traceEvents")
        if not isinstance(events, list):
            return ["traceEvents missing or not a list"]
    elif isinstance(obj, list):
        events = obj
    else:
        return [f"payload is {type(obj).__name__}, expected dict or list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event[{i}] is not an object")
            continue
        for k in _REQUIRED_EVENT_KEYS:
            if k not in ev:
                problems.append(f"event[{i}] ({ev.get('name')!r}) missing {k!r}")
        ph = ev.get("ph")
        if ph == "X":
            if not isinstance(ev.get("dur"), (int, float)):
                problems.append(f"event[{i}] ({ev.get('name')!r}) X without numeric dur")
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)):
                problems.append(f"event[{i}] ({ev.get('name')!r}) X without numeric ts")
            elif ts < 0:
                problems.append(f"event[{i}] ({ev.get('name')!r}) negative ts")
        elif ph == "M":
            if not isinstance(ev.get("args"), dict):
                problems.append(f"event[{i}] metadata without args")
        elif ph is None:
            pass  # already reported as missing
        elif not isinstance(ph, str):
            problems.append(f"event[{i}] ph is not a string")
    return problems


def spans_to_json(traces: Optional[Sequence[Trace]] = None) -> List[Dict[str, Any]]:
    """Flat JSON-safe span dicts (the bench-artifact embedding shape)."""
    if traces is None:
        traces = tracer.finished()
    return [s.to_json() for s in _iter_spans(traces)]


def write_spans_jsonl(
    path: str, traces: Optional[Sequence[Trace]] = None
) -> str:
    """One JSON object per line per span; returns the path."""
    rows = spans_to_json(traces)
    with open(path, "w") as f:
        for row in rows:
            f.write(json.dumps(row))
            f.write("\n")
    return path


class SpanJsonlSink:
    """Incremental JSON-lines span sink for long runs: call
    :meth:`flush` periodically to append newly-finished traces without
    holding every span in memory until the end."""

    def __init__(self, path: str):
        self.path = path
        self.written = 0
        self._t_open = time.time()
        # truncate on open: one sink = one run's spans
        with open(path, "w"):
            pass

    def flush(self) -> int:
        """Drain finished traces from the global tracer into the file;
        returns the number of spans appended."""
        rows = spans_to_json(tracer.drain())
        if rows:
            with open(self.path, "a") as f:
                for row in rows:
                    f.write(json.dumps(row))
                    f.write("\n")
            self.written += len(rows)
        return len(rows)
