"""Space-Saving top-K heavy-hitter sketch over observed keys (ISSUE 13).

The serving tier needs key-frequency evidence — which probe keys are
hot per index, which build-side keys dominate sealed delta tiers — to
feed the skew-aware join work (ROADMAP item 2) and the ``obs skew``
report, without holding the full key stream.  :class:`SpaceSaving`
implements the Metwally/Agrawal/El Abbadi stream-summary sketch: at
most *k* tracked keys, each with a count and an over-estimation error
bound.  Guarantees (the ones the tests pin):

* any key whose true frequency exceeds ``observed / k`` is present;
* for a tracked key, ``count - err <= true count <= count``;
* with fewer than *k* distinct keys the counts are EXACT (err 0).

Implementation note: evicting the minimum-count entry is the classic
cost center.  A lazy min-heap of ``(count, key)`` tuples (stale entries
skipped on pop, heap rebuilt when it outgrows the live set) keeps
``offer`` amortized O(log k) instead of an O(k) scan per miss, so the
sketch can sit on the serving probe path within the always-on budget.

Thread model: a monitor — ``offer``/``offer_many``/``offer_counts``
take the instance lock; the batch forms are one lock round for a whole
coalesced batch (the r08 discipline).
"""

from __future__ import annotations

import heapq
import threading
from typing import Dict, Hashable, Iterable, List, Optional, Tuple

__all__ = ["SpaceSaving", "skew_report"]


def _json_key(key: Hashable) -> object:
    """JSON-safe rendering of a tracked key: scalars pass through,
    tuples (composite index keys) become lists, anything else is
    stringified."""
    if isinstance(key, (str, int, float, bool)) or key is None:
        return key
    if isinstance(key, tuple):
        return [_json_key(p) for p in key]
    return str(key)


class SpaceSaving:
    """Bounded top-K frequency sketch (Space-Saving / stream-summary)."""

    __slots__ = ("k", "_lock", "_counts", "_errs", "_heap", "_observed")

    def __init__(self, k: int = 32):
        if k < 1:
            raise ValueError("k must be >= 1")
        self.k = int(k)
        self._lock = threading.Lock()
        self._counts: Dict[Hashable, int] = {}
        self._errs: Dict[Hashable, int] = {}
        # lazy min-heap of (count, key); entries go stale when a key's
        # count moves on — popped entries are validated against _counts
        self._heap: List[Tuple[int, Hashable]] = []
        self._observed = 0

    # -- ingest ------------------------------------------------------------

    def offer(self, key: Hashable, n: int = 1) -> None:
        """Count one observation of *key* (*n* occurrences)."""
        with self._lock:
            self._offer_locked(key, n)

    def offer_many(self, keys: Iterable[Hashable]) -> None:
        """Count a batch of observations in ONE lock round — the same
        per-dispatch-cycle discipline as ``ServingMetrics``.  Duplicate
        keys in the batch (the normal case under a Zipf workload) are
        aggregated OUTSIDE the lock first, so a hot key costs one
        counter update per batch, not one per occurrence."""
        agg: Dict[Hashable, int] = {}
        for key in keys:
            agg[key] = agg.get(key, 0) + 1
        with self._lock:
            for key, n in agg.items():
                self._offer_locked(key, n)

    def offer_counts(self, keys: Iterable[Hashable], counts: Iterable[int]) -> None:
        """Count PRE-AGGREGATED ``(key, count)`` pairs in one lock round
        — the partitioned join planner's entry point: a strided device
        sample lands as ``np.unique(..., return_counts=True)`` output
        and feeds straight in.  Numpy scalars are unwrapped to native
        ints/strs outside the lock so tracked keys (and their exported
        snapshots) stay JSON-clean and hash-stable across callers."""
        pairs = [
            (key.item() if hasattr(key, "item") else key, int(n))
            for key, n in zip(keys, counts)
        ]
        with self._lock:
            for key, n in pairs:
                if n > 0:
                    self._offer_locked(key, n)

    def _offer_locked(self, key: Hashable, n: int) -> None:
        self._observed += n
        counts = self._counts
        c = counts.get(key)
        if c is not None:
            counts[key] = c + n
            heapq.heappush(self._heap, (c + n, key))
            return
        if len(counts) < self.k:
            counts[key] = n
            self._errs[key] = 0
            heapq.heappush(self._heap, (n, key))
            return
        # evict the true minimum: pop stale heap entries until one
        # matches its key's live count
        heap = self._heap
        while heap:
            mc, mk = heap[0]
            if counts.get(mk) == mc:
                break
            heapq.heappop(heap)
        mc, mk = heapq.heappop(heap)
        del counts[mk]
        del self._errs[mk]
        counts[key] = mc + n
        self._errs[key] = mc
        heapq.heappush(heap, (mc + n, key))
        if len(heap) > 8 * self.k:
            # rebuild from live entries so stale tuples cannot grow
            # the heap without bound
            self._heap = [(v, kk) for kk, v in counts.items()]
            heapq.heapify(self._heap)

    # -- export ------------------------------------------------------------

    @property
    def observed(self) -> int:
        with self._lock:
            return self._observed

    def topk(self, n: Optional[int] = None) -> List[Tuple[Hashable, int, int]]:
        """The tracked keys as ``(key, count, err)`` sorted by count
        descending (count ties broken by key repr for determinism)."""
        with self._lock:
            items = [
                (key, c, self._errs[key]) for key, c in self._counts.items()
            ]
        items.sort(key=lambda t: (-t[1], repr(t[0])))
        return items if n is None else items[:n]

    def snapshot(self, n: Optional[int] = None) -> Dict[str, object]:
        """JSON-safe export: ``{k, observed, top: [{key, count, err}]}``.
        ``count/observed`` is the estimated frequency share; a key is a
        guaranteed heavy hitter when ``(count - err) / observed``
        already clears the caller's threshold."""
        top = self.topk(n)
        with self._lock:
            observed = self._observed
        return {
            "k": self.k,
            "observed": observed,
            "top": [
                {"key": _json_key(key), "count": c, "err": e}
                for key, c, e in top
            ],
        }


def skew_report(snapshot: Dict[str, object], *, top: int = 10) -> str:
    """Render one sketch snapshot as an aligned text table with
    frequency shares and the guaranteed-lower-bound share — the body of
    ``python -m csvplus_tpu.obs skew``."""
    observed = int(snapshot.get("observed", 0) or 0)
    rows = list(snapshot.get("top", []))[:top]
    lines = [f"observed={observed} tracked<=k={snapshot.get('k')}"]
    if not rows:
        lines.append("  (no keys observed)")
        return "\n".join(lines)
    width = max(len(str(r["key"])) for r in rows)
    lines.append(
        f"  {'key':<{width}}  {'count':>10}  {'err':>8}  "
        f"{'share':>7}  {'min_share':>9}"
    )
    for r in rows:
        c, e = int(r["count"]), int(r["err"])
        share = c / observed if observed else 0.0
        floor = (c - e) / observed if observed else 0.0
        lines.append(
            f"  {str(r['key']):<{width}}  {c:>10}  {e:>8}  "
            f"{share:>6.2%}  {floor:>8.2%}"
        )
    return "\n".join(lines)
