"""CLI for the observability subsystem.

``python -m csvplus_tpu.obs diff A.json B.json [--mode auto|stages|bench]
[--threshold N] [--min-share 0.005] [--key stage_table] [--json]
[--fail-on-flag]``
    Compare two bench artifacts.  ``stages`` mode diffs embedded stage
    tables (the r05->r06 warm-join diagnosis as a command); ``bench``
    mode diffs ANY two same-family bench records leaf by leaf (the
    wal/delta/serve/view families, e.g. BENCH_WAL_r11.json vs
    BENCH_WAL_r12.json).  ``auto`` (default) tries stage tables first
    and falls back to the bench-record diff.  ``--fail-on-flag`` exits
    2 when anything is flagged; load/shape errors exit 1.

``python -m csvplus_tpu.obs skew ARTIFACT.json [--top N] [--side
probe|build] [--json]``
    Render the heavy-hitter report from an artifact carrying sketch
    snapshots — a flight-recorder dump, an ``obs-smoke`` record, or any
    JSON embedding a ``skew`` section (``{probe: {index: snapshot},
    build: {...}}``) or a bare sketch ``snapshot()`` dict.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Tuple

from .diff import (
    DEFAULT_BENCH_THRESHOLD,
    DEFAULT_MIN_SHARE,
    DEFAULT_THRESHOLD,
    diff_bench_files,
    diff_files,
    format_bench_diff,
    format_diff,
)
from .sketch import skew_report


def _run_diff(args) -> int:
    result = None
    if args.mode in ("auto", "stages"):
        try:
            result = diff_files(
                args.artifact_a,
                args.artifact_b,
                threshold=args.threshold or DEFAULT_THRESHOLD,
                min_share=args.min_share,
                key=args.key,
            )
            label = format_diff
        except ValueError:
            if args.mode == "stages":
                raise
    if result is None:
        result = diff_bench_files(
            args.artifact_a,
            args.artifact_b,
            threshold=args.threshold or DEFAULT_BENCH_THRESHOLD,
        )
        label = format_bench_diff
        if (
            not result["rows"]
            and result["family_a"] is None
            and result["family_b"] is None
        ):
            raise ValueError(
                "nothing comparable: no stage tables, no shared numeric"
                " leaves, and neither artifact declares a metric family"
            )
    if args.json:
        print(json.dumps(result))
    else:
        print(label(result, args.artifact_a, args.artifact_b))
    if args.fail_on_flag and result["flagged"]:
        return 2
    return 0


def _find_sketches(obj: Any) -> List[Tuple[str, Dict[str, Any]]]:
    """Locate sketch snapshots in an arbitrary artifact: a bare
    snapshot (``k``/``observed``/``top`` keys), or a ``skew`` section
    mapping side -> index -> snapshot (the :meth:`TelemetryPlane
    .skew_snapshot` shape), searched one level deep under common
    wrapper keys."""
    out: List[Tuple[str, Dict[str, Any]]] = []
    if not isinstance(obj, dict):
        return out
    if {"k", "observed", "top"} <= set(obj):
        return [("sketch", obj)]
    skew = obj.get("skew") or obj
    for side in ("probe", "build"):
        sides = skew.get(side)
        if isinstance(sides, dict):
            for index, snap in sorted(sides.items()):
                if isinstance(snap, dict) and {"observed", "top"} <= set(snap):
                    out.append((f"{side}:{index}", snap))
    if not out:
        for wrapper in ("context", "obs", "telemetry"):
            inner = obj.get(wrapper)
            if isinstance(inner, dict):
                out.extend(
                    (f"{wrapper}.{name}", snap)
                    for name, snap in _find_sketches(inner)
                )
    return out


def _run_skew(args) -> int:
    with open(args.artifact) as f:
        obj = json.load(f)
    found = _find_sketches(obj)
    if args.side:
        found = [(n, s) for n, s in found if n.startswith(args.side)]
    if not found:
        raise ValueError(
            f"{args.artifact}: no sketch snapshots found"
            " (expected a `skew` section or a {k, observed, top} dict)"
        )
    if args.json:
        print(json.dumps({name: snap for name, snap in found}))
        return 0
    for i, (name, snap) in enumerate(found):
        if i:
            print()
        print(f"[{name}]")
        print(skew_report(snap, top=args.top))
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m csvplus_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)

    d = sub.add_parser("diff", help="diff two bench artifacts")
    d.add_argument("artifact_a")
    d.add_argument("artifact_b")
    d.add_argument(
        "--mode", choices=("auto", "stages", "bench"), default="auto",
        help="stage-table diff, bench-record diff, or auto-detect",
    )
    d.add_argument(
        "--threshold", type=float, default=None,
        help=f"flag ratio (default {DEFAULT_THRESHOLD} for stages,"
             f" {DEFAULT_BENCH_THRESHOLD} for bench)",
    )
    d.add_argument("--min-share", type=float, default=DEFAULT_MIN_SHARE)
    d.add_argument("--key", default=None, help="artifact key holding the table")
    d.add_argument("--json", action="store_true", help="machine output")
    d.add_argument(
        "--fail-on-flag",
        action="store_true",
        help="exit 2 when anything is flagged",
    )

    s = sub.add_parser("skew", help="heavy-hitter report from sketch snapshots")
    s.add_argument("artifact")
    s.add_argument("--top", type=int, default=10)
    s.add_argument("--side", choices=("probe", "build"), default=None)
    s.add_argument("--json", action="store_true", help="machine output")

    args = parser.parse_args(argv)
    try:
        if args.cmd == "diff":
            return _run_diff(args)
        return _run_skew(args)
    except (OSError, ValueError) as e:
        print(f"obs {args.cmd}: {e}", file=sys.stderr)
        return 1


if __name__ == "__main__":
    sys.exit(main())
