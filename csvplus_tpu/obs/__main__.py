"""CLI for the observability subsystem.

``python -m csvplus_tpu.obs diff A.json B.json [--threshold 2.0]
[--min-share 0.005] [--key stage_table] [--json] [--fail-on-flag]``
    Compare two bench artifacts' stage tables and flag stages whose
    time (or RSS) share moved beyond the threshold — the r05->r06
    warm-join diagnosis as a command.  ``--fail-on-flag`` exits 2 when
    anything is flagged (for CI gates); load/shape errors exit 1.
"""

from __future__ import annotations

import argparse
import json
import sys

from .diff import DEFAULT_MIN_SHARE, DEFAULT_THRESHOLD, diff_files, format_diff


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m csvplus_tpu.obs")
    sub = parser.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("diff", help="diff two artifacts' stage tables")
    d.add_argument("artifact_a")
    d.add_argument("artifact_b")
    d.add_argument("--threshold", type=float, default=DEFAULT_THRESHOLD)
    d.add_argument("--min-share", type=float, default=DEFAULT_MIN_SHARE)
    d.add_argument("--key", default=None, help="artifact key holding the table")
    d.add_argument("--json", action="store_true", help="machine output")
    d.add_argument(
        "--fail-on-flag",
        action="store_true",
        help="exit 2 when any stage is flagged",
    )
    args = parser.parse_args(argv)

    try:
        result = diff_files(
            args.artifact_a,
            args.artifact_b,
            threshold=args.threshold,
            min_share=args.min_share,
            key=args.key,
        )
    except (OSError, ValueError) as e:
        print(f"obs diff: {e}", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(result))
    else:
        print(format_diff(result, args.artifact_a, args.artifact_b))
    if args.fail_on_flag and result["flagged"]:
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
