"""Production telemetry plane: typed metric registry, Prometheus
exposition, JSONL time-series pump, tail-sampled request tracing, and
the :class:`TelemetryPlane` bundle the serving tier wires in (ISSUE 13).

Layers, bottom up:

* Instruments — :class:`Counter`, :class:`Gauge`, :class:`Histogram`
  (exponential buckets).  Each is a tiny monitor; the serve-side call
  sites batch their updates so the dispatcher pays a CONSTANT number of
  lock rounds per cycle, never one per request (the r08 discipline
  ``ServingMetrics`` set).
* :class:`MetricRegistry` — owns instruments by name plus pull-time
  COLLECTORS (zero-arg callables yielding :class:`Sample` rows).  The
  existing observability surfaces — ``ServingMetrics.snapshot()``, WAL
  stats, ``ReadAmpTracker``, ``RecompileWatch``'s compile counts,
  ``rss_mb`` — publish through collectors, so scrape cost is paid by
  the scraper, not the serving hot path.
* Exposition — :meth:`MetricRegistry.render` (Prometheus text format),
  :class:`PromHttpEndpoint` (stdlib ``http.server``, OFF by default),
  and :class:`MetricsPump` (periodic JSONL rows using the same
  ``log_dir`` convention as :class:`~csvplus_tpu.obs.export
  .SpanJsonlSink`).  The pump also samples the ``rss_mb`` watermark
  gauge so long-running serve sessions see memory growth.
* :class:`TailSampler` — always-on tail-sampled tracing: every request
  is offered (one lock round per dispatch cycle), but full records are
  RETAINED only for errors, deadline misses, and latency above a
  rolling p99 threshold, in a bounded ring — the trace-smoke ≤2%
  overhead budget applies (``make obs-smoke`` asserts it).
* :class:`TelemetryPlane` — the bundle :class:`LookupServer` owns:
  registry + tail sampler + per-index probe/build-key
  :class:`~csvplus_tpu.obs.sketch.SpaceSaving` sketches + the global
  :mod:`~csvplus_tpu.obs.flight` recorder, with ``attach_server()``
  wiring every serve/storage/view series into one scrape surface.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import (
    Callable,
    Dict,
    Iterable,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

from . import flight as _flight
from .memory import peak_rss_mb, rss_mb
from .recompile import compile_counts
from .sketch import SpaceSaving

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricRegistry",
    "MetricsPump",
    "PromHttpEndpoint",
    "Sample",
    "TailSampler",
    "TelemetryPlane",
]


class Sample(NamedTuple):
    """One exposition row: series name, instrument kind (``counter`` /
    ``gauge`` — histograms expand into their component series before
    reaching samples), sorted label pairs, numeric value."""

    name: str
    kind: str
    labels: Tuple[Tuple[str, str], ...]
    value: float


def _esc(v: object) -> str:
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def series_id(name: str, labels: Tuple[Tuple[str, str], ...]) -> str:
    """Canonical ``name{k="v",...}`` series identifier (also the JSONL
    pump's key format)."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{_esc(v)}"' for k, v in labels)
    return f"{name}{{{inner}}}"


def _num(v: object) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return repr(float(v))


# -- instruments -----------------------------------------------------------


class Counter:
    """Monotonic counter (a monitor; ``inc`` is one lock round)."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Sample]:
        return [Sample(self.name, "counter", (), self.value)]


class Gauge:
    """Point-in-time value; ``set`` replaces, ``add`` adjusts."""

    __slots__ = ("name", "help", "_lock", "_value")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def add(self, v: float) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Sample]:
        return [Sample(self.name, "gauge", (), self.value)]


class Histogram:
    """Exponential-bucket histogram: upper bounds ``start * factor**i``
    for *count* buckets plus +Inf, rendered in the Prometheus
    cumulative ``_bucket``/``_sum``/``_count`` shape.
    ``observe_many`` is one lock round for a whole batch."""

    __slots__ = ("name", "help", "bounds", "_lock", "_counts", "_sum", "_n")

    def __init__(
        self,
        name: str,
        help: str = "",
        *,
        start: float = 1e-4,
        factor: float = 2.0,
        count: int = 16,
    ):
        if start <= 0 or factor <= 1 or count < 1:
            raise ValueError("need start > 0, factor > 1, count >= 1")
        self.name = name
        self.help = help
        self.bounds = tuple(start * factor**i for i in range(count))
        self._lock = threading.Lock()
        self._counts = [0] * (count + 1)  # last slot = +Inf overflow
        self._sum = 0.0
        self._n = 0

    def _slot(self, v: float) -> int:
        for i, b in enumerate(self.bounds):
            if v <= b:
                return i
        return len(self.bounds)

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[self._slot(v)] += 1
            self._sum += v
            self._n += 1

    def observe_many(self, values: Iterable[float]) -> None:
        with self._lock:
            for v in values:
                self._counts[self._slot(v)] += 1
                self._sum += v
                self._n += 1

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            counts = list(self._counts)
            total, n = self._sum, self._n
        return {"bounds": list(self.bounds), "counts": counts,
                "sum": round(total, 9), "count": n}

    def samples(self) -> List[Sample]:
        snap = self.snapshot()
        out: List[Sample] = []
        acc = 0
        for b, c in zip(snap["bounds"], snap["counts"]):
            acc += c
            out.append(
                Sample(self.name + "_bucket", "histogram",
                       (("le", repr(float(b))),), acc)
            )
        acc += snap["counts"][-1]
        out.append(
            Sample(self.name + "_bucket", "histogram", (("le", "+Inf"),), acc)
        )
        out.append(Sample(self.name + "_sum", "histogram", (), snap["sum"]))
        out.append(Sample(self.name + "_count", "histogram", (), snap["count"]))
        return out


# -- registry --------------------------------------------------------------


class MetricRegistry:
    """Named instruments + pull-time collectors, one scrape surface.

    Instrument constructors are idempotent per name (re-requesting an
    existing name returns the existing instrument; a kind mismatch
    raises).  A collector is a zero-arg callable returning an iterable
    of :class:`Sample`; a collector that raises is skipped for that
    scrape and counted in ``csvplus_registry_collector_errors_total``
    — a broken publisher must not take the whole surface down.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, object] = {}
        self._collectors: List[Tuple[str, Callable[[], Iterable[Sample]]]] = []
        self._collector_errors = 0

    def _instrument(self, cls, name: str, help: str, **kw):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is not None:
                if not isinstance(inst, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(inst).__name__}, not {cls.__name__}"
                    )
                return inst
            inst = cls(name, help, **kw)
            self._instruments[name] = inst
            return inst

    def counter(self, name: str, help: str = "") -> Counter:
        return self._instrument(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._instrument(Gauge, name, help)

    def histogram(self, name: str, help: str = "", **kw) -> Histogram:
        return self._instrument(Histogram, name, help, **kw)

    def register_collector(
        self, fn: Callable[[], Iterable[Sample]], name: str = ""
    ) -> None:
        with self._lock:
            self._collectors.append((name or getattr(fn, "__name__", "?"), fn))

    # -- scrape ------------------------------------------------------------

    def collect(self) -> List[Sample]:
        """All current samples: instruments first, then collectors."""
        with self._lock:
            instruments = list(self._instruments.values())
            collectors = list(self._collectors)
            errors = self._collector_errors
        out: List[Sample] = []
        for inst in instruments:
            out.extend(inst.samples())
        for cname, fn in collectors:
            try:
                out.extend(fn())
            except Exception as err:
                errors += 1
                with self._lock:
                    self._collector_errors += 1
                sys.stderr.write(
                    f"csvplus-metrics: collector {cname!r} failed "
                    f"({type(err).__name__}: {err}) — skipped this scrape\n"
                )
        out.append(
            Sample("csvplus_registry_collector_errors_total", "counter",
                   (), errors)
        )
        return out

    def render(self) -> str:
        """Prometheus text exposition (version 0.0.4): ``# HELP`` /
        ``# TYPE`` once per metric family, samples grouped under it."""
        helps: Dict[str, str] = {}
        with self._lock:
            for inst in self._instruments.values():
                helps[inst.name] = inst.help
        samples = self.collect()
        by_family: Dict[str, Tuple[str, List[Sample]]] = {}
        order: List[str] = []
        for s in samples:
            family = s.name
            if s.kind == "histogram":
                for suffix in ("_bucket", "_sum", "_count"):
                    if family.endswith(suffix):
                        family = family[: -len(suffix)]
                        break
            if family not in by_family:
                by_family[family] = (s.kind, [])
                order.append(family)
            by_family[family][1].append(s)
        lines: List[str] = []
        for family in sorted(order):
            kind, rows = by_family[family]
            h = helps.get(family, "")
            if h:
                lines.append(f"# HELP {family} {_esc(h)}")
            lines.append(f"# TYPE {family} {kind}")
            for s in rows:
                lines.append(f"{series_id(s.name, s.labels)} {_num(s.value)}")
        return "\n".join(lines) + "\n"

    def sample_dict(self) -> Dict[str, float]:
        """Flat ``{series_id: value}`` dict — the JSONL pump's row
        payload and the flight recorder's metric-delta context."""
        return {series_id(s.name, s.labels): s.value for s in self.collect()}


# -- serve/storage/view collectors ----------------------------------------

#: by_index cell keys that are point-in-time values, not monotonic.
_INDEX_GAUGE_KEYS = frozenset({"deltas_live", "last_compact_ms"})
_VIEW_GAUGE_KEYS = frozenset({"epoch"})


def _scalar_samples(
    prefix: str, kind: str, d: Dict[str, object],
    labels: Tuple[Tuple[str, str], ...] = (),
    gauge_keys: frozenset = frozenset(),
) -> Iterable[Sample]:
    for key, v in d.items():
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            continue
        k = "gauge" if (kind == "gauge" or key in gauge_keys) else "counter"
        yield Sample(f"{prefix}_{key}", k, labels, v)


def serve_samples(
    snapshot: Dict[str, object],
    readamp: Optional[Dict[str, Dict[str, object]]] = None,
) -> List[Sample]:
    """Map one ``ServingMetrics.snapshot()`` dict (plus an optional
    per-index ``ReadAmpTracker`` snapshot map) onto exposition samples:
    top-level serve counters, latency/queue-wait quantile gauges,
    per-index cells labelled ``index=...``, per-view cells labelled
    ``view=...``, plan-cache stats, and read-amp series."""
    out: List[Sample] = []
    counter_keys = (
        "ticks", "enqueued", "completed", "shed", "expired", "failed",
        "retried", "degraded", "callback_errors",
    )
    for key in counter_keys:
        v = snapshot.get(key)
        if isinstance(v, (int, float)):
            out.append(Sample(f"csvplus_serve_{key}_total", "counter", (), v))
    for key in ("queue_depth_last", "queue_depth_max"):
        v = snapshot.get(key)
        if isinstance(v, (int, float)):
            out.append(Sample(f"csvplus_serve_{key}", "gauge", (), v))
    for which in ("latency", "queue_wait"):
        res = snapshot.get(which)
        if isinstance(res, dict):
            for q in ("p50_ms", "p90_ms", "p99_ms", "max_ms"):
                v = res.get(q)
                if isinstance(v, (int, float)):
                    out.append(
                        Sample(f"csvplus_serve_{which}_ms", "gauge",
                               (("quantile", q[:-3]),), v)
                    )
    for name, cell in (snapshot.get("by_index") or {}).items():
        out.extend(
            _scalar_samples("csvplus_index", "counter", cell,
                            (("index", str(name)),), _INDEX_GAUGE_KEYS)
        )
    for name, cell in (snapshot.get("by_view") or {}).items():
        out.extend(
            _scalar_samples("csvplus_view", "counter", cell,
                            (("view", str(name)),), _VIEW_GAUGE_KEYS)
        )
    pc = snapshot.get("plancache")
    if isinstance(pc, dict):
        out.extend(_scalar_samples("csvplus_plancache", "gauge", pc))
    for name, ra in (readamp or {}).items():
        out.extend(
            _scalar_samples("csvplus_readamp", "gauge", ra,
                            (("index", str(name)),))
        )
    return out


def process_samples() -> List[Sample]:
    """Process-level series: peak RSS watermark and the per-kernel
    compile-cache sizes ``RecompileWatch`` reads (a cache size that
    GROWS between scrapes is a recompile)."""
    out = [Sample("csvplus_process_peak_rss_mb", "gauge", (), peak_rss_mb())]
    for kernel, n in compile_counts().items():
        if n is not None:
            out.append(
                Sample("csvplus_compile_cache_size", "gauge",
                       (("kernel", str(kernel)),), n)
            )
    return out


# -- tail-sampled tracing --------------------------------------------------


class TailSampler:
    """Always-on tail sampling over per-request completion records.

    Every dispatch cycle offers its whole sample batch in ONE lock
    round; a record is RETAINED (into a bounded ring) only when its
    outcome is not ``ok`` (errors, deadline misses) or its latency
    clears a rolling p99 threshold computed over a bounded window of
    recent latencies.  Threshold recomputation is amortized (every
    *recompute* offers), so the per-record cost is a few comparisons —
    the ≤2% disarmed-overhead budget ``trace-smoke`` enforces applies
    to this path via ``make obs-smoke``.

    Records are the extended serve sample tuples
    ``(latency_s, wait_s, outcome, kind, index, error)`` — trailing
    fields optional."""

    def __init__(
        self,
        capacity: int = 256,
        window: int = 512,
        recompute: int = 128,
        min_latency_s: float = 0.0,
    ):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._window: List[float] = []
        self._window_cap = int(window)
        self._window_i = 0
        self._recompute = int(recompute)
        self._since_recompute = 0
        self._threshold_s = float("inf")
        self._min_latency_s = float(min_latency_s)
        self._retained: List[Dict[str, object]] = []
        self._offered = 0
        self._kept_error = 0
        self._kept_expired = 0
        self._kept_slow = 0

    def offer_batch(self, samples: Sequence[tuple]) -> None:
        """One lock round for a whole cycle's completion records.  The
        common case (ok outcome, under-threshold latency) is a handful
        of local-variable ops per record — attribute state is hoisted
        once per batch, written back once (this path rides EVERY
        dispatch cycle; ``make obs-smoke`` holds it to the ≤2%
        budget)."""
        t = time.time()
        with self._lock:
            window = self._window
            window_cap = self._window_cap
            wi = self._window_i
            since = self._since_recompute
            thr = self._threshold_s
            offered = self._offered
            recompute = self._recompute
            n_win = len(window)
            for s in samples:
                latency_s = s[0]
                outcome = s[2]
                offered += 1
                if n_win < window_cap:
                    window.append(latency_s)
                    n_win += 1
                else:
                    window[wi] = latency_s
                    wi = (wi + 1) % window_cap
                since += 1
                if since >= recompute:
                    since = 0
                    w = sorted(window)
                    rank = min(len(w) - 1, int(0.99 * len(w)))
                    thr = max(w[rank], self._min_latency_s)
                slow = latency_s > thr
                if outcome == "ok" and not slow:
                    continue
                if outcome == "expired":
                    self._kept_expired += 1
                elif outcome != "ok":
                    self._kept_error += 1
                else:
                    self._kept_slow += 1
                rec: Dict[str, object] = {
                    "ts": t,
                    "latency_ms": round(latency_s * 1e3, 4),
                    "wait_ms": round(s[1] * 1e3, 4),
                    "outcome": outcome,
                }
                if len(s) > 3 and s[3]:
                    rec["kind"] = s[3]
                if len(s) > 4 and s[4]:
                    rec["index"] = s[4]
                if len(s) > 5 and s[5]:
                    rec["error"] = s[5]
                if slow:
                    rec["slow"] = True
                self._retained.append(rec)
                if len(self._retained) > self.capacity:
                    del self._retained[: len(self._retained) - self.capacity]
            self._offered = offered
            self._window_i = wi
            self._since_recompute = since
            self._threshold_s = thr

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            thr = self._threshold_s
            return {
                "offered": self._offered,
                "retained": len(self._retained),
                "kept_error": self._kept_error,
                "kept_expired": self._kept_expired,
                "kept_slow": self._kept_slow,
                "p99_threshold_ms": (
                    None if thr == float("inf") else round(thr * 1e3, 4)
                ),
                "records": list(self._retained),
            }

    def samples(self) -> List[Sample]:
        with self._lock:
            rows = [
                ("csvplus_tail_offered_total", "counter", self._offered),
                ("csvplus_tail_retained", "gauge", len(self._retained)),
                ("csvplus_tail_kept_error_total", "counter", self._kept_error),
                ("csvplus_tail_kept_expired_total", "counter",
                 self._kept_expired),
                ("csvplus_tail_kept_slow_total", "counter", self._kept_slow),
            ]
        return [Sample(n, k, (), v) for n, k, v in rows]


# -- exposition transports -------------------------------------------------


class PromHttpEndpoint:
    """Optional stdlib scrape endpoint (OFF by default — nothing in the
    tree starts one unless asked).  ``start()`` binds ``addr:port``
    (port 0 picks a free port), serves ``GET /metrics`` from a daemon
    thread, and returns the bound port."""

    def __init__(self, registry: MetricRegistry, *,
                 addr: str = "127.0.0.1", port: int = 0):
        self.registry = registry
        self.addr = addr
        self.port = int(port)
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> int:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        registry = self.registry

        class _Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib handler API)
                if self.path.split("?", 1)[0] not in ("/", "/metrics"):
                    self.send_error(404)
                    return
                body = registry.render().encode("utf-8")
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._httpd = ThreadingHTTPServer((self.addr, self.port), _Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="csvplus-prom",
            daemon=True,
        )
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class MetricsPump:
    """Periodic JSONL time-series sink, same ``log_dir`` convention as
    :class:`~csvplus_tpu.obs.export.SpanJsonlSink`: one
    ``csvplus_metrics.<pid>.jsonl`` file (truncated on open), one
    ``{"ts": ..., "series": {...}}`` row per tick.  Each tick also
    samples the current ``rss_mb`` into the plane's RSS gauge, so the
    exported series carries the memory watermark between bench
    boundaries.  ``tick()`` is public for deterministic tests."""

    def __init__(
        self,
        registry: MetricRegistry,
        log_dir: str,
        *,
        interval_s: float = 1.0,
        on_tick: Optional[Callable[[], None]] = None,
    ):
        os.makedirs(log_dir, exist_ok=True)
        self.path = os.path.join(
            log_dir, f"csvplus_metrics.{os.getpid()}.jsonl"
        )
        self.registry = registry
        self.interval_s = float(interval_s)
        self._on_tick = on_tick
        self._lock = threading.Lock()
        self._file = open(self.path, "w")
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    def tick(self) -> None:
        """Sample every series and append one JSONL row."""
        if self._on_tick is not None:
            self._on_tick()
        row = {"ts": time.time(), "series": self.registry.sample_dict()}
        line = json.dumps(row, default=str)
        with self._lock:
            if self._file.closed:
                return
            self._file.write(line + "\n")
            self._file.flush()
            self.ticks += 1

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.tick()
            except Exception as err:
                sys.stderr.write(
                    f"csvplus-metrics: pump tick failed "
                    f"({type(err).__name__}: {err})\n"
                )

    def start(self) -> "MetricsPump":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="csvplus-metrics-pump", daemon=True
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            if not self._file.closed:
                self._file.close()


def _joinskew():
    """The process-global join-skew registry (lazy import: the plane
    must stay constructible before any join module is loaded)."""
    from .joinskew import joinskew

    return joinskew


# -- the bundle ------------------------------------------------------------


class TelemetryPlane:
    """The always-on telemetry bundle one :class:`LookupServer` owns.

    Construction is cheap (no threads, no sockets, no files): the
    registry, tail sampler, and sketches are in-memory; exposition
    transports (:meth:`serve_http`, :meth:`start_pump`) are explicit
    opt-ins.  The flight recorder defaults to the PROCESS-GLOBAL ring
    (:data:`csvplus_tpu.obs.flight.recorder`) so storage seal/compact
    events and armed fault firings interleave with serve cycle
    summaries in one post-mortem timeline.
    """

    def __init__(
        self,
        *,
        registry: Optional[MetricRegistry] = None,
        flight_recorder: Optional[_flight.FlightRecorder] = None,
        sketch_k: int = 32,
        tail: Optional[TailSampler] = None,
    ):
        self.registry = registry if registry is not None else MetricRegistry()
        self.flight = (
            flight_recorder if flight_recorder is not None
            else _flight.recorder
        )
        self.tail = tail if tail is not None else TailSampler()
        self.sketch_k = int(sketch_k)
        self._lock = threading.Lock()
        self._probe_sketches: Dict[str, SpaceSaving] = {}
        self._build_sketches: Dict[str, SpaceSaving] = {}
        self._pump: Optional[MetricsPump] = None
        self._http: Optional[PromHttpEndpoint] = None
        self.cycles = self.registry.counter(
            "csvplus_serve_cycles_total", "dispatch cycles executed"
        )
        self.cycle_seconds = self.registry.histogram(
            "csvplus_serve_cycle_seconds", "dispatch cycle wall time",
            start=1e-4, factor=2.0, count=16,
        )
        self.rss_gauge = self.registry.gauge(
            "csvplus_process_rss_mb",
            "resident set size (MiB), sampled by the metrics pump",
        )
        self.registry.register_collector(process_samples, "process")
        self.registry.register_collector(self.tail.samples, "tail")
        self.registry.register_collector(self._sketch_samples, "skew")
        self.registry.register_collector(self._flight_samples, "flight")
        self.registry.register_collector(self._join_samples, "join")
        # sketches ride every flight dump, so `obs skew <dump>` answers
        # "what was hot when it died" without a scraper
        self.flight.attach("skew", self.skew_snapshot)

    # -- sketches ----------------------------------------------------------

    def probe_sketch(self, index_name: str) -> SpaceSaving:
        with self._lock:
            sk = self._probe_sketches.get(index_name)
            if sk is None:
                sk = self._probe_sketches[index_name] = SpaceSaving(
                    self.sketch_k
                )
            return sk

    def build_sketch(self, index_name: str) -> SpaceSaving:
        with self._lock:
            sk = self._build_sketches.get(index_name)
            if sk is None:
                sk = self._build_sketches[index_name] = SpaceSaving(
                    self.sketch_k
                )
            return sk

    def offer_probes(self, index_name: str, probes: Sequence[object]) -> None:
        """One coalesced sub-batch's probe keys into that index's
        sketch — one lock round.  Composite probes arrive as lists or
        tuples (lists normalized so every key hashes); single-column
        probes unwrap to their scalar so the skew surface reads
        ``c5``, not ``('c5',)``."""
        self.probe_sketch(index_name).offer_many([
            (p[0] if len(p) == 1 else tuple(p))
            if isinstance(p, (list, tuple)) else p
            for p in probes
        ])

    def skew_snapshot(self, n: Optional[int] = None) -> Dict[str, object]:
        with self._lock:
            probe = dict(self._probe_sketches)
            build = dict(self._build_sketches)
        for name, sk in _joinskew().build_sketches().items():
            build.setdefault(name, sk)
        return {
            "probe": {name: sk.snapshot(n) for name, sk in probe.items()},
            "build": {name: sk.snapshot(n) for name, sk in build.items()},
        }

    def _sketch_samples(self) -> List[Sample]:
        out: List[Sample] = []
        with self._lock:
            probe = list(self._probe_sketches.items())
            build = dict(self._build_sketches)
        # the partitioned join's build-side samples live in the
        # process-global registry (joins run on pipelines that never
        # attach a plane); merge them into the build side, plane-local
        # sketches winning a label collision
        for name, sk in _joinskew().build_sketches().items():
            build.setdefault(name, sk)
        sides = (("probe", probe), ("build", sorted(build.items())))
        for side, sketches in sides:
            for name, sk in sketches:
                out.append(
                    Sample("csvplus_skew_observed_total", "counter",
                           (("index", name), ("side", side)), sk.observed)
                )
                for rank, (key, count, _err) in enumerate(sk.topk(10)):
                    out.append(
                        Sample(
                            "csvplus_skew_topk", "gauge",
                            (("index", name), ("key", str(key)),
                             ("rank", str(rank)), ("side", side)),
                            count,
                        )
                    )
        return out

    def _join_samples(self) -> List[Sample]:
        """The partitioned join's skew-routing split as counter
        families — how many heavy keys each index's planner detected
        and how the probe rows divided between the replicated broadcast
        tier and the hash-repartition exchange — plus the single-pass
        multiway join's engagement counters (``csvplus_join_multiway_*``:
        executions, fact rows in/out, and the cascade intermediate rows
        the fusion avoided) and the fused probe pass's
        (``csvplus_plan_fusion_*``, ISSUE 19).  Reads the
        process-global registry, so
        pipeline joins that never touch a server still show up on the
        scrape.  A label may carry either counter family or both
        (routing counters land per partitioned probe, multiway counters
        per fused execution), so each family reads with absent-key
        defaults."""
        out: List[Sample] = []
        for label, c in sorted(_joinskew().counters_snapshot().items()):
            tags = (("index", label),)
            if "hot_keys_detected" in c:
                out.append(
                    Sample("csvplus_join_hot_keys_detected_total", "counter",
                           tags, c["hot_keys_detected"])
                )
                out.append(
                    Sample("csvplus_join_rows_broadcast_total", "counter",
                           tags, c["rows_broadcast"])
                )
                out.append(
                    Sample("csvplus_join_rows_repartitioned_total", "counter",
                           tags, c["rows_repartitioned"])
                )
            if "multiway_joins" in c:
                out.append(
                    Sample("csvplus_join_multiway_total", "counter",
                           tags, c["multiway_joins"])
                )
                out.append(
                    Sample("csvplus_join_multiway_rows_in_total", "counter",
                           tags, c.get("multiway_rows_in", 0))
                )
                out.append(
                    Sample("csvplus_join_multiway_rows_out_total", "counter",
                           tags, c.get("multiway_rows_out", 0))
                )
                out.append(
                    Sample(
                        "csvplus_join_multiway_intermediate_rows_avoided_total",
                        "counter", tags,
                        c.get("multiway_intermediate_rows_avoided", 0),
                    )
                )
            if "fused_probes" in c:
                # the fused probe pass's engagement evidence (ISSUE 19):
                # executions, fact rows entering vs surviving the
                # absorbed filters (the rows the fan-out never saw), and
                # rows emitted
                out.append(
                    Sample("csvplus_plan_fusion_total", "counter",
                           tags, c["fused_probes"])
                )
                out.append(
                    Sample("csvplus_plan_fusion_rows_full_total", "counter",
                           tags, c.get("fused_rows_full", 0))
                )
                out.append(
                    Sample("csvplus_plan_fusion_rows_selected_total",
                           "counter", tags, c.get("fused_rows_selected", 0))
                )
                out.append(
                    Sample("csvplus_plan_fusion_rows_out_total", "counter",
                           tags, c.get("fused_rows_out", 0))
                )
        return out

    def _flight_samples(self) -> List[Sample]:
        snap = self.flight.snapshot()
        return [
            Sample("csvplus_flight_events", "gauge", (), snap["events"]),
            Sample("csvplus_flight_dumps_total", "counter", (),
                   snap["dumps"]),
        ]

    # -- serve wiring ------------------------------------------------------

    def attach_server(self, server) -> None:
        """Wire one server's surfaces into the scrape plane: its
        metrics snapshot (serve counters, per-index WAL cells, per-view
        cells, plan cache) plus per-index read-amp trackers as a
        collector; its snapshot as flight-dump context alongside the
        registry's own metric deltas; and a build-key sketch onto every
        registered mutable index (fed at delta-seal)."""

        def _readamp() -> Dict[str, Dict[str, object]]:
            out: Dict[str, Dict[str, object]] = {}
            for name, impl in server.registered().items():
                ra = getattr(impl, "readamp", None)
                if ra is not None:
                    out[name] = ra.snapshot()
            return out

        self.registry.register_collector(
            lambda: serve_samples(server.snapshot(), _readamp()), "serve"
        )
        self.flight.attach("metrics", self.registry.sample_dict)
        self.flight.attach("serve", server.snapshot)
        self.flight.attach("tail", self.tail.snapshot)
        for name, impl in server.registered().items():
            if hasattr(impl, "key_sketch"):
                impl.key_sketch = self.build_sketch(name)

    def on_cycle(self, batch_n: int, seconds: float,
                 samples: Sequence[tuple]) -> None:
        """One dispatch cycle lands here once, after completion: a
        constant number of lock rounds regardless of batch size (cycle
        counter, cycle histogram, one tail-sampler round, one flight
        note)."""
        self.cycles.inc()
        self.cycle_seconds.observe(seconds)
        self.tail.offer_batch(samples)
        ok = failed = expired = 0
        for s in samples:
            o = s[2]
            if o == "ok":
                ok += 1
            elif o == "expired":
                expired += 1
            else:
                failed += 1
        self.flight.note(
            "serve:cycle", batch=batch_n, seconds=round(seconds, 6),
            ok=ok, failed=failed, expired=expired,
        )

    def flight_dump(
        self, reason: str, error: Optional[BaseException] = None
    ) -> Optional[str]:
        """Dump the flight ring; NEVER raises (a post-mortem writer
        must not add a second failure mode to a crash path).  Returns
        the artifact path, or None if the dump itself failed."""
        try:
            return self.flight.dump(reason, error)
        except Exception as err:
            sys.stderr.write(
                f"csvplus-flight: dump failed "
                f"({type(err).__name__}: {err})\n"
            )
            return None

    # -- transports --------------------------------------------------------

    def serve_http(self, *, addr: str = "127.0.0.1", port: int = 0) -> int:
        """Start the optional scrape endpoint; returns the bound port."""
        with self._lock:
            if self._http is None:
                self._http = PromHttpEndpoint(
                    self.registry, addr=addr, port=port
                )
                return self._http.start()
            return self._http.port

    def start_pump(
        self, log_dir: str, *, interval_s: float = 1.0
    ) -> MetricsPump:
        """Start (or return) the periodic JSONL pump for *log_dir*."""

        def _sample_rss() -> None:
            self.rss_gauge.set(rss_mb())

        with self._lock:
            if self._pump is None:
                self._pump = MetricsPump(
                    self.registry, log_dir,
                    interval_s=interval_s, on_tick=_sample_rss,
                ).start()
            return self._pump

    def close(self) -> None:
        with self._lock:
            pump, self._pump = self._pump, None
            http, self._http = self._http, None
        if pump is not None:
            pump.stop()
        if http is not None:
            http.stop()
