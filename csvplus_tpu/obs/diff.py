"""Stage-table regression differ: ``python -m csvplus_tpu.obs diff``.

Productizes the r05 -> r06 diagnosis workflow: the warm-join regression
was found by comparing two runs' per-stage tables by hand and noticing
``join:translate`` / ``join:pack`` had grown from noise to dominant.
This module does that comparison mechanically over two bench artifacts:

* a stage's **time share** (its seconds over the table's total) and its
  **per-row time** (seconds over rows) are both computed per side — the
  per-row metric makes tables from different row tiers comparable (the
  r05 table is a 10M-row run, the r06 record a 100M-row run);
* a stage is **flagged** when either metric moved by more than
  ``--threshold`` (default 2x) in either direction AND the stage is big
  enough to matter on at least one side (``--min-share``, default 0.5%
  of total time) — tiny stages jitter, and a 3x move on 0.1% of the
  run is not a diagnosis;
* stages present on only one side are reported separately (a renamed or
  newly-instrumented stage is signal too, just different signal);
* when both sides carry an ``rss_peak_mb`` extra for a stage (the
  :func:`csvplus_tpu.obs.memory.watch_memory` column), its ratio is
  diffed under the same threshold.

Accepted inputs: any JSON file whose top level is a stage list, or an
artifact dict carrying one under ``stage_table`` / ``stage_table_auto``
/ ``stage_table_serial`` / ``stages`` (first match; override with
``--key``).  Each stage row needs ``stage`` and ``seconds``; ``rows_in``
/ ``rows_out`` enable the per-row metric.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Artifact keys probed, in order, for the embedded stage table.
STAGE_TABLE_KEYS = (
    "stage_table",
    "stage_table_auto",
    "stage_table_serial",
    "stages",
)

DEFAULT_THRESHOLD = 2.0
DEFAULT_MIN_SHARE = 0.005


def load_stage_table(
    path: str, key: Optional[str] = None
) -> List[Dict[str, Any]]:
    """The stage list embedded in *path* (see the module docstring for
    the accepted shapes).  Raises ``ValueError`` with the keys that
    were probed when the artifact carries no stage table."""
    with open(path) as f:
        obj = json.load(f)
    if isinstance(obj, list):
        table = obj
    elif isinstance(obj, dict):
        keys = (key,) if key else STAGE_TABLE_KEYS
        table = next((obj[k] for k in keys if obj.get(k)), None)
        if table is None:
            raise ValueError(
                f"{path}: no stage table under {', '.join(k for k in keys if k)}"
                " — pass --key for a nonstandard artifact"
            )
    else:
        raise ValueError(f"{path}: top level is {type(obj).__name__}")
    out = []
    for row in table:
        if not isinstance(row, dict) or "stage" not in row or "seconds" not in row:
            raise ValueError(f"{path}: stage row missing stage/seconds: {row!r}")
        out.append(row)
    return out


def _stage_facts(table: Sequence[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    total = sum(float(r["seconds"]) for r in table) or 1.0
    facts: Dict[str, Dict[str, float]] = {}
    for r in table:
        sec = float(r["seconds"])
        rows = max(int(r.get("rows_in", 0)), int(r.get("rows_out", 0)))
        facts[str(r["stage"])] = {
            "seconds": sec,
            "share": sec / total,
            "ns_per_row": (sec / rows * 1e9) if rows > 0 else None,
            "rss_peak_mb": r.get("rss_peak_mb"),
        }
    return facts


def _ratio(a: Optional[float], b: Optional[float]) -> Optional[float]:
    if a is None or b is None or a <= 0 or b <= 0:
        return None
    return a / b


def diff_stage_tables(
    table_a: Sequence[Dict[str, Any]],
    table_b: Sequence[Dict[str, Any]],
    *,
    threshold: float = DEFAULT_THRESHOLD,
    min_share: float = DEFAULT_MIN_SHARE,
) -> Dict[str, Any]:
    """Compare two stage tables; see the module docstring for the
    flagging rule.  Returns a JSON-safe dict with per-stage ``rows``,
    the ``flagged`` stages (worst movement first, each tagged with the
    side it regressed in), and the one-sided stage lists."""
    fa, fb = _stage_facts(table_a), _stage_facts(table_b)
    rows: List[Dict[str, Any]] = []
    flagged: List[Dict[str, Any]] = []
    for stage in [s for s in fa if s in fb]:
        a, b = fa[stage], fb[stage]
        share_ratio = _ratio(a["share"], b["share"])
        row_ratio = _ratio(a["ns_per_row"], b["ns_per_row"])
        rss_ratio = _ratio(a["rss_peak_mb"], b["rss_peak_mb"])
        # movement = the larger departure from 1.0 among the metrics,
        # measured symmetrically (2.0 and 0.5 are the same movement)
        movement = max(
            (max(r, 1.0 / r) for r in (share_ratio, row_ratio, rss_ratio) if r),
            default=1.0,
        )
        big_enough = max(a["share"], b["share"]) >= min_share
        flag = big_enough and movement >= threshold
        # the side whose cost is HIGHER is the regressed side; per-row
        # time decides when available (scale-invariant), share otherwise
        decider = row_ratio if row_ratio is not None else share_ratio
        regressed_in = None
        if flag and decider is not None:
            regressed_in = "A" if decider > 1.0 else "B"
        row = {
            "stage": stage,
            "share_a": round(a["share"], 4),
            "share_b": round(b["share"], 4),
            "ns_per_row_a": _rnd(a["ns_per_row"]),
            "ns_per_row_b": _rnd(b["ns_per_row"]),
            "movement": round(movement, 2),
            "flagged": flag,
            "regressed_in": regressed_in,
        }
        if rss_ratio is not None:
            row["rss_peak_mb_a"] = a["rss_peak_mb"]
            row["rss_peak_mb_b"] = b["rss_peak_mb"]
        rows.append(row)
        if flag:
            flagged.append(row)
    flagged.sort(key=lambda r: -r["movement"])
    return {
        "threshold": threshold,
        "min_share": min_share,
        "rows": rows,
        "flagged": flagged,
        "only_in_a": [s for s in fa if s not in fb],
        "only_in_b": [s for s in fb if s not in fa],
    }


def _rnd(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)


def format_diff(result: Dict[str, Any], label_a: str, label_b: str) -> str:
    """Human-readable report (the CLI's default output)."""
    lines = [
        f"stage-table diff: A={label_a}  B={label_b}",
        f"threshold {result['threshold']}x, min share"
        f" {result['min_share'] * 100:.1f}%",
        "",
        f"{'stage':<24} {'share A':>8} {'share B':>8} {'ns/row A':>10}"
        f" {'ns/row B':>10} {'move':>6}  flag",
    ]
    for r in result["rows"]:
        nra = "-" if r["ns_per_row_a"] is None else f"{r['ns_per_row_a']:.2f}"
        nrb = "-" if r["ns_per_row_b"] is None else f"{r['ns_per_row_b']:.2f}"
        mark = f"REGRESSED in {r['regressed_in']}" if r["flagged"] else ""
        lines.append(
            f"{r['stage']:<24} {r['share_a'] * 100:>7.2f}%"
            f" {r['share_b'] * 100:>7.2f}% {nra:>10} {nrb:>10}"
            f" {r['movement']:>5.2f}x  {mark}"
        )
    for side, stages in (("A", result["only_in_a"]), ("B", result["only_in_b"])):
        if stages:
            lines.append(f"only in {side}: {', '.join(stages)}")
    if result["flagged"]:
        worst = ", ".join(
            f"{r['stage']} ({r['movement']:.1f}x in {r['regressed_in']})"
            for r in result["flagged"]
        )
        lines.append(f"flagged: {worst}")
    else:
        lines.append("flagged: none")
    return "\n".join(lines)


def diff_files(
    path_a: str,
    path_b: str,
    *,
    threshold: float = DEFAULT_THRESHOLD,
    min_share: float = DEFAULT_MIN_SHARE,
    key: Optional[str] = None,
) -> Dict[str, Any]:
    """Load both artifacts and diff their stage tables."""
    return diff_stage_tables(
        load_stage_table(path_a, key),
        load_stage_table(path_b, key),
        threshold=threshold,
        min_share=min_share,
    )


# -- bench-record mode (ISSUE 13 satellite) --------------------------------
#
# The mesh artifact is the only family carrying a stage table; the
# wal/delta/serve/view bench records are nested dicts of scalar
# measurements (rows_per_sec, p99_ms, fsyncs...).  ``diff_bench_records``
# mechanizes regression triage for THOSE: flatten both records to dotted
# numeric leaves, ratio every shared leaf, flag symmetric movement
# beyond the threshold.  Direction is reported, not judged — whether
# "higher" is a regression depends on the metric (rows/s vs p99_ms), so
# each flagged row says which side is higher and the reader applies the
# sign.

#: Flattened-path substrings excluded from the bench diff: host-shape
#: facts and identifiers, not measurements.
BENCH_DIFF_SKIP = (
    "host_cpus",
    "jax_device_count",
    "schema_version",
)

DEFAULT_BENCH_THRESHOLD = 1.5


def flatten_numeric(obj: Any, prefix: str = "") -> Dict[str, float]:
    """Dotted-path -> value map of every numeric leaf (bools excluded;
    list elements indexed)."""
    out: Dict[str, float] = {}
    if isinstance(obj, bool):
        return out
    if isinstance(obj, (int, float)):
        out[prefix or "value"] = float(obj)
        return out
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            out.update(flatten_numeric(v, p))
    elif isinstance(obj, list):
        for i, v in enumerate(obj):
            out.update(flatten_numeric(v, f"{prefix}[{i}]"))
    return out


def diff_bench_records(
    rec_a: Dict[str, Any],
    rec_b: Dict[str, Any],
    *,
    threshold: float = DEFAULT_BENCH_THRESHOLD,
) -> Dict[str, Any]:
    """Compare two same-family bench records leaf by leaf.  Returns a
    JSON-safe dict: per-metric ``rows`` (a, b, ratio b/a, symmetric
    movement, flagged, higher side), ``flagged`` sorted worst first,
    one-sided metric lists, and a family note when the records' top
    ``metric`` keys disagree."""
    fam_a, fam_b = rec_a.get("metric"), rec_b.get("metric")
    fa = {
        k: v for k, v in flatten_numeric(rec_a).items()
        if not any(s in k for s in BENCH_DIFF_SKIP)
    }
    fb = {
        k: v for k, v in flatten_numeric(rec_b).items()
        if not any(s in k for s in BENCH_DIFF_SKIP)
    }
    rows: List[Dict[str, Any]] = []
    flagged: List[Dict[str, Any]] = []
    for metric in [k for k in fa if k in fb]:
        a, b = fa[metric], fb[metric]
        ratio = _ratio(b, a)  # b over a: >1 = grew in B
        movement = max(ratio, 1.0 / ratio) if ratio else 1.0
        flag = ratio is not None and movement >= threshold
        row = {
            "metric": metric,
            "a": a,
            "b": b,
            "ratio": None if ratio is None else round(ratio, 4),
            "movement": round(movement, 2),
            "flagged": flag,
            "higher_in": (
                None if ratio is None or ratio == 1.0
                else ("B" if ratio > 1.0 else "A")
            ),
        }
        rows.append(row)
        if flag:
            flagged.append(row)
    flagged.sort(key=lambda r: -r["movement"])
    return {
        "mode": "bench",
        "family_a": fam_a,
        "family_b": fam_b,
        "family_match": (fam_a == fam_b) if (fam_a and fam_b) else None,
        "threshold": threshold,
        "rows": rows,
        "flagged": flagged,
        "only_in_a": [k for k in fa if k not in fb],
        "only_in_b": [k for k in fb if k not in fa],
    }


def format_bench_diff(
    result: Dict[str, Any], label_a: str, label_b: str
) -> str:
    """Human-readable bench-record report (flagged rows only, plus
    one-sided metrics — a full leaf table would be hundreds of lines)."""
    lines = [
        f"bench diff: A={label_a}  B={label_b}",
        f"family A={result['family_a']!r} B={result['family_b']!r}"
        + ("" if result["family_match"] in (True, None)
           else "  (FAMILY MISMATCH)"),
        f"threshold {result['threshold']}x over"
        f" {len(result['rows'])} shared metrics",
    ]
    if result["flagged"]:
        lines.append("")
        lines.append(
            f"{'metric':<48} {'A':>12} {'B':>12} {'move':>6}  higher"
        )
        for r in result["flagged"]:
            lines.append(
                f"{r['metric']:<48} {r['a']:>12.4g} {r['b']:>12.4g}"
                f" {r['movement']:>5.2f}x  {r['higher_in']}"
            )
    else:
        lines.append("flagged: none")
    for side in ("a", "b"):
        only = result[f"only_in_{side}"]
        if only:
            shown = ", ".join(only[:8]) + (" ..." if len(only) > 8 else "")
            lines.append(f"only in {side.upper()}: {shown}")
    return "\n".join(lines)


def diff_bench_files(
    path_a: str,
    path_b: str,
    *,
    threshold: float = DEFAULT_BENCH_THRESHOLD,
) -> Dict[str, Any]:
    """Load two bench artifacts and diff their numeric leaves."""
    with open(path_a) as f:
        rec_a = json.load(f)
    with open(path_b) as f:
        rec_b = json.load(f)
    if not isinstance(rec_a, dict) or not isinstance(rec_b, dict):
        raise ValueError("bench diff needs dict-shaped artifacts")
    return diff_bench_records(rec_a, rec_b, threshold=threshold)
