"""Compile/recompile accounting for the module-level jitted kernels.

The r06 diagnosis and the serving tier both converged on the same
invariant: a warm pass over an already-seen shape must lower NOTHING.
``PlanCache`` asserts it for plan shapes via its ``lowered`` counter;
this module extends it to every module-level kernel — the exact
functions whose eager predecessors caused the r05 warm-join regression.

Kernels self-register at definition site::

    @register_kernel("join.pack_qk")
    @jax.jit
    def _pack_qk_kernel(...): ...

and :func:`compile_counts` reads each registered function's jit-cache
entry count (``PjitFunction._cache_size`` — the number of distinct
lowerings jax holds for it).  A grown count between two snapshots IS a
(re)compile; :class:`RecompileWatch` packages the
snapshot/delta/assert-zero workflow the benches and tests use::

    with RecompileWatch() as w:
        ...warm passes...
    w.assert_zero()        # raises listing every kernel that lowered

``_cache_size`` is jax-private; :func:`compile_counts` degrades to
``None`` per kernel when the running jax build lacks it, and
:class:`RecompileWatch` then treats that kernel as unobservable rather
than failing the run (record-or-postmortem, not a hard dependency on a
private API).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional

_REGISTRY_LOCK = threading.Lock()
_KERNELS: Dict[str, Any] = {}


def register_kernel(name: str) -> Callable:
    """Decorator: register a jitted callable under *name* for
    compile-count accounting.  Returns the callable unchanged — zero
    call-path overhead."""

    def deco(fn):
        with _REGISTRY_LOCK:
            _KERNELS[name] = fn
        return fn

    return deco


def registered_kernels() -> Dict[str, Any]:
    """Name -> jitted callable snapshot of the registry."""
    with _REGISTRY_LOCK:
        return dict(_KERNELS)


def _cache_size(fn: Any) -> Optional[int]:
    try:
        return int(fn._cache_size())
    except Exception:
        return None


def compile_counts() -> Dict[str, Optional[int]]:
    """Per-kernel count of distinct lowerings jax currently caches
    (``None`` when the kernel's count is unobservable on this jax)."""
    return {name: _cache_size(fn) for name, fn in registered_kernels().items()}


class RecompileWatch:
    """Asserts the zero-warm-recompiles invariant over a region.

    Snapshot on ``__enter__``; :meth:`delta` reports every kernel whose
    lowering count grew (plus the plan cache's ``lowered`` counter when
    one was passed); :meth:`assert_zero` raises ``AssertionError``
    naming the offenders.  Kernels registered *inside* the region count
    from zero — a brand-new kernel compiling in a warm region is a
    recompile by definition.
    """

    def __init__(self, plancache=None):
        self._plancache = plancache
        self._before: Dict[str, Optional[int]] = {}
        self._plan_before = 0

    def __enter__(self) -> "RecompileWatch":
        self._before = compile_counts()
        if self._plancache is not None:
            self._plan_before = self._plancache.stats()["lowered"]
        return self

    def __exit__(self, *exc) -> None:
        pass

    def delta(self) -> Dict[str, int]:
        """Kernels (and ``plancache``) whose lowering count grew since
        ``__enter__``; empty dict == the invariant held."""
        out: Dict[str, int] = {}
        after = compile_counts()
        for name, n in after.items():
            if n is None:
                continue
            base = self._before.get(name)
            if base is None:
                base = 0 if name not in self._before else n
            if n > base:
                out[name] = n - base
        if self._plancache is not None:
            grew = self._plancache.stats()["lowered"] - self._plan_before
            if grew > 0:
                out["plancache"] = grew
        return out

    def observable(self) -> bool:
        """False when no registered kernel exposes a cache size (the
        invariant cannot be checked on this jax build)."""
        return any(v is not None for v in compile_counts().values())

    def assert_zero(self, context: str = "warm pass") -> None:
        d = self.delta()
        if d:
            detail = ", ".join(f"{k}:+{v}" for k, v in sorted(d.items()))
            raise AssertionError(
                f"recompiles during {context}: {detail} — the zero-warm-"
                "recompiles invariant is broken (r06 regression shape)"
            )
