"""Process-global skew-routing evidence from the partitioned join (ISSUE 15).

The sharded join's planner detects probe-side heavy hitters per
execution (parallel/pjoin.py ``_detect_hot``) and routes them through
the replicated broadcast tier while the tail rides the hash-repartition
exchange.  That routing decision is exactly the evidence an operator
needs at scrape time: which indexes saw hot keys, how many rows
bypassed the exchange, and what the build-side key distribution looked
like when the plan was made.  This module is the registry those
counters land in — one lock round per join — and ``TelemetryPlane``
exports it inside the same constant-lock-round metrics cycle as every
other collector (the ``csvplus_join_*`` counter families plus the
build side of ``csvplus_skew_*``).

It is process-global rather than plane-local because joins run on
pipelines that never attach a serving plane; a plane merely *reads*
this registry when it samples.

Thread model: a monitor.  ``on_join`` / ``offer_build`` are worker
entry points (the partitioned probe executes on ingest workers, the
serve dispatcher, and caller threads alike); every registry mutation
sits under the registry lock, and sketch ingestion goes through the
sketch's own lock (``SpaceSaving.offer_counts``).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Hashable

from .sketch import SpaceSaving

__all__ = ["JoinSkewStats", "joinskew"]


class JoinSkewStats:
    """Per-index-label join routing counters + build-side key sketches."""

    def __init__(self, sketch_k: int = 32):
        self.sketch_k = int(sketch_k)
        self._lock = threading.Lock()
        self._counters: Dict[str, Dict[str, int]] = {}
        self._build_sketches: Dict[str, SpaceSaving] = {}

    # -- ingest ------------------------------------------------------------

    def on_join(
        self,
        label: str,
        hot_keys: int,
        rows_broadcast: int,
        rows_repartitioned: int,
    ) -> None:
        """Fold one partitioned-probe execution's routing split into the
        label's counters — one lock round per join."""
        with self._lock:
            c = self._counters.get(label)
            if c is None:
                c = self._counters[label] = {}
            # absent-key defaults: a label may already exist with only
            # the multiway family (on_multiway) — the two families share
            # the label map but keep disjoint keys
            c["joins"] = c.get("joins", 0) + 1
            c["hot_keys_detected"] = c.get("hot_keys_detected", 0) + int(hot_keys)
            c["rows_broadcast"] = c.get("rows_broadcast", 0) + int(rows_broadcast)
            c["rows_repartitioned"] = (
                c.get("rows_repartitioned", 0) + int(rows_repartitioned)
            )

    def on_multiway(
        self,
        label: str,
        dims: int,
        rows_in: int,
        rows_out: int,
        intermediate_rows_avoided: int,
    ) -> None:
        """Fold one single-pass multiway join execution (ISSUE 17) into
        the label's counters — the ``csvplus_join_multiway_*`` evidence
        that the fused operator engaged and how large the cascade
        intermediate it killed would have been.  One lock round per
        join, same discipline as :meth:`on_join`.  Multiway labels get
        their OWN counter dict (keys are disjoint from the routing
        counters; the exporter reads both families with absent-key
        defaults)."""
        with self._lock:
            c = self._counters.get(label)
            if c is None:
                c = self._counters[label] = {}
            c["multiway_joins"] = c.get("multiway_joins", 0) + 1
            c["multiway_dims"] = c.get("multiway_dims", 0) + int(dims)
            c["multiway_rows_in"] = c.get("multiway_rows_in", 0) + int(rows_in)
            c["multiway_rows_out"] = (
                c.get("multiway_rows_out", 0) + int(rows_out)
            )
            c["multiway_intermediate_rows_avoided"] = (
                c.get("multiway_intermediate_rows_avoided", 0)
                + int(intermediate_rows_avoided)
            )

    def on_fused(
        self,
        label: str,
        dims: int,
        rows_full: int,
        rows_selected: int,
        rows_out: int,
    ) -> None:
        """Fold one fused probe-pass execution (ISSUE 19) into the
        label's counters — the ``csvplus_plan_fusion_*`` evidence that a
        FusedProbe engaged, how many fact rows the absorbed filters cut
        before the fan-out (*rows_full* entering vs *rows_selected*
        probed), and how many rows it emitted.  One lock round, keys
        disjoint from the other families."""
        with self._lock:
            c = self._counters.get(label)
            if c is None:
                c = self._counters[label] = {}
            c["fused_probes"] = c.get("fused_probes", 0) + 1
            c["fused_dims"] = c.get("fused_dims", 0) + int(dims)
            c["fused_rows_full"] = c.get("fused_rows_full", 0) + int(rows_full)
            c["fused_rows_selected"] = (
                c.get("fused_rows_selected", 0) + int(rows_selected)
            )
            c["fused_rows_out"] = c.get("fused_rows_out", 0) + int(rows_out)

    def build_sketch(self, label: str) -> SpaceSaving:
        """Get-or-create the label's build-side sketch."""
        with self._lock:
            sk = self._build_sketches.get(label)
            if sk is None:
                sk = self._build_sketches[label] = SpaceSaving(self.sketch_k)
            return sk

    def offer_build(
        self, label: str, keys: Iterable[Hashable], counts: Iterable[int]
    ) -> None:
        """A build-side key sample (decoded values + sample counts) into
        the label's sketch.  Aggregation already happened at sampling
        time (``np.unique``), so this is one sketch lock round."""
        self.build_sketch(label).offer_counts(keys, counts)

    # -- export ------------------------------------------------------------

    def counters_snapshot(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {label: dict(c) for label, c in self._counters.items()}

    def build_sketches(self) -> Dict[str, SpaceSaving]:
        """A point-in-time copy of the label->sketch map (the sketches
        themselves are shared monitors, safe to snapshot() concurrently)."""
        with self._lock:
            return dict(self._build_sketches)

    def reset(self) -> None:
        """Tests only: drop all counters and sketches."""
        with self._lock:
            self._counters.clear()
            self._build_sketches.clear()


joinskew = JoinSkewStats()
