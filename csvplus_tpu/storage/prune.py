"""Per-tier key fences + fingerprint filters: host-side LSM read pruning.

The read-amplification cliff (BENCH_WAL_r11): every point probe against
a :class:`~csvplus_tpu.storage.lsm.MutableIndex` paid one
``bounds_many`` pass PER TIER, so lookups collapsed ~47x once a write
burst left 139 live deltas behind.  Classic LSM read-path design
(per-run fences + Bloom filters, as in the Monkey/Dostoevsky line of
work) fixes this: at delta-seal time the encode path already holds the
packed keys, so we pay a few bits per key once and afterwards every
probe consults host-side summaries to shortlist the 1-3 tiers that can
actually contain the key before any per-tier bounds pass runs.

Two summaries per sealed tier (:class:`TierPruner`):

* **fences** — the tier's min and max full key tuple (rows are sorted,
  so these are row 0 and row n-1).  Exact for every probe width: a
  prefix probe ``p`` can match only when ``lo[:k] <= p <= hi[:k]``.
* **filter** — a seeded deterministic Bloom filter over the full-width
  keys (``CSVPLUS_LSM_FILTER_BITS`` bits/key, default 10, ``0`` means
  fences only).  Double hashing ``g_i = h1 + i*h2 (mod m)`` from one
  64-bit FNV-1a fold of per-column ``crc32`` values — the same
  arithmetic scalar (probe) and vectorized (build) side, so a present
  key can NEVER be filtered out.  Filters answer full-width probes
  only; prefix probes rely on fences.

Parity is structural, not statistical: both summaries are one-sided.  A
fence or filter rejection proves the tier holds no match, so pruning a
tier is observationally identical to probing it and reading back the
empty bounds ``(0, 0)`` — false positives cost one redundant bounds
pass and nothing else.  Everything here is plain host numpy (the DPG
cache-conscious-index lesson, arxiv cs/0308004): no jitted kernels, so
pruning can never recompile and never perturbs device state.

:class:`PruneDirectory` aggregates one TierSet's pruners into
concatenated numpy arrays so a probe batch tests EVERY tier's filter in
one vectorized pass instead of a Python loop over 139 tiers.

Sidecars: :func:`write_pruner` / :func:`load_pruner` persist the
summaries next to a checkpointed base (``prune-%08d.flt``, named in the
manifest) with the storage durability idiom — write tmp, fsync,
``os.replace``, directory fsync — so :meth:`MutableIndex.open` reloads
them without a rebuild scan.  A missing or corrupt sidecar degrades to
an in-memory rebuild, never to wrong answers.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.env import env_int, env_str

__all__ = [
    "DEFAULT_BITS_PER_KEY",
    "PruneDirectory",
    "TierPruner",
    "build_pruner",
    "filter_bits_per_key",
    "filter_seed",
    "load_pruner",
    "probe_hashes",
    "prune_enabled",
    "write_pruner",
]

_MASK64 = (1 << 64) - 1
_FNV_OFFSET = 0xCBF29CE484222325
_FNV_PRIME = 0x100000001B3
_U64 = np.uint64

DEFAULT_BITS_PER_KEY = 10
_MAX_HASHES = 6  # ln(2)*bits_per_key capped: k>6 buys <0.1% FPR
_SMALL_BATCH = 8  # below this, fence-first scalar checks beat the broadcast

_SIDECAR_MAGIC = "csvplus-tpu-prune"
_SIDECAR_VERSION = 1


def prune_enabled() -> bool:
    """``CSVPLUS_LSM_PRUNE`` — default on; ``0``/``off``/``false`` kills
    fence+filter pruning entirely (the bitwise-parity escape hatch the
    property tests diff against)."""
    return (env_str("CSVPLUS_LSM_PRUNE", "1") or "1").lower() not in (
        "0",
        "off",
        "false",
    )


def filter_bits_per_key() -> int:
    """``CSVPLUS_LSM_FILTER_BITS`` (default 10; 0 = fences only)."""
    return max(0, env_int("CSVPLUS_LSM_FILTER_BITS", DEFAULT_BITS_PER_KEY))


def filter_seed() -> int:
    """``CSVPLUS_LSM_FILTER_SEED`` — crc32 seed, fixed per process so
    every tier of one index hashes identically (the directory's
    vectorized pass requires a shared seed)."""
    return env_int("CSVPLUS_LSM_FILTER_SEED", 0x5EED) & 0xFFFFFFFF


def _n_hashes(bits_per_key: int) -> int:
    return max(1, min(_MAX_HASHES, int(round(bits_per_key * 0.6931))))


def _value_bytes(v) -> bytes:
    if isinstance(v, str):
        return v.encode("utf-8")
    if isinstance(v, bytes):
        return v
    return str(v).encode("utf-8")


def probe_hashes(values: Sequence, seed: int) -> Tuple[int, int]:
    """``(h1, h2)`` for one full-width key tuple.

    EXACTLY the arithmetic of the vectorized build path (FNV-1a fold
    over per-column ``crc32(utf8, seed)``, wrapped at 64 bits) — the
    no-false-negative guarantee rests on this equality, which
    tests/test_prune.py checks value-by-value.  Python-int arithmetic
    masked to 64 bits: identical mod 2**64 to numpy's silent uint64
    wraparound without the scalar overflow warnings."""
    h = _FNV_OFFSET
    for v in values:
        c = zlib.crc32(_value_bytes(v), seed) & 0xFFFFFFFF
        h = ((h ^ c) * _FNV_PRIME) & _MASK64
    return h & 0xFFFFFFFF, (h >> 32) | 1


def _probe_filterable(probe: Sequence) -> bool:
    # NUL bytes round-trip ambiguously through numpy 'S' dictionaries
    # (trailing-null truncation); skip the filter for such probes rather
    # than reason about encoder behavior.  Fences skip them too.
    for v in probe:
        if isinstance(v, str):
            if "\x00" in v:
                return False
        elif isinstance(v, bytes):
            if b"\x00" in v:
                return False
    return True


class TierPruner:
    """Fences + filter for ONE sorted tier.  Immutable after build."""

    __slots__ = (
        "nrows",
        "fence_lo",
        "fence_hi",
        "bits",
        "m",
        "k",
        "seed",
        "bits_per_key",
    )

    def __init__(
        self,
        nrows: int,
        fence_lo: Optional[Tuple],
        fence_hi: Optional[Tuple],
        bits: Optional[np.ndarray],
        m: int,
        k: int,
        seed: int,
        bits_per_key: int,
    ):
        self.nrows = nrows
        self.fence_lo = fence_lo  # full-width key tuples, or None
        self.fence_hi = fence_hi
        self.bits = bits  # packed uint8 bitset ((m+7)//8 bytes), or None
        self.m = m
        self.k = k
        self.seed = seed
        self.bits_per_key = bits_per_key

    def fence_excludes(self, probe: Sequence) -> bool:
        """True when the [min, max] key fence PROVES no row of this tier
        can match the (possibly prefix) probe.  Conservative: no fence,
        empty probe, or un-orderable values -> False (cannot prune)."""
        if self.nrows == 0:
            return True
        lo, hi = self.fence_lo, self.fence_hi
        if lo is None or not probe:
            return False
        k = len(probe)
        p = tuple(probe)
        if not _probe_filterable(p):
            return False
        try:
            return p < lo[:k] or p > hi[:k]
        except TypeError:
            return False  # mixed-type keys: no total order, never prune

    def filter_excludes(self, h1: int, h2: int) -> bool:
        """True when the Bloom filter proves the full-width key is
        absent.  Callers hash via :func:`probe_hashes` with this
        pruner's seed."""
        bits = self.bits
        if bits is None:
            return False
        m = self.m
        for i in range(self.k):
            pos = (h1 + i * h2) % m
            if not (bits[pos >> 3] >> (pos & 7)) & 1:
                return True
        return False

    def can_contain(self, probe: Sequence, width: int) -> bool:
        """Scalar reference predicate (the vectorized
        :meth:`PruneDirectory.pass_matrix` must agree with this — the
        property tests diff them)."""
        if self.nrows == 0:
            return False
        if self.fence_excludes(probe):
            return False
        if (
            len(probe) == width
            and self.bits is not None
            and _probe_filterable(probe)
        ):
            h1, h2 = probe_hashes(probe, self.seed)
            if self.filter_excludes(h1, h2):
                return False
        return True


# -- build ----------------------------------------------------------------


def _fence_of(impl, key_columns: Sequence[str]):
    """(lo, hi) full key tuples of a SORTED tier: rows 0 and n-1.

    Device-lazy tiers read the two fence keys from each key column's
    cached host dictionary + code mirror (two scalar lookups, zero
    device dispatch); columns without a host dictionary fall back to
    decoding exactly those two rows — never the whole table."""
    n = len(impl)
    if impl._rows is None and impl.dev is not None:
        table = impl.dev.table
        lo_vals: list = []
        hi_vals: list = []
        for c in key_columns:
            col = table.columns.get(c)
            d = getattr(col, "_dictionary", None) if col is not None else None
            if d is None or d.dtype.kind != "S":
                # lane-only or non-string dictionary: decode just the
                # two fence rows through the device path.
                sel = np.asarray([0, n - 1] if n > 1 else [0], dtype=np.int64)
                rows = table.to_rows(sel)
                first, last = rows[0], rows[-1]
                lo_vals = [first[k] for k in key_columns]
                hi_vals = [last[k] for k in key_columns]
                break
            # host mirror path: two scalar dictionary lookups, no
            # device dispatch and no full-row decode.
            codes = col.codes_host()
            lo_vals.append(d[int(codes[0])].decode("utf-8"))
            hi_vals.append(d[int(codes[n - 1])].decode("utf-8"))
        lo = tuple(lo_vals)
        hi = tuple(hi_vals)
    else:
        rows = impl.rows
        first, last = rows[0], rows[-1]
        lo = tuple(first[c] for c in key_columns)
        hi = tuple(last[c] for c in key_columns)
    if not (_probe_filterable(lo) and _probe_filterable(hi)):
        return None, None
    return lo, hi


def _row_hashes(impl, key_columns: Sequence[str], seed: int):
    """Per-row 64-bit key hashes, or None when hashing would force an
    unbounded host materialization (lane-only dictionaries).

    Device tiers hash each column's dictionary ONCE (it is tiny next to
    the row count) and gather by host-mirrored codes; host tiers fold
    row values directly.  Both paths produce bit-identical hashes to
    :func:`probe_hashes`."""
    n = len(impl)
    if impl._rows is None and impl.dev is not None:
        table = impl.dev.table
        h = np.full(n, _FNV_OFFSET, dtype=_U64)
        with np.errstate(over="ignore"):
            for c in key_columns:
                col = table.columns[c]
                if col._dictionary is None:
                    # lane-only column: .dictionary would unpack the
                    # whole dictionary to host — bounded-RSS contract
                    # says no.  Fence-only pruning for this tier.
                    return None
                d = col._dictionary
                if d.dtype.kind != "S":
                    return None
                entries = d.tolist()
                dh = np.asarray(
                    [zlib.crc32(e, seed) & 0xFFFFFFFF for e in entries]
                    or [0],
                    dtype=_U64,
                )
                codes = np.asarray(col.codes_host()[:n], dtype=np.int64)
                codes = np.clip(codes, 0, max(len(entries) - 1, 0))
                h = (h ^ dh[codes]) * _U64(_FNV_PRIME)
        return h
    rows = impl.rows
    out = np.empty(len(rows), dtype=_U64)
    for i, r in enumerate(rows):
        h = _FNV_OFFSET
        for c in key_columns:
            cc = zlib.crc32(_value_bytes(r[c]), seed) & 0xFFFFFFFF
            h = ((h ^ cc) * _FNV_PRIME) & _MASK64
        out[i] = h
    return out


def build_pruner(
    impl,
    key_columns: Sequence[str],
    *,
    bits_per_key: Optional[int] = None,
    seed: Optional[int] = None,
) -> TierPruner:
    """Build fences + filter for one sorted tier (an ``IndexImpl``).

    O(n) host work at seal time; the double-hash insert is a vectorized
    unpacked-bit scatter + ``np.packbits`` — no device round trips
    beyond the 2-row fence decode."""
    if bits_per_key is None:
        bits_per_key = filter_bits_per_key()
    if seed is None:
        seed = filter_seed()
    n = len(impl)
    if n == 0:
        return TierPruner(0, None, None, None, 0, 0, seed, bits_per_key)
    fence_lo, fence_hi = _fence_of(impl, key_columns)
    bits = None
    m = 0
    k = 0
    if bits_per_key > 0:
        h = _row_hashes(impl, key_columns, seed)
        if h is not None:
            k = _n_hashes(bits_per_key)
            m = max(8, n * bits_per_key)
            h1 = (h & _U64(0xFFFFFFFF)).astype(_U64)
            h2 = (h >> _U64(32)) | _U64(1)
            ks = np.arange(k, dtype=_U64)
            with np.errstate(over="ignore"):
                pos = (h1[:, None] + ks[None, :] * h2[:, None]) % _U64(m)
            # set bits via an unpacked byte-per-bit scatter + packbits:
            # fancy-index assignment is ~10x cheaper than the
            # np.bitwise_or.at ufunc scatter, and bitorder="little"
            # reproduces the (pos >> 3, 1 << (pos & 7)) layout exactly.
            nbytes = (m + 7) // 8
            unpacked = np.zeros(nbytes * 8, dtype=np.uint8)
            unpacked[pos.astype(np.int64).ravel()] = 1
            bits = np.packbits(unpacked, bitorder="little")
    return TierPruner(
        n, fence_lo, fence_hi, bits, m, k, seed, bits_per_key
    )


# -- per-TierSet aggregation ----------------------------------------------


class PruneDirectory:
    """One TierSet's pruners, aggregated for vectorized probing.

    Built LAZILY by ``TierSet.prune_directory()`` on the first probe
    after a swap (double-checked under the per-TierSet lock, with each
    delta's TierPruner cached on its DeltaTier so successor epochs
    reuse it) — the append path pays no per-seal scan, and every probe
    after the first touches only immutable state, the THREAD001 probe
    contract.  Filter bitsets concatenate into one uint8 array with
    per-tier bit offsets; a probe batch then answers every
    (probe, tier) filter test in one numpy broadcast.  Tiers without a
    filter contribute a 1-byte all-ones chunk (always pass), empty
    tiers a 1-byte all-zeros chunk (never pass — exact, they hold
    nothing)."""

    __slots__ = (
        "pruners",
        "n_tiers",
        "width",
        "k",
        "seed",
        "scalar_only",
        "bits_cat",
        "m_arr",
        "off_bits",
        "empty_mask",
        "alive_mask",
        "fence_lo_b",
        "fence_hi_b",
        "fence_vec",
        "fence_unvec",
    )

    def __init__(self, pruners: Sequence[TierPruner], width: int):
        self.pruners = list(pruners)
        self.n_tiers = len(self.pruners)
        self.width = width
        self.empty_mask = np.asarray(
            [p.nrows == 0 for p in self.pruners], dtype=bool
        )
        self.alive_mask = ~self.empty_mask
        # single-column fences as byte arrays: the small-batch fast
        # path answers one probe's fence test against EVERY tier in two
        # numpy 'S' compares.  Byte order equals code-point order only
        # for NUL-free UTF-8 str fences; any other tier keeps the exact
        # Python check (fence_unvec marks them "not vector-decided").
        self.fence_lo_b = None
        self.fence_hi_b = None
        self.fence_vec = None
        self.fence_unvec = None
        if width == 1:
            los: List[bytes] = []
            his: List[bytes] = []
            vec: List[bool] = []
            for p in self.pruners:
                lo, hi = p.fence_lo, p.fence_hi
                ok = (
                    p.nrows > 0
                    and lo is not None
                    and isinstance(lo[0], str)
                    and isinstance(hi[0], str)
                    and "\x00" not in lo[0]
                    and "\x00" not in hi[0]
                )
                vec.append(ok)
                los.append(lo[0].encode("utf-8") if ok else b"")
                his.append(hi[0].encode("utf-8") if ok else b"")
            if any(vec):
                self.fence_lo_b = np.asarray(los, dtype=np.bytes_)
                self.fence_hi_b = np.asarray(his, dtype=np.bytes_)
                self.fence_vec = np.asarray(vec, dtype=bool)
                self.fence_unvec = ~self.fence_vec
        ks = {p.k for p in self.pruners if p.bits is not None}
        seeds = {p.seed for p in self.pruners}
        if len(seeds) <= 1 and len(ks) <= 1:
            # homogeneous parameters (the normal case: one process, one
            # env) -- vectorized directory
            self.scalar_only = False
            self.seed = next(iter(seeds)) if seeds else 0
            self.k = next(iter(ks)) if ks else 0
            chunks: List[np.ndarray] = []
            ms: List[int] = []
            offs: List[int] = []
            off = 0
            pass_byte = np.full(1, 0xFF, dtype=np.uint8)
            fail_byte = np.zeros(1, dtype=np.uint8)
            for p in self.pruners:
                if p.nrows == 0:
                    chunk, m = fail_byte, 8
                elif p.bits is None:
                    chunk, m = pass_byte, 8
                else:
                    chunk, m = p.bits, p.m
                offs.append(off * 8)
                ms.append(m)
                off += len(chunk)
                chunks.append(chunk)
            self.bits_cat = (
                np.concatenate(chunks)
                if chunks
                else np.zeros(0, dtype=np.uint8)
            )
            self.m_arr = np.asarray(ms, dtype=_U64)
            self.off_bits = np.asarray(offs, dtype=_U64)
        else:
            # mixed seed/k across tiers (env changed between seals of a
            # reopened index): fall back to exact per-tier scalar checks
            self.scalar_only = True
            self.seed = 0
            self.k = 0
            self.bits_cat = np.zeros(0, dtype=np.uint8)
            self.m_arr = np.zeros(0, dtype=_U64)
            self.off_bits = np.zeros(0, dtype=_U64)

    def pass_matrix(self, probes: Sequence[Sequence]) -> np.ndarray:
        """(n_probes, n_tiers) bool: True where the tier MAY contain the
        probe.  One-sided like the scalar predicate: a False entry is a
        proof of absence, a True entry just means "go do the bounds
        pass"."""
        n = len(probes)
        nt = self.n_tiers
        out = np.ones((n, nt), dtype=bool)
        if nt == 0 or n == 0:
            return out
        if n <= _SMALL_BATCH and self.fence_vec is not None:
            return self._pass_small(probes, out)
        if self.empty_mask.any():
            out[:, self.empty_mask] = False
        width = self.width
        full = [
            i
            for i, p in enumerate(probes)
            if len(p) == width and _probe_filterable(p)
        ]
        if full and self.k and not self.scalar_only:
            h1 = np.empty(len(full), dtype=_U64)
            h2 = np.empty(len(full), dtype=_U64)
            for j, i in enumerate(full):
                a, b = probe_hashes(probes[i], self.seed)
                h1[j] = a
                h2[j] = b
            ks = np.arange(self.k, dtype=_U64)
            with np.errstate(over="ignore"):
                # (n_full, n_tiers, k) global bit positions
                pos = (
                    h1[:, None, None] + ks[None, None, :] * h2[:, None, None]
                ) % self.m_arr[None, :, None] + self.off_bits[None, :, None]
            byte = self.bits_cat[(pos >> _U64(3)).astype(np.int64)]
            bit = (byte >> (pos & _U64(7)).astype(np.uint8)) & np.uint8(1)
            survives = bit.astype(bool).all(axis=2)
            out[np.asarray(full, dtype=np.int64)] &= survives
        # fences (and, under scalar_only, per-tier filters): Python
        # checks only on (probe, tier) pairs still alive
        pruners = self.pruners
        scalar_filters = self.scalar_only
        for i, p in enumerate(probes):
            if not p:
                continue
            row = out[i]
            alive = np.flatnonzero(row)
            if not alive.size:
                continue
            is_full = len(p) == width and _probe_filterable(p)
            for t in alive:
                pr = pruners[t]
                if pr.fence_excludes(p):
                    row[t] = False
                elif scalar_filters and is_full and pr.bits is not None:
                    a, b = probe_hashes(p, pr.seed)
                    if pr.filter_excludes(a, b):
                        row[t] = False
        return out

    def _pass_small(self, probes: Sequence[Sequence], out: np.ndarray):
        """Small batches route probe-by-probe through :meth:`shortlist`
        (one implementation of the fence-first scalar path) and scatter
        the survivors back into the matrix."""
        out[:] = False
        for i, p in enumerate(probes):
            sl = self.shortlist(p)
            if sl:
                out[i, sl] = True
        return out

    def shortlist(self, probe: Sequence) -> List[int]:
        """Surviving tier indices for ONE probe — the serving
        point-lookup shape, equivalent to
        ``np.flatnonzero(pass_matrix([probe])[0])`` but orders of
        magnitude cheaper: fences go FIRST (two vectorized byte
        compares decide every tier at once), then scalar filter tests
        run only on the handful of fence survivors."""
        if not probe:
            # empty probe matches every non-empty tier
            return np.flatnonzero(self.alive_mask).tolist()
        filterable = _probe_filterable(probe)
        vec_decided = None
        if (
            self.fence_vec is not None
            and filterable
            and len(probe) == 1
            and isinstance(probe[0], str)
        ):
            pb = probe[0].encode("utf-8")
            inside = self.fence_lo_b <= pb
            inside &= pb <= self.fence_hi_b
            inside |= self.fence_unvec
            inside &= self.alive_mask
            cand = np.flatnonzero(inside).tolist()
            vec_decided = self.fence_vec
        else:
            cand = np.flatnonzero(self.alive_mask).tolist()
        full = filterable and len(probe) == self.width
        pruners = self.pruners
        out: List[int] = []
        for t in cand:
            pr = pruners[t]
            if (vec_decided is None or not vec_decided[t]) and (
                pr.fence_excludes(probe)
            ):
                continue
            if full and pr.bits is not None:
                a, b = probe_hashes(probe, pr.seed)
                if pr.filter_excludes(a, b):
                    continue
            out.append(t)
        return out


# -- sidecar persistence --------------------------------------------------


def _fsync_dir(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _jsonable_fence(fence: Optional[Tuple]):
    if fence is None:
        return None
    try:
        json.dumps(list(fence))
    except (TypeError, ValueError):
        return None
    return list(fence)


def write_pruner(path: str, pruner: TierPruner) -> None:
    """Persist one pruner: npz payload with a JSON meta record, written
    tmp -> fsync -> ``os.replace`` -> dir fsync (the manifest idiom, so
    a crash leaves either the old sidecar or the new one, never a torn
    file)."""
    lo = _jsonable_fence(pruner.fence_lo)
    hi = _jsonable_fence(pruner.fence_hi)
    if lo is None or hi is None:
        lo = hi = None
    meta = {
        "magic": _SIDECAR_MAGIC,
        "version": _SIDECAR_VERSION,
        "nrows": int(pruner.nrows),
        "m": int(pruner.m),
        "k": int(pruner.k),
        "seed": int(pruner.seed),
        "bits_per_key": int(pruner.bits_per_key),
        "fence_lo": lo,
        "fence_hi": hi,
        "has_filter": pruner.bits is not None,
    }
    blob = np.frombuffer(
        json.dumps(meta, sort_keys=True).encode("utf-8"), dtype=np.uint8
    )
    bits = (
        pruner.bits
        if pruner.bits is not None
        else np.zeros(0, dtype=np.uint8)
    )
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, meta=blob, bits=bits)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(os.path.abspath(path)))


def load_pruner(path: str, *, expect_nrows: Optional[int] = None) -> TierPruner:
    """Load a sidecar written by :func:`write_pruner`.

    Raises ``ValueError`` on any structural mismatch (bad magic,
    truncated arrays, row-count disagreement with the base it claims to
    describe) — callers treat that as "rebuild by scan", never as data.
    """
    with np.load(path) as z:
        if "meta" not in z or "bits" not in z:
            raise ValueError(f"prune sidecar {path}: missing arrays")
        meta = json.loads(bytes(z["meta"].tobytes()).decode("utf-8"))
        bits = np.asarray(z["bits"], dtype=np.uint8)
    if meta.get("magic") != _SIDECAR_MAGIC:
        raise ValueError(f"prune sidecar {path}: bad magic")
    if int(meta.get("version", -1)) != _SIDECAR_VERSION:
        raise ValueError(f"prune sidecar {path}: unsupported version")
    nrows = int(meta["nrows"])
    if expect_nrows is not None and nrows != expect_nrows:
        raise ValueError(
            f"prune sidecar {path}: describes {nrows} rows, "
            f"base has {expect_nrows}"
        )
    m = int(meta["m"])
    k = int(meta["k"])
    has_filter = bool(meta.get("has_filter"))
    if has_filter:
        if bits.size != (m + 7) // 8 or m <= 0 or k <= 0:
            raise ValueError(f"prune sidecar {path}: truncated filter")
        out_bits: Optional[np.ndarray] = bits
    else:
        out_bits = None
    lo = meta.get("fence_lo")
    hi = meta.get("fence_hi")
    fence_lo = tuple(lo) if lo is not None else None
    fence_hi = tuple(hi) if hi is not None else None
    if (fence_lo is None) != (fence_hi is None):
        raise ValueError(f"prune sidecar {path}: half a fence")
    return TierPruner(
        nrows,
        fence_lo,
        fence_hi,
        out_bits,
        m,
        k,
        int(meta["seed"]),
        int(meta["bits_per_key"]),
    )
