"""Mutable indexes: durable LSM delta tiers behind the lookup engine.

The reference csvplus ``Index`` is a frozen sorted materialization
(csvplus.go:610-920); every layer above it in this repo — the batched
lookup engine, the serving tier, resilience — assumed a build-once
read-forever world.  This package opens the write workload without
touching that machinery: appended rows land as small **sorted delta
tiers** (each one an ordinary :class:`~csvplus_tpu.index.Index` built
through the existing ingest + ``create_index`` encode path), deletes
land as **tombstones** that shadow older tiers in both visibility
modes, lookups probe base+deltas through the same multi-tier
``bounds_many`` engine and stitch results per probe, and a background
**compactor** folds tiers with a cache-conscious multi-way merge that
swaps in atomically under readers (epoch-snapshotted tier sets; the
probe hot path takes no lock) — either everything into the base each
pass, level-by-level under the size-ratio policy for bounded write
amplification, or from observed read amplification (``readamp``) so
compaction work tracks what readers actually pay.

Read pruning (ISSUE 11): every sealed row tier carries min/max key
fences and a seeded Bloom fingerprint filter
(:mod:`~csvplus_tpu.storage.prune`); lookups consult them on the host
to shortlist tiers BEFORE any per-tier bounds pass, so a probe against
a hundred live tiers touches the 1-3 that can contain the key.
Pruning is one-sided — bitwise-identical results with it on or off —
and checkpointed bases persist their summaries as ``prune-*.flt``
sidecars so recovery never rescans.

Durability: construct with ``directory=`` (or recover with
``MutableIndex.open``) and every append/delete writes one checksummed
record to a segmented write-ahead log before it becomes visible,
fsynced per ``CSVPLUS_WAL_SYNC``; full merges checkpoint the base and
swap ``MANIFEST.json`` atomically, so a crash at ANY point recovers
state checksum-equal to replaying the acked logical stream.

* :mod:`~csvplus_tpu.storage.lsm` — :class:`DeltaTier`, :class:`TierSet`,
  :class:`MutableIndex` (visibility rules, epoch snapshots, durable
  append/delete/recovery, the from-scratch rebuild reference used by
  the parity harness).
* :mod:`~csvplus_tpu.storage.compact` — the stable searchsorted
  multi-way merge over union-dictionary code spaces (tombstone-aware,
  dead-dictionary pruning), the size-ratio leveling planner, and the
  :class:`Compactor` background thread.
* :mod:`~csvplus_tpu.storage.wal` — segmented, length-prefixed,
  crc32-checksummed write-ahead log with torn-tail truncation.
* :mod:`~csvplus_tpu.storage.manifest` — the atomic
  write-temp-then-rename recovery manifest.

Hard contract (tests/test_storage.py + ``make bench-delta`` + the
``make chaos`` crash matrix): at every compaction step AND after every
crash-recovery, base+deltas checksum-match a from-scratch rebuild of
the acked logical stream (bitwise, positional), and warm lookups
against a compacted or recovered index record zero recompiles.  See
docs/STORAGE.md.
"""

from .compact import Compactor, merge_tiers, merge_units, plan_compaction
from .lsm import (
    DeltaTier,
    MutableIndex,
    ReadAmpTracker,
    TierSet,
    index_checksums,
    rebuild_reference,
)
from .manifest import MANIFEST_NAME, ManifestError, read_manifest, write_manifest
from .prune import PruneDirectory, TierPruner, build_pruner, load_pruner, write_pruner
from .wal import Wal, WalError, wal_sync_mode

__all__ = [
    "Compactor",
    "DeltaTier",
    "MANIFEST_NAME",
    "ManifestError",
    "MutableIndex",
    "PruneDirectory",
    "ReadAmpTracker",
    "TierPruner",
    "TierSet",
    "Wal",
    "WalError",
    "build_pruner",
    "index_checksums",
    "load_pruner",
    "merge_tiers",
    "merge_units",
    "plan_compaction",
    "read_manifest",
    "rebuild_reference",
    "wal_sync_mode",
    "write_manifest",
]
