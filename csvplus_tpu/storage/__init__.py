"""Mutable indexes: LSM delta tiers behind the immutable lookup engine.

The reference csvplus ``Index`` is a frozen sorted materialization
(csvplus.go:610-920); every layer above it in this repo — the batched
lookup engine, the serving tier, resilience — assumed a build-once
read-forever world.  This package opens the write workload without
touching that machinery: appended rows land as small **sorted delta
tiers** (each one an ordinary :class:`~csvplus_tpu.index.Index` built
through the existing ingest + ``create_index`` encode path), lookups
probe base+deltas through the same multi-tier ``bounds_many`` engine
and stitch results per probe, and a background **compactor** folds
deltas into the base with a cache-conscious multi-way merge that swaps
in atomically under readers (epoch-snapshotted tier sets; the probe
hot path takes no lock).

* :mod:`~csvplus_tpu.storage.lsm` — :class:`DeltaTier`, :class:`TierSet`,
  :class:`MutableIndex` (visibility rules, epoch snapshots, the
  from-scratch rebuild reference used by the parity harness).
* :mod:`~csvplus_tpu.storage.compact` — the stable searchsorted
  multi-way merge over union-dictionary code spaces and the
  :class:`Compactor` background thread.

Hard contract (tests/test_storage.py + ``make bench-delta``): at every
compaction step, base+deltas checksum-match a from-scratch rebuild of
the same logical rows (bitwise, positional), and warm lookups against a
compacted index record zero recompiles.  See docs/STORAGE.md.
"""

from .compact import Compactor, merge_tiers
from .lsm import (
    DeltaTier,
    MutableIndex,
    TierSet,
    index_checksums,
    rebuild_reference,
)

__all__ = [
    "Compactor",
    "DeltaTier",
    "MutableIndex",
    "TierSet",
    "index_checksums",
    "merge_tiers",
    "rebuild_reference",
]
