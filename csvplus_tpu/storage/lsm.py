"""LSM tier sets: delta tiers, tombstones, epoch snapshots, durability.

Layout
------

A :class:`MutableIndex` is a **base** tier (an ordinary sorted
:class:`~csvplus_tpu.index.Index`) plus a tuple of **delta** tiers.  A
delta tier holds a small sorted Index built from one append batch
through the existing encode path (``DeviceTable`` columnarization or
the staged streamed-ingest pipeline for ``append_csv``), a set of
**tombstone** keys written by :meth:`MutableIndex.delete`, or — after a
partial (leveled) merge — both.  The logical row stream is the
concatenation base → delta0 → delta1 → … in append order; every read
answers as if that stream had been indexed from scratch after applying
each delete at its stream position.

Visibility (``mode``)
---------------------

* ``"append"`` (default) — multiset appends: all tiers are visible,
  equal keys interleave in (key, tier, within-tier position) order —
  bitwise-identical to a from-scratch **stable** rebuild of the
  logical stream, because each tier is itself a stable sort of its
  batch.
* ``"upsert"`` — newest-wins: a key present in a newer tier shadows
  every older tier's rows for that key (whole key groups, so one
  append batch may still hold duplicates).  Equal to rebuilding after
  dropping each row whose full key reappears in any LATER tier.

Tombstones shadow in BOTH modes: a tombstone at tier position *p*
erases every matching full key in tiers strictly older than *p* (rows
appended after the delete are visible again).  A full merge into the
base drops tombstones permanently; a partial merge carries the
surviving tombstone set on the merged tier (it must keep shadowing
out-of-range older tiers).

Durability (ISSUE 10)
---------------------

Pass ``directory=`` at construction (or use :meth:`MutableIndex.open`)
and every append/delete writes one checksummed record to a segmented
write-ahead log (:mod:`~csvplus_tpu.storage.wal`) BEFORE the tier
becomes visible, fsynced per ``CSVPLUS_WAL_SYNC`` (``always`` |
``batch`` | ``off``).  Full compactions checkpoint: the merged base
persists via the versioned ``Index.write_to`` format, the WAL seals its
active segment, and ``MANIFEST.json`` swaps atomically
(:mod:`~csvplus_tpu.storage.manifest`); applied segments are then
dropped.  :meth:`open` recovers by loading the manifest's base and
replaying only WAL records newer than its ``applied_lsn``, truncating a
torn final record — recovered state is bitwise-equal
(:func:`index_checksums`) to replaying the acked logical stream into a
fresh index, the crash-matrix contract ``make chaos`` enforces.

Concurrency (the r10 epoch rule)
--------------------------------

All tier-list state lives in one immutable :class:`TierSet`; readers
pin it with a single attribute read (``self._tiers`` — atomic under
the GIL) and never take a lock on the probe hot path.  Writers
(``append_*`` / ``delete`` / ``compact_once`` / ``compact_step``)
build a NEW TierSet and swap it under ``self._lock``.  The compactor
merges OUTSIDE the lock against its pinned snapshot and swaps only the
merged range, so appends landing mid-merge survive as the new tier
list's tail.  ``append_rows``, ``delete``, ``compact_once``,
``compact_step``, ``wal_sync``, ``bounds_many`` and the
:class:`ReadAmpTracker` entries are THREAD001 worker entries
(analysis/astlint.py): every shared-state mutation below them must sit
under a lock, with zero allowances.

Read pruning (ISSUE 11, lazy since ISSUE 12)
--------------------------------------------

Each row tier carries a :class:`~csvplus_tpu.storage.prune.TierPruner`
(min/max key fences + a seeded Bloom filter); every :meth:`bounds_many`
batch consults the TierSet's :class:`~csvplus_tpu.storage.prune.PruneDirectory`
on the host to shortlist tiers BEFORE any per-tier bounds pass.  Delta
summaries build LAZILY on the first probe after a swap (cached on the
DeltaTier, shared across epochs), so the append path no longer pays
the O(n) fence+filter scan per sealed batch.  Pruning is one-sided, so
results are bitwise-identical with it on or off
(``CSVPLUS_LSM_PRUNE=0`` disables it).  Checkpoints persist the merged
base's summaries as a ``prune-%08d.flt`` sidecar named in the
manifest, so recovery reloads them without a rescan.

Tier-swap listeners (ISSUE 12)
------------------------------

:meth:`MutableIndex.subscribe` registers a callback that fires on
every append (``("rows", seq, index)``) and delete
(``("tombs", seq, keys)``) tier swap — the live materialized views'
delta feed (:mod:`csvplus_tpu.views`).  Callbacks run UNDER the writer
lock immediately after the swap, so delivery order is exactly tier
order with no gaps relative to the TierSet returned at subscription;
the contract is that a listener is O(1) enqueue-only, never raises,
and never calls back into the index.  Compactions fire no events:
they rewrite physical tiers, not the logical stream.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from ..index import Index, create_index, load_index
from ..obs import flight as _flight
from ..resilience import faults
from ..row import Row
from ..source import take_rows
from ..utils.env import env_int
from ..utils.observe import telemetry
from .prune import (
    PruneDirectory,
    TierPruner,
    build_pruner,
    load_pruner,
    prune_enabled,
    write_pruner,
)

__all__ = [
    "DeltaTier",
    "MutableIndex",
    "ReadAmpTracker",
    "TierSet",
    "index_checksums",
    "rebuild_reference",
    "tier_rows",
]

_MODES = ("append", "upsert")


class DeltaTier:
    """One append batch and/or tombstone set at one stream position.

    ``index`` is the batch's small sorted Index (None for a pure
    tombstone tier); ``tombs`` is a sorted tuple of full-width key
    tuples that shadow every strictly OLDER tier (never this tier's own
    rows — after a partial merge a tier carries both, and its rows were
    appended after its deletes)."""

    __slots__ = ("seq", "index", "tombs", "tomb_set", "pruner",
                 "_pruner_built", "_plock")

    def __init__(self, seq: int, index: Optional[Index],
                 tombs: Sequence[Tuple[str, ...]] = (),
                 pruner: Optional[TierPruner] = None):
        self.seq = seq
        self.index = index
        self.tombs: Tuple[Tuple[str, ...], ...] = tuple(
            sorted(set(tuple(k) for k in tombs))
        )
        self.tomb_set: FrozenSet[Tuple[str, ...]] = frozenset(self.tombs)
        # fences + fingerprint filter for this tier's rows (prune.py);
        # None for pure tombstone tiers or when pruning is disabled.
        # Tombstones themselves are NEVER pruned — shadowing reads the
        # tomb_set directly, so a pruned row tier cannot un-shadow
        # anything.
        #
        # Freshly appended tiers arrive WITHOUT a pruner (the write-side
        # tax fix): the O(n) fence+filter scan is deferred to the first
        # probe via ensure_pruner, and the built summary is cached HERE
        # — the tier object survives TierSet swaps, so successor epochs
        # reuse it and each sealed batch pays the scan at most once.
        self.pruner = pruner
        self._pruner_built = pruner is not None or index is None
        self._plock = threading.Lock()

    def ensure_pruner(self, key_columns: Sequence[str]) -> Optional[TierPruner]:
        """The tier's pruner, building it on first demand (double-
        checked under the per-tier lock — the IndexImpl lazy-build
        idiom, so concurrent first probes scan once)."""
        if self._pruner_built:
            return self.pruner
        with self._plock:
            if not self._pruner_built:
                self.pruner = build_pruner(self.index._impl, key_columns)
                self._pruner_built = True
        return self.pruner

    @property
    def nrows(self) -> int:
        return 0 if self.index is None else len(self.index._impl)

    def __repr__(self) -> str:  # debugging aid only
        return (
            f"DeltaTier(seq={self.seq}, nrows={self.nrows}, "
            f"tombs={len(self.tombs)})"
        )


class TierSet:
    """Immutable snapshot of the tier list at one epoch.

    Readers that captured a TierSet keep answering from it even while
    a writer swaps in a successor — the old tiers stay alive (and
    correct) for as long as any reader holds them.
    """

    __slots__ = ("epoch", "base", "deltas", "base_pruner", "prune_dir",
                 "row_tiers", "positions", "tombs_by_age", "tomb_newest",
                 "key_columns", "_pd_built", "_pd_lock")

    def __init__(self, epoch: int, base: Index, deltas: Tuple[DeltaTier, ...],
                 base_pruner: Optional[TierPruner] = None):
        self.epoch = epoch
        self.base = base
        self.deltas = deltas
        self.base_pruner = base_pruner
        self.key_columns = tuple(base._impl.columns)
        # read-path projections, computed ONCE per swap: rebuilding
        # these per lookup costs one Python pass over every delta —
        # measurable at 100+ tiers even when pruning skips them all
        self.row_tiers = (base,) + tuple(
            d.index for d in deltas if d.index is not None
        )
        self.positions = (0,) + tuple(
            p + 1 for p, d in enumerate(deltas) if d.index is not None
        )
        self.tombs_by_age = tuple(
            (p + 1, d.tomb_set) for p, d in enumerate(deltas) if d.tombs
        )
        # merged newest-tombstone-per-key map: the full-width probe
        # shadow test becomes one dict hit instead of a membership test
        # against every tombstone tier
        newest: Dict[Tuple[str, ...], int] = {}
        for p, tset in self.tombs_by_age:
            for key in tset:
                newest[key] = p  # tombs_by_age ascends: last write wins
        self.tomb_newest = newest
        # the read path's prune directory is built LAZILY on the first
        # probe (satellite of ISSUE 12): appends no longer pay the O(n)
        # fence+filter scan per sealed delta — the first bounds_many
        # after a swap does, once, with each per-tier summary cached on
        # the DeltaTier itself so successor epochs reuse it.  Pruning
        # engages only when a base pruner exists (CSVPLUS_LSM_PRUNE on
        # at seal time); with it off prune_dir stays None forever.
        self.prune_dir = None
        self._pd_built = base_pruner is None
        self._pd_lock = threading.Lock()

    def prune_directory(self) -> Optional[PruneDirectory]:
        """The epoch's prune directory, aggregated on first demand.

        Double-checked under the per-TierSet lock (the IndexImpl
        lazy-build idiom THREAD001 sanctions): concurrent first probes
        build once; every later probe is the same single attribute read
        the eager path had.  Missing delta summaries are built through
        :meth:`DeltaTier.ensure_pruner`, which caches them on the tier
        object — shared across epochs, so each sealed batch is scanned
        at most once over its whole lifetime."""
        if self._pd_built:
            return self.prune_dir
        with self._pd_lock:
            if not self._pd_built:
                prs = [self.base_pruner] + [
                    d.ensure_pruner(self.key_columns)
                    for d in self.deltas if d.index is not None
                ]
                if all(p is not None for p in prs):
                    self.prune_dir = PruneDirectory(prs, len(self.key_columns))
                self._pd_built = True
        return self.prune_dir

    def indexes(self) -> Tuple[Index, ...]:
        """All ROW tiers oldest→newest (base first; pure tombstone
        tiers carry no rows and are skipped)."""
        return self.row_tiers


class MultiBounds:
    """Pinned tier set + per-row-tier bounds for one probe batch.

    Opaque handle between :meth:`MutableIndex.bounds_many` and
    :meth:`MutableIndex.rows_for_bounds` — pinning the TierSet here
    keeps the two phases epoch-consistent even when the compactor
    swaps between them (the serving tier calls them separately).
    ``positions`` maps each bounds row back to its tier-stream position
    (base = 0, delta *i* = *i*+1) so tombstone shadowing can compare
    ages across row and tombstone tiers."""

    __slots__ = ("tiers", "per_tier", "probes", "row_tiers", "positions",
                 "tiers_probed", "tiers_pruned")

    def __init__(self, tiers: TierSet, per_tier, probes, row_tiers, positions):
        self.tiers = tiers
        self.per_tier = per_tier
        self.probes = probes
        self.row_tiers = row_tiers
        self.positions = positions
        # (probe, tier) bounds passes actually paid / skipped via
        # fences+filters for this batch — the serving tier forwards
        # these into its per-index metrics cells
        self.tiers_probed = 0
        self.tiers_pruned = 0


class ReadAmpTracker:
    """Observed read amplification: (probe, tier) bounds passes per
    lookup, with a resettable window the read-amp-aware Compactor
    polls.  ``on_lookup_batch`` and ``take_window`` are THREAD001
    worker entries — all state mutates under ``_lock`` (one lock round
    per probe BATCH, off the per-probe fast path)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._probes_total = 0
        self._tier_probes_total = 0
        self._pruned_total = 0
        self._win_probes = 0
        self._win_tier_probes = 0

    def on_lookup_batch(self, n_probes: int, tiers_probed: int,
                        tiers_pruned: int) -> None:
        with self._lock:
            self._probes_total += n_probes
            self._tier_probes_total += tiers_probed
            self._pruned_total += tiers_pruned
            self._win_probes += n_probes
            self._win_tier_probes += tiers_probed

    def take_window(self) -> Optional[float]:
        """Mean tiers probed per lookup since the last call (None when
        no lookups landed) — and reset the window."""
        with self._lock:
            p = self._win_probes
            tp = self._win_tier_probes
            self._win_probes = 0
            self._win_tier_probes = 0
        return (tp / p) if p else None

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            p = self._probes_total
            tp = self._tier_probes_total
            pr = self._pruned_total
        return {
            "probes": p,
            "tier_probes": tp,
            "tiers_pruned": pr,
            "mean_tiers_probed": round(tp / p, 3) if p else None,
        }


def tier_rows(impl) -> List[Row]:
    """Decode one tier's sorted rows WITHOUT flipping a device-lazy
    impl onto its host branch: touching ``impl.rows`` would cache host
    rows and permanently reroute ``bounds_many`` off the device path
    (the same trap HostLookupOracle documents)."""
    if impl._rows is None and impl.dev is not None:
        return impl.dev.table.to_rows()
    return impl.rows


def _logical_streams(ts: TierSet) -> List[List[Row]]:
    return [tier_rows(ix._impl) for ix in ts.indexes()]


def _upsert_filter(streams: List[List[Row]], key_cols: Sequence[str]) -> List[List[Row]]:
    """Drop every row whose full key appears in any LATER tier — the
    newest-wins rebuild rule, computed key-by-key on host rows
    (deliberately independent of the packed-key merge in compact.py so
    the parity harness cross-checks two implementations)."""
    newest: Dict[tuple, int] = {}
    for t, rows in enumerate(streams):
        for r in rows:
            newest[tuple(r[c] for c in key_cols)] = t
    return [
        [r for r in rows if newest[tuple(r[c] for c in key_cols)] == t]
        for t, rows in enumerate(streams)
    ]


def rebuild_reference(mindex: "MutableIndex", ts: Optional[TierSet] = None) -> Index:
    """From-scratch rebuild of the pinned tier set's logical stream —
    the parity harness's ground truth.  Replays tier events in order
    (a tier's tombstones erase matching keys from everything
    accumulated so far, THEN its rows append), applies the upsert
    newest-wins rule to the survivors, and routes through the HOST
    ``create_index`` build (stable Python sort over Row dicts) — a
    completely separate code path from the compactor's packed
    searchsorted merge, so agreement is meaningful."""
    ts = ts if ts is not None else mindex.tiers()
    cols = mindex.columns
    streams: List[List[Row]] = [tier_rows(ts.base._impl)]
    for d in ts.deltas:
        if d.tombs:
            dead = d.tomb_set
            streams = [
                [r for r in rows if tuple(r[c] for c in cols) not in dead]
                for rows in streams
            ]
        if d.index is not None:
            streams.append(tier_rows(d.index._impl))
        else:
            streams.append([])
    if mindex.mode == "upsert":
        streams = _upsert_filter(streams, cols)
    rows = [Row(r) for s in streams for r in s]
    return create_index(take_rows(rows), cols)


def index_checksums(index: Index, columns: Optional[Sequence[str]] = None) -> Dict[str, int]:
    """Positional per-column checksums over an index's sorted rows —
    the differential-harness currency (utils/checksum.py), order-
    sensitive so tier-merge bugs that permute equal keys still trip."""
    from ..utils.checksum import checksum_host_rows

    rows = tier_rows(index._impl)
    if columns is None:
        seen = set()
        columns = []
        for r in rows:
            for c in r:
                if c not in seen:
                    seen.add(c)
                    columns.append(c)
        columns = sorted(columns)
    return checksum_host_rows(rows, columns, positional=True)


class MutableIndex:
    """LSM-style mutable index over the immutable lookup engine.

    Implements the lookup-impl protocol the serving tier consumes
    (``columns`` / ``bounds_many`` / ``rows_for_bounds`` /
    ``find_rows_many``) plus the write surface (``append_rows`` /
    ``append_table`` / ``append_csv`` / ``delete`` / ``compact_once``
    / ``compact_step``), so a ``LookupServer`` can register one
    directly.  With ``directory=`` the write surface is durable (WAL +
    manifest, see the module docstring); ``wal_sync()`` is the serving
    tier's per-cycle ack barrier.
    """

    # lookup-protocol compatibility: the host-fallback oracle checks
    # ``impl.dev`` to decide whether it may reuse the impl directly —
    # a MutableIndex IS its own host-correct fallback
    dev = None

    def __init__(self, base: Index, *, mode: str = "append", ingest_device=None,
                 directory: Optional[str] = None, wal_sync: Optional[str] = None,
                 _manifest: Optional[Dict[str, object]] = None):
        if not isinstance(base, Index):
            raise TypeError("MutableIndex wraps an existing Index as its base tier")
        if mode not in _MODES:
            raise ValueError(f"unknown MutableIndex mode {mode!r} (use append|upsert)")
        self.mode = mode
        self._columns = list(base._impl.columns)
        impl = base._impl
        self._device = (
            impl.dev.table.device if impl.dev is not None else ingest_device
        )
        self._ingest_device = ingest_device
        self._lock = threading.Lock()
        # serializes whole compaction passes (snapshot -> merge -> swap):
        # the swap-range invariant assumes at most one in-flight merge
        self._compact_lock = threading.Lock()
        # fences + fingerprint filters (prune.py): CSVPLUS_LSM_PRUNE
        # gates the whole subsystem.  A recovered index reloads the
        # checkpointed base's sidecar (named in the manifest) instead
        # of rescanning; a missing or corrupt sidecar degrades to the
        # rebuild scan — slower startup, never wrong answers.
        self._prune = prune_enabled()
        self._readamp = ReadAmpTracker()
        # optional build-side key-skew sketch (ISSUE 13): when the
        # telemetry plane installs a SpaceSaving here, every sealed
        # delta's keys are offered — heavy-hitter evidence for the
        # skew-aware join work.  None = zero overhead.
        self.key_sketch = None
        # tier-swap listeners (the views delta feed) — a tuple swapped
        # whole under self._lock so delivery iterates immutable state
        self._listeners: Tuple = ()
        base_pruner: Optional[TierPruner] = None
        if self._prune:
            side = None if _manifest is None else _manifest.get("prune")
            if directory is not None and side:
                try:
                    base_pruner = load_pruner(
                        os.path.join(directory, str(side)),
                        expect_nrows=len(base._impl),
                    )
                except Exception:
                    base_pruner = None  # rebuild by scan below
            if base_pruner is None:
                base_pruner = build_pruner(base._impl, self._columns)
        self._tiers = TierSet(0, base, (), base_pruner=base_pruner)
        self._next_seq = 1
        self._compactions = 0
        self._compact_seconds = 0.0
        # durability state (all None/0 for a memory-only index)
        self._dir = directory
        self._wal = None
        self._ckpt = 0
        self._applied_lsn = 0
        self._base_file: Optional[str] = None
        self.recovered_records = 0
        self.recovery_info: Optional[Dict[str, object]] = None
        if directory is None:
            return
        from . import manifest as mf
        from .wal import Wal

        if _manifest is None:
            # fresh durable directory: persist the base, start the WAL,
            # publish the first manifest — all durable before any ack
            os.makedirs(directory, exist_ok=True)
            if os.path.exists(os.path.join(directory, mf.MANIFEST_NAME)):
                raise mf.ManifestError(
                    f"{directory}: already a durable MutableIndex directory "
                    f"(use MutableIndex.open)"
                )
            self._ckpt = 1
            self._base_file = f"base-{self._ckpt:08d}.idx"
            path = os.path.join(directory, self._base_file)
            base.write_to(path)
            fd = os.open(path, os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
            self._wal = Wal.create(directory, sync=wal_sync,
                                   columns=self._columns)
            prune_name = None
            if base_pruner is not None:
                prune_name = f"prune-{self._ckpt:08d}.flt"
                write_pruner(
                    os.path.join(directory, prune_name), base_pruner
                )
            mf.write_manifest(directory, mf.manifest_doc(
                mode=self.mode, key_columns=self._columns,
                checkpoint=self._ckpt, base=self._base_file, applied_lsn=0,
                segments=self._wal.segment_names(), prune=prune_name,
            ))
        else:
            # recovery: replay the WAL tail newer than the manifest's
            # applied_lsn through the SAME delta-encode path appends ride
            man = _manifest
            self._ckpt = int(man["checkpoint"])  # type: ignore[arg-type]
            self._applied_lsn = int(man["applied_lsn"])  # type: ignore[arg-type]
            self._base_file = str(man["base"])
            self._next_seq = self._applied_lsn + 1
            wal, replay, info = Wal.open(
                directory, self._applied_lsn, sync=wal_sync,
                columns=self._columns,
            )
            self._wal = wal
            for doc in replay:
                lsn = int(doc["lsn"])
                if doc.get("op") == "del":
                    delta = DeltaTier(lsn, None, (tuple(doc["key"]),))
                else:
                    rows = [Row(r) for r in doc["rows"]]
                    idx = self._build_delta_index(rows)
                    # no seal-time pruner: the first probe builds it
                    # (same lazy rule as the live append path)
                    delta = DeltaTier(lsn, idx)
                ts = self._tiers
                self._tiers = TierSet(ts.epoch + 1, ts.base,
                                      ts.deltas + (delta,),
                                      base_pruner=ts.base_pruner)
                self._next_seq = lsn + 1
            self.recovered_records = len(replay)
            self.recovery_info = info
            mf.remove_stale(directory, man)

    @classmethod
    def create(cls, src, columns: Sequence[str], *, mode: str = "append",
               ingest_device=None, directory: Optional[str] = None,
               wal_sync: Optional[str] = None) -> "MutableIndex":
        """Build the base tier with ``create_index`` and wrap it
        (durably when *directory* is given)."""
        return cls(create_index(src, columns), mode=mode,
                   ingest_device=ingest_device, directory=directory,
                   wal_sync=wal_sync)

    @classmethod
    def open(cls, directory: str, *, ingest_device=None,
             wal_sync: Optional[str] = None) -> "MutableIndex":
        """Recover a durable MutableIndex: load the manifest's base
        tier, replay the unsealed WAL tail (truncating a torn final
        record), and sweep crash leftovers.  The recovered state is
        bitwise-equal to replaying the acked logical stream into a
        fresh index."""
        from . import manifest as mf

        man = mf.read_manifest(directory)
        base = load_index(os.path.join(directory, str(man["base"])))
        if list(man["key_columns"]) != list(base._impl.columns):
            raise mf.ManifestError(
                f"{directory}: manifest key columns {man['key_columns']!r} "
                f"disagree with base tier columns {base._impl.columns!r}"
            )
        return cls(base, mode=str(man["mode"]), ingest_device=ingest_device,
                   directory=directory, wal_sync=wal_sync, _manifest=man)

    # -- lookup-impl protocol ----------------------------------------------

    @property
    def _impl(self) -> "MutableIndex":
        # LookupServer unwraps ``index._impl``; a MutableIndex is its
        # own impl (bounds_many/rows_for_bounds below span all tiers)
        return self

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    @property
    def epoch(self) -> int:
        return self._tiers.epoch

    @property
    def delta_count(self) -> int:
        return len(self._tiers.deltas)

    @property
    def durable(self) -> bool:
        return self._wal is not None

    @property
    def readamp(self) -> ReadAmpTracker:
        """Observed read-amplification counters (the read-amp-aware
        Compactor polls ``readamp.take_window()``)."""
        return self._readamp

    def tiers(self) -> TierSet:
        """Pin the current tier-set epoch (one atomic read)."""
        return self._tiers

    def subscribe(self, callback) -> TierSet:
        """Register a tier-swap listener and return the TierSet pinned
        at registration — every later append/delete fires exactly one
        event after it, so replaying the pinned set then applying
        events in delivery order reconstructs the logical stream with
        no gap and no duplicate (the views subsystem's feed).

        Events are ``("rows", seq, index)`` for an append tier and
        ``("tombs", seq, keys)`` for a tombstone tier (*keys* a tuple
        of full-width key tuples).  The callback runs UNDER the writer
        lock: it must be O(1) enqueue-only, must not raise, and must
        not call back into this index."""
        with self._lock:
            self._listeners = self._listeners + (callback,)
            return self._tiers

    def unsubscribe(self, callback) -> None:
        """Remove a tier-swap listener (no-op when absent); events
        already delivered stay delivered."""
        with self._lock:
            self._listeners = tuple(
                cb for cb in self._listeners if cb is not callback
            )

    def __len__(self) -> int:
        ts = self._tiers
        return sum(len(ix._impl) for ix in ts.indexes())

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe accounting for metrics/bench artifacts."""
        ts = self._tiers
        with self._lock:
            compactions = self._compactions
            compact_s = self._compact_seconds
            ckpt = self._ckpt
            applied = self._applied_lsn
        out = {
            "mode": self.mode,
            "epoch": ts.epoch,
            "base_rows": len(ts.base._impl),
            "deltas": len(ts.deltas),
            "delta_rows": sum(d.nrows for d in ts.deltas),
            "tombstones": sum(len(d.tombs) for d in ts.deltas),
            "compactions": compactions,
            "compact_seconds_total": round(compact_s, 6),
        }
        out["prune"] = dict(self._readamp.snapshot())
        # base_pruner presence (not prune_dir, which builds lazily on
        # the first probe) is what decides whether probes can prune
        out["prune"]["enabled"] = bool(
            self._prune and ts.base_pruner is not None
        )
        if self._wal is not None:
            out["wal"] = self._wal.stats()
            out["checkpoint"] = ckpt
            out["applied_lsn"] = applied
            out["recovered_records"] = self.recovered_records
        return out

    # -- reads (no lock on this path) --------------------------------------

    def bounds_many(self, probes: Sequence[Sequence[str]]) -> MultiBounds:
        """Per-tier bounds for the whole probe batch, pinned to one
        epoch.  Tombstone tiers hold no rows — they join at merge time
        via the pinned TierSet.

        Read-path pruning (the r11→r12 cliff fix): before ANY per-tier
        bounds pass, the pinned TierSet's :class:`PruneDirectory`
        answers every (probe, tier) fence+filter test in one host numpy
        pass and the bounds passes run only against the shortlist —
        batched probes prune per-key against the shortlist union, so a
        tier pays a bounds pass only for the probes it may actually
        contain.  Pruning is one-sided (a skipped (probe, tier) pair is
        PROVEN empty and reads back as the same ``(0, 0)`` the bounds
        pass would have returned), so results are bitwise-identical
        with pruning on or off; false positives cost one redundant
        bounds pass.  Host numpy only — nothing here can recompile."""
        norm = [(p,) if isinstance(p, str) else tuple(p) for p in probes]
        width = len(self._columns)
        for p in norm:
            if len(p) > width:
                raise ValueError("too many columns in Index.find()")
        ts = self._tiers
        row_tiers = ts.row_tiers
        positions = ts.positions
        n_tiers = len(row_tiers)
        pd = ts.prune_directory()
        pruned = 0
        if pd is not None and norm and n_tiers > 1:
            t0 = time.perf_counter()
            n_b = len(norm)
            # tiers no probe survived drop out of the MultiBounds
            # entirely: they would contribute only (0, 0) bounds, and
            # carrying them would make rows_for_bounds pay one Python
            # visit per pruned tier per probe — the cold-tier tax this
            # pass exists to kill.  positions keep the ORIGINAL tier
            # epochs, so tombstone age masks and upsert newest-wins
            # ordering are unaffected by the renumbering.
            kept_rt = []
            kept_pos = []
            per_tier = []
            probed = 0
            if n_b == 1:
                # the serving single-probe shape: every surviving tier
                # needs the full (1-probe) bounds pass — no pass
                # matrix, no per-tier count bookkeeping
                for t in pd.shortlist(norm[0]):
                    per_tier.append(row_tiers[t]._impl.bounds_many(norm))
                    kept_rt.append(row_tiers[t])
                    kept_pos.append(positions[t])
                probed = len(kept_rt)
            else:
                keep = pd.pass_matrix(norm)
                counts = keep.sum(axis=0, dtype=np.int64).tolist()
                empty = [(0, 0)] * n_b
                for t, c in enumerate(counts):
                    if not c:
                        continue
                    ix = row_tiers[t]
                    if c == n_b:
                        sub = ix._impl.bounds_many(norm)
                    else:
                        sel = np.flatnonzero(keep[:, t])
                        part = ix._impl.bounds_many(
                            [norm[int(i)] for i in sel]
                        )
                        sub = list(empty)
                        for k, i in enumerate(sel):
                            sub[int(i)] = part[k]
                    per_tier.append(sub)
                    kept_rt.append(ix)
                    kept_pos.append(positions[t])
                    probed += c
            row_tiers = kept_rt
            positions = kept_pos
            pruned = n_b * n_tiers - probed
            if telemetry.enabled:
                telemetry.add_stage(
                    "storage:prune", n_b * n_tiers, probed,
                    time.perf_counter() - t0, tiers=n_tiers,
                )
        else:
            per_tier = [ix._impl.bounds_many(norm) for ix in row_tiers]
            probed = n_tiers * len(norm)
        self._readamp.on_lookup_batch(len(norm), probed, pruned)
        mb = MultiBounds(ts, per_tier, norm, row_tiers, positions)
        mb.tiers_probed = probed
        mb.tiers_pruned = pruned
        return mb

    def rows_for_bounds(self, mb: MultiBounds) -> List[List[Row]]:
        """Merge per-tier bounds into per-probe row blocks with ONE
        amortized gather-decode per tier (each tier's matched ranges
        decode together through its ``rows_for_bounds``).

        Fast paths: a probe matched by a single tier returns that
        tier's block directly; a full-width probe needs no key-level
        merge (all rows share one key — tombstones mask whole tiers by
        age, ``append`` concatenates survivors in tier order,
        ``upsert`` decodes only the newest matching tier).  Only
        PREFIX probes overlapping a live tombstone pay the host
        key-merge."""
        ts = mb.tiers
        row_tiers = mb.row_tiers
        positions = mb.positions
        per_tier = mb.per_tier
        n_tiers = len(row_tiers)
        n_probes = len(mb.probes)
        width = len(self._columns)
        upsert = self.mode == "upsert"
        tombs = ts.tombs_by_age
        eff: List[List[Tuple[int, int]]] = [
            [(0, 0)] * n_probes for _ in range(n_tiers)
        ]
        plan: List[Tuple[str, Tuple[int, ...]]] = [("none", ())] * n_probes
        for i in range(n_probes):
            live = [
                t for t in range(n_tiers) if per_tier[t][i][1] > per_tier[t][i][0]
            ]
            if not live:
                continue
            probe = mb.probes[i]
            full = len(probe) == width
            if tombs and full:
                # whole-tier age mask: the newest tombstone holding this
                # exact key erases every strictly older tier's rows
                shadow = ts.tomb_newest.get(probe, -1)
                if shadow >= 0:
                    live = [t for t in live if positions[t] >= shadow]
                    if not live:
                        continue
            elif tombs and any(tp > positions[live[0]] for tp, _ in tombs):
                # prefix probe with a tombstone newer than some matched
                # tier: individual keys may be shadowed — host key-merge
                for t in live:
                    eff[t][i] = per_tier[t][i]
                plan[i] = ("merge", tuple(live))
                continue
            if len(live) == 1 or (upsert and full):
                t = live[-1] if upsert else live[0]
                # single visible tier (or newest-wins point probe):
                # decode exactly one tier's range, shadowed rows never
                # leave the device/mirror
                eff[t][i] = per_tier[t][i]
                plan[i] = ("one", (t,))
            else:
                for t in live:
                    eff[t][i] = per_tier[t][i]
                kind = "concat" if full else "merge"
                plan[i] = (kind, tuple(live))
        decoded: List[Optional[List[List[Row]]]] = [None] * n_tiers
        for t in range(n_tiers):
            if any(hi > lo for lo, hi in eff[t]):
                decoded[t] = row_tiers[t]._impl.rows_for_bounds(eff[t])
        out: List[List[Row]] = []
        for i in range(n_probes):
            kind, live = plan[i]
            if kind == "none":
                out.append([])
            elif kind == "one":
                out.append(decoded[live[0]][i])
            elif kind == "concat":
                # full-width probe: every matched row carries the same
                # key, so tier order IS the rebuild's stable order
                rows: List[Row] = []
                for t in live:
                    rows.extend(decoded[t][i])
                out.append(rows)
            else:
                out.append(
                    _merge_blocks(
                        [(positions[t], decoded[t][i]) for t in live],
                        self._columns,
                        upsert,
                        tombs,
                    )
                )
        return out

    def find_rows_many(self, probes: Sequence[Sequence[str]]) -> List[List[Row]]:
        return self.rows_for_bounds(self.bounds_many(probes))

    def find_rows(self, values: Sequence[str]) -> List[Row]:
        return self.find_rows_many([values])[0]

    def has(self, values: Sequence[str]) -> bool:
        return bool(self.find_rows_many([values])[0])

    # -- writes (THREAD001 entries) ----------------------------------------

    def _build_delta_index(self, rows: List[Row]) -> Index:
        """One batch through the standard per-tier encode path — shared
        by the live append surface and WAL replay so a recovered tier
        is built exactly like the acked one was."""
        from ..columnar.ingest import source_from_table
        from ..columnar.table import DeviceTable

        table = DeviceTable.from_rows(rows, device=self._device)
        return create_index(source_from_table(table), self._columns)

    def _make_pruner(self, idx: Index) -> Optional[TierPruner]:
        """Fences + filter for a freshly sealed tier (None when pruning
        is disabled).  Runs at seal time, outside any reader path."""
        if not self._prune:
            return None
        return build_pruner(idx._impl, self._columns)

    def append_rows(self, rows: Sequence) -> int:
        """Append a batch of rows as one new delta tier.

        The batch columnarizes through ``DeviceTable.from_rows`` and
        the device ``create_index`` build — the same per-tier encode
        path every index rides — then lands as a sorted delta.  On a
        durable index the batch's WAL record is written (and under
        ``CSVPLUS_WAL_SYNC=always`` fsynced) BEFORE the tier becomes
        visible; a WAL failure acks nothing and changes nothing."""
        rows = [r if isinstance(r, Row) else Row(r) for r in rows]
        if not rows:
            return 0
        idx = self._build_delta_index(rows)
        self._push_delta(idx, [dict(r) for r in rows])
        return len(rows)

    def append_table(self, table) -> int:
        """Append an already-columnarized DeviceTable as one delta."""
        from ..columnar.ingest import source_from_table

        if table.nrows == 0:
            return 0
        idx = create_index(source_from_table(table), self._columns)
        self._push_delta(idx, None)
        return table.nrows

    def append_csv(self, path: str, *, device: Optional[str] = None, shards=None) -> int:
        """Append a CSV file through the staged streamed-ingest
        pipeline (``columnar/ingest.py`` tiers, K workers via
        ``CSVPLUS_INGEST_WORKERS``) — bitwise-identical deltas
        regardless of worker count, per the standing ingest contract."""
        from ..reader import from_file

        src = from_file(path).on_device(
            device if device is not None else (self._ingest_device or "cpu"),
            shards=shards,
        )
        idx = create_index(src, self._columns)
        n = len(idx._impl)
        if n == 0:
            return 0
        self._push_delta(idx, None)
        return n

    def delete(self, key: Sequence[str]) -> None:
        """Tombstone one full-width key: every currently visible row
        with this exact key disappears (in both visibility modes); rows
        appended afterwards are visible again.  Tombstones drop
        permanently at the next full merge.  Durable indexes write the
        tombstone's WAL record before it takes effect."""
        norm = (key,) if isinstance(key, str) else tuple(key)
        if len(norm) != len(self._columns):
            raise ValueError(
                f"delete() needs a full-width key ({len(self._columns)} "
                f"columns, got {len(norm)})"
            )
        sk = self.key_sketch
        if sk is not None:
            # a tombstone seal is build-side key traffic too
            sk.offer(norm[0] if len(norm) == 1 else norm)
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            if self._wal is not None:
                self._wal.append_record(
                    seq, {"lsn": seq, "op": "del", "key": list(norm)}
                )
            ts = self._tiers
            self._tiers = TierSet(
                ts.epoch + 1, ts.base,
                ts.deltas + (DeltaTier(seq, None, (norm,)),),
                base_pruner=ts.base_pruner,
            )
            for cb in self._listeners:
                cb(("tombs", seq, (norm,)))

    def wal_sync(self) -> Dict[str, int]:
        """Force buffered WAL records durable (the ``batch`` policy's
        ack barrier; cheap no-op shapes otherwise) and return the
        cycle-delta counters {records, bytes, fsyncs}.  The serving
        tier calls this once per dispatch cycle BEFORE completing
        append futures — the ack-after-fsync ordering."""
        w = self._wal
        if w is None:
            return {"records": 0, "bytes": 0, "fsyncs": 0}
        w.sync_now()
        return w.stats_delta()

    def close(self) -> None:
        """Flush and close the WAL (memory-only indexes: no-op)."""
        if self._wal is not None:
            self._wal.close()

    def _push_delta(self, idx: Index, wal_rows: Optional[List[Dict]]) -> None:
        if wal_rows is None and self._wal is not None:
            # append_table/append_csv: log the tier's own sorted rows
            # (replaying a stable sort of already-sorted rows rebuilds
            # the identical tier)
            wal_rows = [dict(r) for r in tier_rows(idx._impl)]
        sk = self.key_sketch
        if sk is not None:
            # build-side skew evidence, offered OUTSIDE the writer lock
            # (the sketch is its own monitor; order is immaterial)
            cols = self._columns
            rows = wal_rows if wal_rows is not None else tier_rows(idx._impl)
            if len(cols) == 1:
                col = cols[0]
                sk.offer_many(r.get(col) for r in rows)
            else:
                sk.offer_many(tuple(r.get(c) for c in cols) for r in rows)
        # no seal-time summary build: the first probe after the swap
        # pays the O(n) fence+filter scan once, via
        # DeltaTier.ensure_pruner — the write path stays scan-free
        with self._lock:
            seq = self._next_seq
            self._next_seq += 1
            if self._wal is not None:
                self._wal.append_record(
                    seq, {"lsn": seq, "op": "rows", "rows": wal_rows}
                )
            ts = self._tiers
            delta = DeltaTier(seq, idx)
            self._tiers = TierSet(ts.epoch + 1, ts.base, ts.deltas + (delta,),
                                  base_pruner=ts.base_pruner)
            for cb in self._listeners:
                cb(("rows", seq, idx))
        _flight.note(
            "storage:seal", seq=seq, rows=len(idx._impl),
            deltas=len(ts.deltas) + 1,
        )

    # -- compaction --------------------------------------------------------

    def compact_once(self) -> Optional[Dict[str, object]]:
        """Merge ALL current deltas into the base and swap the merged
        tier set in atomically (tombstones apply and then drop for
        good).  Returns merge stats, or None when there was nothing to
        compact.  On a durable index a successful full merge
        checkpoints (new base file + sealed WAL + manifest swap).

        Crash safety: the fault-injection site ``storage:compact``
        fires once on entry and once just before the swap; an
        exception at either point (or anywhere in the merge) leaves
        ``self._tiers`` untouched — the pre-compaction tier set stays
        live and a retry starts clean.  A crash DURING the checkpoint
        (after the in-memory swap) leaves the durable state stale but
        consistent: recovery replays the original WAL records and
        reaches the same logical stream.  Appends racing the merge are
        preserved: only the pinned snapshot's deltas are folded in,
        newer deltas carry over as the new tail."""
        faults.inject("storage:compact")
        with self._compact_lock:
            ts = self._tiers
            if not ts.deltas:
                return None
            return self._compact_full(ts)

    def compact_step(self, *, ratio: Optional[int] = None) -> Optional[Dict[str, object]]:
        """One pass of the size-ratio leveling policy: fold the oldest
        run of ≥ *ratio* same-level deltas into one merged delta (a
        PARTIAL merge — bounded write amplification, base untouched,
        no checkpoint), or escalate to a full merge once the delta
        mass reaches 1/*ratio* of the base.  Returns the pass's stats
        (``kind`` = ``partial`` | ``full``), or None when the policy
        finds nothing due.  *ratio* defaults to ``CSVPLUS_LSM_RATIO``
        (4)."""
        if ratio is None:
            ratio = env_int("CSVPLUS_LSM_RATIO", 4)
        if ratio < 2:
            raise ValueError("compact_step ratio must be >= 2")
        faults.inject("storage:compact")
        with self._compact_lock:
            ts = self._tiers
            from .compact import plan_compaction

            sel = plan_compaction(ts, ratio)
            if sel is None:
                return None
            kind, span = sel
            if kind == "full":
                return self._compact_full(ts)
            i, j = span
            return self._compact_partial(ts, i, j)

    def _compact_full(self, ts: TierSet) -> Dict[str, object]:
        """Full fold (caller holds ``_compact_lock``)."""
        from .compact import merge_units, units_of

        n_in = sum(len(ix._impl) for ix in ts.indexes())
        t0 = time.perf_counter()
        with telemetry.stage("storage:compact", n_in) as _t:
            merged, _ = merge_units(
                units_of(ts), self._columns, self.mode, drop_tombstones=True
            )
            _t["deltas"] = len(ts.deltas)
            # the pre-swap crash window: a compactor death AFTER the
            # merge but BEFORE the swap must also leave the old tier
            # set intact (chaos scenario `storage_compact_crash`)
            faults.inject("storage:compact")
            pruner = self._make_pruner(merged)  # outside the lock
            seconds = time.perf_counter() - t0
            with self._lock:
                cur = self._tiers
                self._tiers = TierSet(
                    cur.epoch + 1, merged, cur.deltas[len(ts.deltas):],
                    base_pruner=pruner,
                )
                self._compactions += 1
                self._compact_seconds += seconds
            _t["rows_out"] = len(merged._impl)
        if self._wal is not None:
            self._checkpoint(merged, ts.deltas[-1].seq, pruner)
        _flight.note(
            "storage:compact", mode="full", deltas=len(ts.deltas),
            rows_out=len(merged._impl), seconds=round(seconds, 6),
        )
        return {
            "kind": "full",
            "deltas": len(ts.deltas),
            "rows_in": n_in,
            "rows_out": len(merged._impl),
            "seconds": seconds,
            "epoch": self._tiers.epoch,
        }

    def _compact_partial(self, ts: TierSet, i: int, j: int) -> Dict[str, object]:
        """Merge the contiguous delta run [i, j) into ONE delta tier
        (caller holds ``_compact_lock``).  In-range shadowing applies
        (upsert dead groups and tombstoned rows drop); surviving
        tombstones ride the merged tier so out-of-range older tiers
        stay shadowed.  The base and the manifest are untouched —
        recovery replays the ORIGINAL records and reaches the same
        logical stream."""
        from .compact import delta_units, merge_units

        run = ts.deltas[i:j]
        n_in = sum(d.nrows for d in run)
        t0 = time.perf_counter()
        with telemetry.stage("storage:compact", n_in) as _t:
            merged, tombs = merge_units(
                delta_units(run), self._columns, self.mode,
                drop_tombstones=False,
            )
            _t["deltas"] = len(run)
            _t["kind"] = "partial"
            faults.inject("storage:compact")
            seconds = time.perf_counter() - t0
            n_out = len(merged._impl)
            pruner = self._make_pruner(merged) if n_out else None
            with self._lock:
                cur = self._tiers
                # appends only extend the tail and merges serialize on
                # _compact_lock, so cur.deltas[i:j] is still `run`
                if n_out or tombs:
                    new = (
                        DeltaTier(run[-1].seq, merged if n_out else None,
                                  tombs, pruner=pruner),
                    )
                else:
                    new = ()
                self._tiers = TierSet(
                    cur.epoch + 1, cur.base,
                    cur.deltas[:i] + new + cur.deltas[j:],
                    base_pruner=cur.base_pruner,
                )
                self._compactions += 1
                self._compact_seconds += seconds
            _t["rows_out"] = n_out
        _flight.note(
            "storage:compact", mode="partial", deltas=len(run),
            rows_out=n_out, seconds=round(seconds, 6),
        )
        return {
            "kind": "partial",
            "deltas": len(run),
            "rows_in": n_in,
            "rows_out": n_out,
            "seconds": seconds,
            "epoch": self._tiers.epoch,
        }

    def _checkpoint(self, merged: Index, applied_lsn: int,
                    pruner: Optional[TierPruner] = None) -> None:
        """Publish a full merge durably: persist the merged base
        (versioned ``write_to`` format) and its prune sidecar, seal
        the active WAL segment, swap the manifest atomically, then
        drop applied segments and stale files.  ``storage:manifest-swap``
        fires in the pre-rename (hit 0) and post-rename/pre-drop
        (hit 1) windows; ``storage:prune-sidecar`` fires before (hit 0)
        and after (hit 1) the sidecar write — a crash in ANY of these
        leaves the previous manifest live (orphaned base/sidecar files
        are swept on the next checkpoint) and recovers to the same
        logical stream."""
        from . import manifest as mf

        directory = self._dir
        with self._lock:
            ck = self._ckpt + 1
        base_name = f"base-{ck:08d}.idx"
        final = os.path.join(directory, base_name)
        tmp = final + ".tmp"
        merged.write_to(tmp)
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, final)
        prune_name = None
        if pruner is not None:
            prune_name = f"prune-{ck:08d}.flt"
            faults.inject("storage:prune-sidecar")
            write_pruner(os.path.join(directory, prune_name), pruner)
            faults.inject("storage:prune-sidecar")
        self._wal.seal_active()
        faults.inject("storage:manifest-swap")
        doc = mf.manifest_doc(
            mode=self.mode, key_columns=self._columns, checkpoint=ck,
            base=base_name, applied_lsn=int(applied_lsn),
            segments=self._wal.segment_names(), prune=prune_name,
        )
        mf.write_manifest(directory, doc)
        faults.inject("storage:manifest-swap")
        with self._lock:
            self._ckpt = ck
            self._applied_lsn = int(applied_lsn)
            self._base_file = base_name
        self._wal.drop_applied(int(applied_lsn))
        mf.remove_stale(directory, doc)
        _flight.note(
            "storage:checkpoint", checkpoint=ck,
            applied_lsn=int(applied_lsn), base=base_name,
        )

    def to_index(self) -> Index:
        """A frozen Index equal to fully compacting the CURRENT tier
        set, without swapping it in (the concurrent-read tests' frozen
        equivalent)."""
        from .compact import merge_units, units_of

        ts = self._tiers
        if not ts.deltas:
            return ts.base
        merged, _ = merge_units(
            units_of(ts), self._columns, self.mode, drop_tombstones=True
        )
        return merged


def _merge_blocks(
    tagged: List[Tuple[int, List[Row]]],
    key_cols: Sequence[str],
    upsert: bool,
    tombs: Sequence[Tuple[int, FrozenSet[tuple]]] = (),
) -> List[Row]:
    """Key-level merge of per-tier row blocks for one PREFIX probe.

    Each block is sorted by full key (it came out of a sorted tier) and
    tagged with its tier-stream position; a tombstone at position *tp*
    erases matching keys from strictly older blocks.  The rebuild's
    order for the surviving union is (key, tier, within-tier position),
    which a stable sort by key alone reproduces because the input list
    is built tier-by-tier in position order."""
    if tombs:
        filtered: List[Tuple[int, List[Row]]] = []
        for pos, rows in tagged:
            newer = [tset for tp, tset in tombs if tp > pos]
            if newer:
                rows = [
                    r for r in rows
                    if not any(
                        tuple(r[c] for c in key_cols) in tset for tset in newer
                    )
                ]
            filtered.append((pos, rows))
        tagged = filtered
    if upsert:
        newest: Dict[tuple, int] = {}
        for t, rows in tagged:
            for r in rows:
                newest[tuple(r[c] for c in key_cols)] = t
        tagged = [
            (t, [r for r in rows if newest[tuple(r[c] for c in key_cols)] == t])
            for t, rows in tagged
        ]
    items: List[Tuple[tuple, Row]] = []
    for t, rows in tagged:
        for r in rows:
            items.append((tuple(r[c] for c in key_cols), r))
    items.sort(key=lambda it: it[0])  # stable: ties keep (tier, pos) order
    return [r for _, r in items]
