"""LSM tier sets: delta tiers, epoch snapshots, multi-tier lookups.

Layout
------

A :class:`MutableIndex` is a **base** tier (an ordinary sorted
:class:`~csvplus_tpu.index.Index`) plus a tuple of **delta** tiers,
each itself a small sorted Index built from one append batch through
the existing encode path (``DeviceTable`` columnarization or the
staged streamed-ingest pipeline for ``append_csv``).  The logical row
stream is the concatenation base → delta0 → delta1 → … in append
order; every read answers as if that stream had been indexed from
scratch.

Visibility (``mode``)
---------------------

* ``"append"`` (default) — multiset appends: all tiers are visible,
  equal keys interleave in (key, tier, within-tier position) order —
  bitwise-identical to a from-scratch **stable** rebuild of the
  logical stream, because each tier is itself a stable sort of its
  batch.
* ``"upsert"`` — newest-wins: a key present in a newer tier shadows
  every older tier's rows for that key (whole key groups, so one
  append batch may still hold duplicates).  Equal to rebuilding after
  dropping each row whose full key reappears in any LATER tier.

Concurrency (the r10 epoch rule)
--------------------------------

All tier-list state lives in one immutable :class:`TierSet`; readers
pin it with a single attribute read (``self._tiers`` — atomic under
the GIL) and never take a lock on the probe hot path.  Writers
(``append_*`` / ``compact_once``) build a NEW TierSet and swap it
under ``self._lock``.  The compactor merges OUTSIDE the lock against
its pinned snapshot and swaps only the merged prefix, so appends
landing mid-merge survive as the new tier list's tail.  ``append_rows``
and ``compact_once`` are THREAD001 worker entries
(analysis/astlint.py): every shared-state mutation below them must sit
under a lock, with zero allowances.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..index import Index, create_index
from ..resilience import faults
from ..row import Row
from ..source import take_rows
from ..utils.observe import telemetry

__all__ = [
    "DeltaTier",
    "MutableIndex",
    "TierSet",
    "index_checksums",
    "rebuild_reference",
    "tier_rows",
]

_MODES = ("append", "upsert")


class DeltaTier:
    """One append batch, materialized as a small sorted Index."""

    __slots__ = ("seq", "index")

    def __init__(self, seq: int, index: Index):
        self.seq = seq
        self.index = index

    @property
    def nrows(self) -> int:
        return len(self.index._impl)

    def __repr__(self) -> str:  # debugging aid only
        return f"DeltaTier(seq={self.seq}, nrows={self.nrows})"


class TierSet:
    """Immutable snapshot of the tier list at one epoch.

    Readers that captured a TierSet keep answering from it even while
    a writer swaps in a successor — the old tiers stay alive (and
    correct) for as long as any reader holds them.
    """

    __slots__ = ("epoch", "base", "deltas")

    def __init__(self, epoch: int, base: Index, deltas: Tuple[DeltaTier, ...]):
        self.epoch = epoch
        self.base = base
        self.deltas = deltas

    def indexes(self) -> Tuple[Index, ...]:
        """All tiers oldest→newest (base first)."""
        return (self.base,) + tuple(d.index for d in self.deltas)


class MultiBounds:
    """Pinned tier set + per-tier bounds for one probe batch.

    Opaque handle between :meth:`MutableIndex.bounds_many` and
    :meth:`MutableIndex.rows_for_bounds` — pinning the TierSet here
    keeps the two phases epoch-consistent even when the compactor
    swaps between them (the serving tier calls them separately).
    """

    __slots__ = ("tiers", "per_tier", "probes")

    def __init__(self, tiers: TierSet, per_tier, probes):
        self.tiers = tiers
        self.per_tier = per_tier
        self.probes = probes


def tier_rows(impl) -> List[Row]:
    """Decode one tier's sorted rows WITHOUT flipping a device-lazy
    impl onto its host branch: touching ``impl.rows`` would cache host
    rows and permanently reroute ``bounds_many`` off the device path
    (the same trap HostLookupOracle documents)."""
    if impl._rows is None and impl.dev is not None:
        return impl.dev.table.to_rows()
    return impl.rows


def _logical_streams(ts: TierSet) -> List[List[Row]]:
    return [tier_rows(ix._impl) for ix in ts.indexes()]


def _upsert_filter(streams: List[List[Row]], key_cols: Sequence[str]) -> List[List[Row]]:
    """Drop every row whose full key appears in any LATER tier — the
    newest-wins rebuild rule, computed key-by-key on host rows
    (deliberately independent of the packed-key merge in compact.py so
    the parity harness cross-checks two implementations)."""
    newest: Dict[tuple, int] = {}
    for t, rows in enumerate(streams):
        for r in rows:
            newest[tuple(r[c] for c in key_cols)] = t
    return [
        [r for r in rows if newest[tuple(r[c] for c in key_cols)] == t]
        for t, rows in enumerate(streams)
    ]


def rebuild_reference(mindex: "MutableIndex", ts: Optional[TierSet] = None) -> Index:
    """From-scratch rebuild of the pinned tier set's logical rows —
    the parity harness's ground truth.  Routes through the HOST
    ``create_index`` build (stable Python sort over Row dicts), a
    completely separate code path from the compactor's packed
    searchsorted merge, so agreement is meaningful."""
    ts = ts if ts is not None else mindex.tiers()
    streams = _logical_streams(ts)
    if mindex.mode == "upsert":
        streams = _upsert_filter(streams, mindex.columns)
    rows = [Row(r) for s in streams for r in s]
    return create_index(take_rows(rows), mindex.columns)


def index_checksums(index: Index, columns: Optional[Sequence[str]] = None) -> Dict[str, int]:
    """Positional per-column checksums over an index's sorted rows —
    the differential-harness currency (utils/checksum.py), order-
    sensitive so tier-merge bugs that permute equal keys still trip."""
    from ..utils.checksum import checksum_host_rows

    rows = tier_rows(index._impl)
    if columns is None:
        seen = set()
        columns = []
        for r in rows:
            for c in r:
                if c not in seen:
                    seen.add(c)
                    columns.append(c)
        columns = sorted(columns)
    return checksum_host_rows(rows, columns, positional=True)


class MutableIndex:
    """LSM-style mutable index over the immutable lookup engine.

    Implements the lookup-impl protocol the serving tier consumes
    (``columns`` / ``bounds_many`` / ``rows_for_bounds`` /
    ``find_rows_many``) plus the write surface (``append_rows`` /
    ``append_table`` / ``append_csv`` / ``compact_once``), so a
    ``LookupServer`` can register one directly.
    """

    # lookup-protocol compatibility: the host-fallback oracle checks
    # ``impl.dev`` to decide whether it may reuse the impl directly —
    # a MutableIndex IS its own host-correct fallback
    dev = None

    def __init__(self, base: Index, *, mode: str = "append", ingest_device=None):
        if not isinstance(base, Index):
            raise TypeError("MutableIndex wraps an existing Index as its base tier")
        if mode not in _MODES:
            raise ValueError(f"unknown MutableIndex mode {mode!r} (use append|upsert)")
        self.mode = mode
        self._columns = list(base._impl.columns)
        impl = base._impl
        self._device = (
            impl.dev.table.device if impl.dev is not None else ingest_device
        )
        self._ingest_device = ingest_device
        self._lock = threading.Lock()
        # serializes whole compaction passes (snapshot -> merge -> swap):
        # the swap-prefix invariant assumes at most one in-flight merge
        self._compact_lock = threading.Lock()
        self._tiers = TierSet(0, base, ())
        self._next_seq = 1
        self._compactions = 0
        self._compact_seconds = 0.0

    @classmethod
    def create(cls, src, columns: Sequence[str], *, mode: str = "append", ingest_device=None) -> "MutableIndex":
        """Build the base tier with ``create_index`` and wrap it."""
        return cls(create_index(src, columns), mode=mode, ingest_device=ingest_device)

    # -- lookup-impl protocol ----------------------------------------------

    @property
    def _impl(self) -> "MutableIndex":
        # LookupServer unwraps ``index._impl``; a MutableIndex is its
        # own impl (bounds_many/rows_for_bounds below span all tiers)
        return self

    @property
    def columns(self) -> List[str]:
        return list(self._columns)

    @property
    def epoch(self) -> int:
        return self._tiers.epoch

    @property
    def delta_count(self) -> int:
        return len(self._tiers.deltas)

    def tiers(self) -> TierSet:
        """Pin the current tier-set epoch (one atomic read)."""
        return self._tiers

    def __len__(self) -> int:
        ts = self._tiers
        return sum(len(ix._impl) for ix in ts.indexes())

    def snapshot(self) -> Dict[str, object]:
        """JSON-safe accounting for metrics/bench artifacts."""
        ts = self._tiers
        with self._lock:
            compactions = self._compactions
            compact_s = self._compact_seconds
        return {
            "mode": self.mode,
            "epoch": ts.epoch,
            "base_rows": len(ts.base._impl),
            "deltas": len(ts.deltas),
            "delta_rows": sum(d.nrows for d in ts.deltas),
            "compactions": compactions,
            "compact_seconds_total": round(compact_s, 6),
        }

    # -- reads (no lock on this path) --------------------------------------

    def bounds_many(self, probes: Sequence[Sequence[str]]) -> MultiBounds:
        """Per-tier bounds for the whole probe batch: one vectorized
        ``bounds_many`` pass per tier (the existing multi-tier
        ``point_bounds_many`` machinery), pinned to one epoch."""
        norm = [(p,) if isinstance(p, str) else tuple(p) for p in probes]
        width = len(self._columns)
        for p in norm:
            if len(p) > width:
                raise ValueError("too many columns in Index.find()")
        ts = self._tiers
        per_tier = [ix._impl.bounds_many(norm) for ix in ts.indexes()]
        return MultiBounds(ts, per_tier, norm)

    def rows_for_bounds(self, mb: MultiBounds) -> List[List[Row]]:
        """Merge per-tier bounds into per-probe row blocks with ONE
        amortized gather-decode per tier (each tier's matched ranges
        decode together through its ``rows_for_bounds``).

        Fast paths: a probe matched by a single tier returns that
        tier's block directly; a full-width probe needs no key-level
        merge (all rows share one key — ``append`` concatenates in
        tier order, ``upsert`` decodes only the newest matching tier).
        Only multi-tier PREFIX probes pay the host key-merge."""
        tiers = mb.tiers.indexes()
        per_tier = mb.per_tier
        n_tiers = len(tiers)
        n_probes = len(mb.probes)
        width = len(self._columns)
        upsert = self.mode == "upsert"
        eff: List[List[Tuple[int, int]]] = [
            [(0, 0)] * n_probes for _ in range(n_tiers)
        ]
        plan: List[Tuple[str, Tuple[int, ...]]] = [("none", ())] * n_probes
        for i in range(n_probes):
            live = [
                t for t in range(n_tiers) if per_tier[t][i][1] > per_tier[t][i][0]
            ]
            if not live:
                continue
            if len(live) == 1 or (upsert and len(mb.probes[i]) == width):
                t = live[-1] if upsert else live[0]
                # single visible tier (or newest-wins point probe):
                # decode exactly one tier's range, shadowed rows never
                # leave the device/mirror
                eff[t][i] = per_tier[t][i]
                plan[i] = ("one", (t,))
            else:
                for t in live:
                    eff[t][i] = per_tier[t][i]
                kind = "concat" if len(mb.probes[i]) == width else "merge"
                plan[i] = (kind, tuple(live))
        decoded: List[Optional[List[List[Row]]]] = [None] * n_tiers
        for t in range(n_tiers):
            if any(hi > lo for lo, hi in eff[t]):
                decoded[t] = tiers[t]._impl.rows_for_bounds(eff[t])
        out: List[List[Row]] = []
        for i in range(n_probes):
            kind, live = plan[i]
            if kind == "none":
                out.append([])
            elif kind == "one":
                out.append(decoded[live[0]][i])
            elif kind == "concat":
                # full-width probe: every matched row carries the same
                # key, so tier order IS the rebuild's stable order
                rows: List[Row] = []
                for t in live:
                    rows.extend(decoded[t][i])
                out.append(rows)
            else:
                out.append(
                    _merge_blocks(
                        [(t, decoded[t][i]) for t in live],
                        self._columns,
                        upsert,
                    )
                )
        return out

    def find_rows_many(self, probes: Sequence[Sequence[str]]) -> List[List[Row]]:
        return self.rows_for_bounds(self.bounds_many(probes))

    def find_rows(self, values: Sequence[str]) -> List[Row]:
        return self.find_rows_many([values])[0]

    def has(self, values: Sequence[str]) -> bool:
        return bool(self.find_rows_many([values])[0])

    # -- writes (THREAD001 entries) ----------------------------------------

    def append_rows(self, rows: Sequence) -> int:
        """Append a batch of rows as one new delta tier.

        The batch columnarizes through ``DeviceTable.from_rows`` and
        the device ``create_index`` build — the same per-tier encode
        path every index rides — then lands as a sorted delta."""
        rows = [r if isinstance(r, Row) else Row(r) for r in rows]
        if not rows:
            return 0
        from ..columnar.ingest import source_from_table
        from ..columnar.table import DeviceTable

        table = DeviceTable.from_rows(rows, device=self._device)
        idx = create_index(source_from_table(table), self._columns)
        self._push_delta(idx)
        return len(rows)

    def append_table(self, table) -> int:
        """Append an already-columnarized DeviceTable as one delta."""
        from ..columnar.ingest import source_from_table

        if table.nrows == 0:
            return 0
        idx = create_index(source_from_table(table), self._columns)
        self._push_delta(idx)
        return table.nrows

    def append_csv(self, path: str, *, device: Optional[str] = None, shards=None) -> int:
        """Append a CSV file through the staged streamed-ingest
        pipeline (``columnar/ingest.py`` tiers, K workers via
        ``CSVPLUS_INGEST_WORKERS``) — bitwise-identical deltas
        regardless of worker count, per the standing ingest contract."""
        from ..reader import from_file

        src = from_file(path).on_device(
            device if device is not None else (self._ingest_device or "cpu"),
            shards=shards,
        )
        idx = create_index(src, self._columns)
        n = len(idx._impl)
        if n == 0:
            return 0
        self._push_delta(idx)
        return n

    def _push_delta(self, idx: Index) -> None:
        with self._lock:
            ts = self._tiers
            delta = DeltaTier(self._next_seq, idx)
            self._next_seq += 1
            self._tiers = TierSet(ts.epoch + 1, ts.base, ts.deltas + (delta,))

    def compact_once(self) -> Optional[Dict[str, object]]:
        """Merge the current deltas into the base and swap the merged
        tier set in atomically.  Returns merge stats, or None when
        there was nothing to compact.

        Crash safety: the fault-injection site ``storage:compact``
        fires once on entry and once just before the swap; an
        exception at either point (or anywhere in the merge) leaves
        ``self._tiers`` untouched — the pre-compaction tier set stays
        live and a retry starts clean.  Appends racing the merge are
        preserved: only the pinned snapshot's deltas are folded in,
        newer deltas carry over as the new tail."""
        faults.inject("storage:compact")
        with self._compact_lock:
            ts = self._tiers
            if not ts.deltas:
                return None
            from .compact import merge_tiers

            n_in = sum(len(ix._impl) for ix in ts.indexes())
            t0 = time.perf_counter()
            with telemetry.stage("storage:compact", n_in) as _t:
                merged = merge_tiers(list(ts.indexes()), self._columns, self.mode)
                _t["deltas"] = len(ts.deltas)
                # the pre-swap crash window: a compactor death AFTER the
                # merge but BEFORE the swap must also leave the old tier
                # set intact (chaos scenario `storage_compact_crash`)
                faults.inject("storage:compact")
                seconds = time.perf_counter() - t0
                with self._lock:
                    cur = self._tiers
                    self._tiers = TierSet(
                        cur.epoch + 1, merged, cur.deltas[len(ts.deltas):]
                    )
                    self._compactions += 1
                    self._compact_seconds += seconds
                _t["rows_out"] = len(merged._impl)
            return {
                "deltas": len(ts.deltas),
                "rows_in": n_in,
                "rows_out": len(merged._impl),
                "seconds": seconds,
                "epoch": self._tiers.epoch,
            }

    def to_index(self) -> Index:
        """A frozen Index equal to fully compacting the CURRENT tier
        set, without swapping it in (the concurrent-read tests' frozen
        equivalent)."""
        from .compact import merge_tiers

        ts = self._tiers
        if not ts.deltas:
            return ts.base
        return merge_tiers(list(ts.indexes()), self._columns, self.mode)


def _merge_blocks(
    tagged: List[Tuple[int, List[Row]]], key_cols: Sequence[str], upsert: bool
) -> List[Row]:
    """Key-level merge of per-tier row blocks for one PREFIX probe.

    Each block is sorted by full key (it came out of a sorted tier);
    the rebuild's order for the union is (key, tier, within-tier
    position), which a stable sort by key alone reproduces because the
    input list is built tier-by-tier in position order."""
    if upsert:
        newest: Dict[tuple, int] = {}
        for t, rows in tagged:
            for r in rows:
                newest[tuple(r[c] for c in key_cols)] = t
        tagged = [
            (t, [r for r in rows if newest[tuple(r[c] for c in key_cols)] == t])
            for t, rows in tagged
        ]
    items: List[Tuple[tuple, Row]] = []
    for t, rows in tagged:
        for r in rows:
            items.append((tuple(r[c] for c in key_cols), r))
    items.sort(key=lambda it: it[0])  # stable: ties keep (tier, pos) order
    return [r for _, r in items]
