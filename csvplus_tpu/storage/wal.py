"""Segmented write-ahead log for :class:`MutableIndex` durability.

Format
------

A WAL directory holds segments ``wal-00000001.log``, ``wal-00000002.log``,
… (monotonic, never reused).  Each segment starts with one JSON header
line (magic, version, segment number, key columns) followed by binary
records::

    [u32 length][u32 crc32][payload bytes]

The payload is one UTF-8 JSON document reusing the v1 JSONL row
encoding of :meth:`Index.write_to` (``json.dumps(row, sort_keys=True,
separators=(",", ":"))`` per row)::

    {"lsn": 17, "op": "rows", "rows": [{...}, ...]}
    {"lsn": 18, "op": "del",  "key": ["k003"]}

``lsn`` is the tier sequence number assigned by the owning
``MutableIndex`` — one logical stream position per append batch or
tombstone, strictly increasing across segments.  The crc32 is over the
payload bytes; a record whose length prefix or checksum does not match
is **torn**.  A torn record at the tail of the NEWEST segment is the
expected crash shape and replay truncates the file back to the last
good record; a torn record anywhere else is corruption and raises
:class:`WalError`.

Sync policy (``CSVPLUS_WAL_SYNC``)
----------------------------------

* ``always`` (default) — flush + ``os.fsync`` before every append
  returns: an acked record can never be lost, at one fsync per batch.
* ``batch`` — flush per append, fsync deferred to :meth:`sync_now`
  (the serving tier calls it once per dispatch cycle BEFORE completing
  futures, so acks still imply durability; a crash between cycles can
  lose only unacked records).
* ``off`` — flush only; durability is best-effort (crash window = OS
  page cache).  For bulk loads that re-run on failure.

Thread model: ``append_record`` / ``sync_now`` / ``seal_active`` are
THREAD001 worker entries — every mutation of WAL state sits under
``self._lock``.  The ``storage:wal-write`` fault site fires at the top
of ``append_record`` (a crashed write acks nothing) and ``seal_active``
(a crash mid-seal leaves the old active segment replayable).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Dict, List, Optional, Tuple

from ..errors import CsvPlusError
from ..obs import flight as _flight
from ..utils.env import env_int, env_str
from ..resilience import faults

__all__ = ["Wal", "WalError", "wal_sync_mode"]

_MAGIC = "csvplus-tpu-wal"
_VERSION = 1
_HDR = struct.Struct("<II")  # (payload length, payload crc32)
_SEG_FMT = "wal-%08d.log"
_SEG_PREFIX = "wal-"
_SEG_SUFFIX = ".log"
_MAX_RECORD = 1 << 31  # sanity bound: larger length prefixes are torn trash
_SYNC_MODES = ("always", "batch", "off")


class WalError(CsvPlusError):
    """Unrecoverable WAL damage: a torn record that is NOT the newest
    segment's tail, a bad segment header, or a non-monotonic LSN."""


def wal_sync_mode(explicit: Optional[str] = None) -> str:
    """Resolve the fsync policy: explicit argument beats the
    ``CSVPLUS_WAL_SYNC`` environment knob beats the ``always`` default.
    Unknown values raise (a typo'd durability knob must not silently
    weaken the ack contract the way a typo'd tuning knob may degrade)."""
    mode = explicit if explicit is not None else env_str(
        "CSVPLUS_WAL_SYNC", "always"
    )
    if mode not in _SYNC_MODES:
        raise ValueError(
            f"unknown CSVPLUS_WAL_SYNC mode {mode!r} (one of {_SYNC_MODES})"
        )
    return mode


def _fsync_dir(path: str) -> None:
    """Make a directory entry change (create/rename/unlink) durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _segment_path(directory: str, seg: int) -> str:
    return os.path.join(directory, _SEG_FMT % seg)


def _segment_number(name: str) -> Optional[int]:
    if not (name.startswith(_SEG_PREFIX) and name.endswith(_SEG_SUFFIX)):
        return None
    try:
        return int(name[len(_SEG_PREFIX):-len(_SEG_SUFFIX)])
    except ValueError:
        return None


def list_segments(directory: str) -> List[Tuple[int, str]]:
    """(segment number, file name) pairs present in *directory*, sorted."""
    out = []
    for name in os.listdir(directory):
        n = _segment_number(name)
        if n is not None:
            out.append((n, name))
    out.sort()
    return out


def _scan_segment(path: str, is_last: bool) -> Tuple[List[Dict], int, bool]:
    """Decode one segment: (records, keep_bytes, torn).

    *keep_bytes* is the offset of the first torn byte (== file size when
    clean); *torn* reports whether a damaged tail was found.  Damage in
    a non-last segment raises :class:`WalError` — records there were
    sealed behind an fsync, so a bad checksum is disk corruption, not a
    crash shape."""
    records: List[Dict] = []
    with open(path, "rb") as f:
        header_line = f.readline()
        offset = len(header_line)
        try:
            header = json.loads(header_line)
            ok = header.get("magic") == _MAGIC and header.get("version") == _VERSION
        except (json.JSONDecodeError, UnicodeDecodeError):
            ok = False
        if not ok:
            if is_last:
                # crash during segment creation: the header itself is
                # torn — recover by rewriting the segment from scratch
                return [], 0, True
            raise WalError(f"{path}: bad WAL segment header")
        while True:
            hdr = f.read(_HDR.size)
            if not hdr:
                return records, offset, False
            if len(hdr) < _HDR.size:
                break
            length, crc = _HDR.unpack(hdr)
            if length > _MAX_RECORD:
                break
            payload = f.read(length)
            if len(payload) < length or zlib.crc32(payload) != crc:
                break
            try:
                doc = json.loads(payload.decode("utf-8"))
            except (json.JSONDecodeError, UnicodeDecodeError):
                break
            records.append(doc)
            offset += _HDR.size + length
    if not is_last:
        raise WalError(f"{path}: torn record in a sealed WAL segment")
    return records, offset, True


class Wal:
    """One directory's segmented write-ahead log.

    Create fresh with :meth:`create`, or :meth:`open` an existing
    directory to replay its tail (returning the decoded records newer
    than the manifest's ``applied_lsn``).  All public methods are safe
    to call from the appender and compactor threads concurrently.
    """

    def __init__(self, directory: str, *, sync: Optional[str] = None,
                 columns: Optional[List[str]] = None,
                 segment_bytes: Optional[int] = None):
        self.directory = directory
        self.sync = wal_sync_mode(sync)
        self._columns = list(columns or [])
        if segment_bytes is None:
            segment_bytes = env_int("CSVPLUS_WAL_SEGMENT_BYTES", 8 << 20)
        self._segment_bytes = int(segment_bytes)
        # reentrant: the public entries hold it across the internal
        # roll/open/drop helpers, which retake it for their own
        # mutations (THREAD001 wants every store lexically guarded)
        self._lock = threading.RLock()
        self._f = None  # active segment file object
        self._seg = 0  # active segment number
        self._size = 0  # active segment bytes (append-mode tell() lies)
        self._seg_records = 0  # records in the active segment
        self._seg_max_lsn: Dict[int, int] = {}  # per-segment newest lsn
        self._last_lsn = 0
        # cycle-delta counters consumed by MutableIndex.wal_sync()
        self._bytes_total = 0
        self._fsyncs_total = 0
        self._records_total = 0
        self._reported = (0, 0, 0)

    # -- construction ------------------------------------------------------

    @classmethod
    def create(cls, directory: str, *, sync: Optional[str] = None,
               columns: Optional[List[str]] = None,
               segment_bytes: Optional[int] = None) -> "Wal":
        """Start a fresh log: segment 1, empty, header fsynced."""
        w = cls(directory, sync=sync, columns=columns,
                segment_bytes=segment_bytes)
        with w._lock:
            w._open_segment(1)
        return w

    @classmethod
    def open(cls, directory: str, applied_lsn: int, *,
             sync: Optional[str] = None, columns: Optional[List[str]] = None,
             segment_bytes: Optional[int] = None) -> Tuple["Wal", List[Dict], Dict]:
        """Recover: scan every segment in order, truncate a torn tail in
        the newest one, drop segments wholly covered by *applied_lsn*,
        and return ``(wal, records_to_replay, info)``.

        *records_to_replay* are the decoded payload docs with
        ``lsn > applied_lsn`` in LSN order; *info* reports what recovery
        did (for metrics and the chaos artifact)."""
        w = cls(directory, sync=sync, columns=columns,
                segment_bytes=segment_bytes)
        segments = list_segments(directory)
        replay: List[Dict] = []
        truncated = 0
        removed: List[str] = []
        last_lsn = int(applied_lsn)
        last_seg_records = 0
        with w._lock:
            for pos, (seg, name) in enumerate(segments):
                path = os.path.join(directory, name)
                is_last = pos == len(segments) - 1
                records, keep, torn = _scan_segment(path, is_last)
                if is_last:
                    last_seg_records = len(records)
                if torn:
                    size = os.path.getsize(path)
                    truncated = size - keep
                    with open(path, "r+b") as f:
                        f.truncate(keep)
                        f.flush()
                        os.fsync(f.fileno())
                seg_max = int(applied_lsn)
                for doc in records:
                    lsn = int(doc["lsn"])
                    seg_max = max(seg_max, lsn)
                    if lsn <= applied_lsn:
                        continue
                    if lsn <= last_lsn:
                        raise WalError(
                            f"{path}: non-monotonic LSN {lsn} after {last_lsn}"
                        )
                    last_lsn = lsn
                    replay.append(doc)
                w._seg_max_lsn[seg] = seg_max
            w._last_lsn = last_lsn
            if segments:
                # reopen the newest segment for appends; rewrite its
                # header if the torn tail swallowed it entirely
                seg, name = segments[-1]
                path = os.path.join(directory, name)
                if os.path.getsize(path) == 0:
                    os.unlink(path)
                    w._open_segment(seg)
                else:
                    w._seg = seg
                    w._f = open(path, "ab")
                    w._size = os.path.getsize(path)
                    w._seg_records = last_seg_records
            else:
                w._open_segment(1)
            w._drop_applied_locked(int(applied_lsn), removed)
        info = {
            "replayed": len(replay),
            "truncated_bytes": int(truncated),
            "removed_segments": removed,
            "segments": [name for _, name in list_segments(directory)],
        }
        _flight.note(
            "wal:recover", replayed=len(replay),
            truncated_bytes=int(truncated), segments=len(info["segments"]),
        )
        return w, replay, info

    # -- internals (caller holds self._lock) -------------------------------

    def _open_segment(self, seg: int) -> None:
        path = _segment_path(self.directory, seg)
        f = open(path, "xb")
        header = json.dumps(
            {"magic": _MAGIC, "version": _VERSION, "segment": seg,
             "key_columns": self._columns},
            sort_keys=True, separators=(",", ":"),
        )
        f.write(header.encode("utf-8"))
        f.write(b"\n")
        f.flush()
        os.fsync(f.fileno())
        _fsync_dir(self.directory)
        with self._lock:
            self._f = f
            self._seg = seg
            self._size = f.tell()
            self._seg_records = 0
            self._seg_max_lsn.setdefault(seg, self._last_lsn)

    def _roll_locked(self) -> None:
        with self._lock:
            f = self._f
            f.flush()
            os.fsync(f.fileno())
            self._fsyncs_total += 1
            f.close()
            self._open_segment(self._seg + 1)

    def _drop_applied_locked(self, applied_lsn: int, removed: List[str]) -> None:
        with self._lock:
            for seg, name in list_segments(self.directory):
                if seg == self._seg:
                    continue
                if self._seg_max_lsn.get(seg, applied_lsn + 1) <= applied_lsn:
                    os.unlink(os.path.join(self.directory, name))
                    self._seg_max_lsn.pop(seg, None)
                    removed.append(name)
            if removed:
                _fsync_dir(self.directory)

    # -- THREAD001 worker entries ------------------------------------------

    def append_record(self, lsn: int, doc: Dict) -> int:
        """Write one length-prefixed, crc32-checksummed record.  Under
        ``always`` the record is fsynced before return; the caller may
        ack.  Returns the bytes appended."""
        faults.inject("storage:wal-write")
        payload = json.dumps(doc, sort_keys=True, separators=(",", ":")).encode(
            "utf-8"
        )
        frame = _HDR.pack(len(payload), zlib.crc32(payload)) + payload
        with self._lock:
            if self._f is None:
                raise WalError("WAL is closed")
            if int(lsn) <= self._last_lsn:
                raise WalError(
                    f"non-monotonic LSN {lsn} after {self._last_lsn}"
                )
            if self._size + len(frame) > self._segment_bytes and self._seg_records:
                # roll only a segment that already holds records — an
                # oversized single record still lands (in its own file)
                self._roll_locked()
            self._f.write(frame)
            self._size += len(frame)
            self._seg_records += 1
            self._f.flush()
            if self.sync == "always":
                os.fsync(self._f.fileno())
                self._fsyncs_total += 1
            self._last_lsn = int(lsn)
            self._seg_max_lsn[self._seg] = int(lsn)
            self._bytes_total += len(frame)
            self._records_total += 1
        return len(frame)

    def sync_now(self) -> None:
        """Force the active segment durable (the ``batch`` policy's
        per-cycle hook; a no-op under ``off``)."""
        with self._lock:
            if self._f is None or self.sync == "off":
                return
            self._f.flush()
            os.fsync(self._f.fileno())
            self._fsyncs_total += 1

    def seal_active(self) -> str:
        """Fsync + close the active segment and open the next one (the
        checkpoint boundary).  Returns the new active segment name."""
        faults.inject("storage:wal-write")
        with self._lock:
            if self._f is None:
                raise WalError("WAL is closed")
            self._roll_locked()
            name = _SEG_FMT % self._seg
        _flight.note("wal:seal", segment=name)
        return name

    def drop_applied(self, applied_lsn: int) -> List[str]:
        """Delete sealed segments wholly covered by *applied_lsn* (their
        records are folded into the persisted base)."""
        removed: List[str] = []
        with self._lock:
            self._drop_applied_locked(int(applied_lsn), removed)
        return removed

    # -- accounting --------------------------------------------------------

    def stats_delta(self) -> Dict[str, int]:
        """Counters accumulated since the previous call — the serving
        tier folds one delta per dispatch cycle into ServingMetrics."""
        with self._lock:
            cur = (self._records_total, self._bytes_total, self._fsyncs_total)
            prev = self._reported
            self._reported = cur
        return {
            "records": cur[0] - prev[0],
            "bytes": cur[1] - prev[1],
            "fsyncs": cur[2] - prev[2],
        }

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "sync": self.sync,
                "segment": self._seg,
                "last_lsn": self._last_lsn,
                "records": self._records_total,
                "bytes": self._bytes_total,
                "fsyncs": self._fsyncs_total,
            }

    def segment_names(self) -> List[str]:
        with self._lock:
            return [name for _, name in list_segments(self.directory)]

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.flush()
                if self.sync != "off":
                    os.fsync(self._f.fileno())
                    self._fsyncs_total += 1
                self._f.close()
                self._f = None
