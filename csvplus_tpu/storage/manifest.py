"""Atomic durability manifest for a :class:`MutableIndex` directory.

``MANIFEST.json`` is the single source of truth for recovery: which
persisted base tier file is current, the newest LSN folded into it
(``applied_lsn``), the visibility mode and key columns, and the WAL
segments live at the last checkpoint.  It is replaced atomically —
write to a temp name, flush, fsync, ``os.replace``, fsync the directory
— so a reader either sees the old manifest or the new one, never a torn
in-between.  The ``storage:manifest-swap`` fault site brackets the
rename in ``MutableIndex._checkpoint`` (not here): hit 0 is the
post-merge/pre-rename crash window, hit 1 the post-rename/pre-WAL-drop
window; both recover to the same logical stream.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Sequence

from ..errors import CsvPlusError

__all__ = ["MANIFEST_NAME", "ManifestError", "read_manifest", "write_manifest"]

MANIFEST_NAME = "MANIFEST.json"
_MAGIC = "csvplus-tpu-manifest"
_VERSION = 1


class ManifestError(CsvPlusError):
    """Missing, torn, or version-incompatible MANIFEST.json."""


def manifest_doc(
    *,
    mode: str,
    key_columns: Sequence[str],
    checkpoint: int,
    base: str,
    applied_lsn: int,
    segments: Sequence[str],
    prune: Optional[str] = None,
) -> Dict[str, object]:
    """Assemble the versioned manifest document.  ``prune`` names the
    base tier's fence/filter sidecar (``prune-%08d.flt``), or None when
    the checkpoint was taken with pruning disabled — recovery then
    rebuilds summaries by scan."""
    doc: Dict[str, object] = {
        "magic": _MAGIC,
        "version": _VERSION,
        "mode": mode,
        "key_columns": list(key_columns),
        "checkpoint": int(checkpoint),
        "base": base,
        "applied_lsn": int(applied_lsn),
        "segments": list(segments),
    }
    if prune is not None:
        doc["prune"] = prune
    return doc


def write_manifest(directory: str, doc: Dict[str, object]) -> str:
    """Atomically publish *doc* as the directory's manifest."""
    final = os.path.join(directory, MANIFEST_NAME)
    tmp = final + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f, sort_keys=True, separators=(",", ":"))
        f.write("\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    fd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    return final


def read_manifest(directory: str) -> Dict[str, object]:
    """Load and validate the manifest; raises :class:`ManifestError`
    when the directory has none (or an unreadable one)."""
    path = os.path.join(directory, MANIFEST_NAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            doc = json.load(f)
    except FileNotFoundError:
        raise ManifestError(
            f"{directory}: no {MANIFEST_NAME} (not a durable MutableIndex "
            f"directory — create one with MutableIndex.create(..., "
            f"directory=...))"
        ) from None
    except (json.JSONDecodeError, UnicodeDecodeError) as err:
        raise ManifestError(f"{path}: unreadable manifest ({err})") from None
    if not isinstance(doc, dict) or doc.get("magic") != _MAGIC:
        raise ManifestError(f"{path}: not a csvplus-tpu manifest")
    if doc.get("version") != _VERSION:
        raise ManifestError(
            f"{path}: unsupported manifest version {doc.get('version')}"
        )
    for field in ("mode", "key_columns", "checkpoint", "base", "applied_lsn"):
        if field not in doc:
            raise ManifestError(f"{path}: manifest missing {field!r}")
    return doc


def stale_files(directory: str, doc: Dict[str, object]) -> List[str]:
    """Leftovers a crash may strand: ``*.tmp`` staging files and base
    tier files the manifest no longer references.  WAL segments are NOT
    listed — the WAL's own ``drop_applied`` owns their lifecycle."""
    keep = {MANIFEST_NAME, str(doc["base"])}
    if doc.get("prune"):
        keep.add(str(doc["prune"]))
    out: List[str] = []
    for name in os.listdir(directory):
        if name.endswith(".tmp"):
            out.append(name)
        elif name.startswith("base-") and name not in keep:
            out.append(name)
        elif name.startswith("prune-") and name not in keep:
            out.append(name)
    return sorted(out)


def remove_stale(directory: str, doc: Dict[str, object]) -> List[str]:
    """Delete crash leftovers (janitor half of recovery); returns what
    was removed."""
    removed = stale_files(directory, doc)
    for name in removed:
        os.unlink(os.path.join(directory, name))
    if removed:
        fd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    return removed
