"""Tier compaction: stable multi-way merge, leveling, the compactor.

The merge is grounded in the cache-efficient sorting design of the
Data-Parallel Graphics (DPG) line (arxiv cs/0308004): instead of a
naive k-way heap merge (one cache-hostile pointer chase per row), every
pass below is a **vectorized sequential sweep** over sorted arrays —
``np.union1d`` for the dictionary unions, one translation gather per
tier per column, and one ``np.searchsorted`` per tier pair to place
rows.  For tier *t*'s row *i* (packed key *k*), the merged position is

    pos = i + Σ_{u<t} searchsorted_right(keys_u, k)
            + Σ_{u>t} searchsorted_left(keys_u, k)

which reproduces the STABLE order of sorting the concatenated logical
stream (older tiers win ties), so the merged index is bitwise-equal to
a from-scratch rebuild — the parity contract the differential harness
enforces at every compaction step.  Tombstones ride the same packed
comparison: a tombstone unit's keys pack into the union code space and
two searchsorted probes mask every strictly OLDER unit's matching rows.
The final materialization is one permuted concat per column, landed on
device with a single ``device_put`` (no jitted kernels: compaction
cannot perturb the warm-lookup zero-recompile gate).  When shadowing
dropped rows, each column's union dictionary is pruned to the codes the
survivors actually reference — the r10 upsert dead-group fix: a merged
base no longer carries dictionary entries only dead groups used.

Tiers that cannot ride the packed path (host-only tiers, typed
``IntColumn`` columns, non-bytes dictionaries, or a >62-bit union key
space with upsert shadowing or tombstones in play) fall back to a
host-row merge that is correct by construction (the same event replay
``rebuild_reference`` performs, then a stable sort).

Leveling (:func:`plan_compaction`) gives sustained append load bounded
write amplification: instead of folding ALL deltas into the base every
pass, same-sized delta runs fold into one another (size-ratio levels,
default ``CSVPLUS_LSM_RATIO=4``) and only a delta mass within one ratio
of the base triggers the full fold.  Each level merge is the same
snapshot-swap + searchsorted path — no new kernels, no recompiles.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..index import Index, IndexImpl
from ..row import Row
from ..utils.env import env_float
from ..utils.observe import telemetry
from .lsm import DeltaTier, MutableIndex, TierSet, _upsert_filter, tier_rows

__all__ = ["Compactor", "merge_tiers", "merge_units", "plan_compaction"]

# a merge unit is (impl-or-None, tombstone key tuple): one tier's rows
# and/or deletes at one stream position, oldest -> newest
Unit = Tuple[Optional[object], Tuple[Tuple[str, ...], ...]]


def units_of(ts: TierSet) -> List[Unit]:
    """Full tier set as merge units (base first, no base tombstones)."""
    return [(ts.base._impl, ())] + delta_units(ts.deltas)


def delta_units(deltas: Sequence[DeltaTier]) -> List[Unit]:
    return [
        ((d.index._impl if d.index is not None else None), d.tombs)
        for d in deltas
    ]


def merge_tiers(
    tiers: Sequence[Index], key_columns: Sequence[str], mode: str = "append"
) -> Index:
    """Merge sorted *tiers* (oldest→newest) into one sorted Index,
    bitwise-equal to rebuilding from the concatenated logical rows.
    (Tombstone-free compatibility wrapper around :func:`merge_units`.)"""
    merged, _ = merge_units(
        [(t._impl, ()) for t in tiers], key_columns, mode,
        drop_tombstones=True,
    )
    return merged


def merge_units(
    units: Sequence[Unit],
    key_columns: Sequence[str],
    mode: str = "append",
    *,
    drop_tombstones: bool,
) -> Tuple[Index, Tuple[Tuple[str, ...], ...]]:
    """Merge *units* (oldest→newest) into one sorted Index plus the
    surviving tombstone set.

    A unit's tombstones erase matching full keys from every strictly
    OLDER unit (its own rows were appended after its deletes and stay).
    ``drop_tombstones=True`` is the full merge into the base — nothing
    older remains, so tombstones are spent and the survivors are ``()``.
    ``drop_tombstones=False`` is a partial (level) merge — every unit
    tombstone survives onto the merged tier, because tiers older than
    the merged range still need shadowing."""
    key_columns = list(key_columns)
    units = list(units)
    n_total = sum(
        len(impl) for impl, _ in units if impl is not None  # type: ignore[arg-type]
    )
    with telemetry.stage("storage:merge", n_total) as _t:
        merged = _merge_device(units, key_columns, mode)
        _t["path"] = "device" if merged is not None else "host"
        _t["tiers"] = len(units)
        if merged is None:
            merged = _merge_host(units, key_columns, mode)
        _t["rows_out"] = len(merged._impl)
    if drop_tombstones:
        survivors: Tuple[Tuple[str, ...], ...] = ()
    else:
        survivors = tuple(
            sorted(set(k for _, tombs in units for k in tombs))
        )
    return merged, survivors


def _translate_host(col, union: np.ndarray, n: int) -> np.ndarray:
    """One tier column's codes in the union dictionary's code space —
    a host translation gather over the cached code mirror (negative
    codes pass through: -1 absent stays -1)."""
    codes = col.codes_host()
    if codes.shape[0] != n:
        codes = codes[:n]
    codes = codes.astype(np.int64)
    d = col.dictionary
    if d.size == 0:
        return codes  # no real values: every code is already negative
    trans = np.searchsorted(union, d).astype(np.int64)
    return np.where(codes >= 0, trans[np.clip(codes, 0, d.size - 1)], codes)


def _pack_tomb_keys(
    tombs: Sequence[Tuple[str, ...]],
    key_unions: List[np.ndarray],
    shifts: List[int],
) -> np.ndarray:
    """Tombstone keys in the packed union code space, sorted.  A key
    value absent from its column's union matches no in-range row and is
    simply skipped here (the tombstone itself still survives a partial
    merge for out-of-range shadowing)."""
    out: List[int] = []
    for key in tombs:
        packed = 0
        present = True
        for v, u, sh in zip(key, key_unions, shifts):
            b = v.encode("utf-8") if isinstance(v, str) else bytes(v)
            code = int(np.searchsorted(u, b))
            if code >= u.size or u[code] != b:
                present = False
                break
            packed |= code << sh
        if present:
            out.append(packed)
    return np.array(sorted(out), dtype=np.int64)


def _merge_device(
    units: List[Unit], key_columns: List[str], mode: str
) -> Optional[Index]:
    """The packed searchsorted merge; None when any tier/column cannot
    ride it (the caller then takes the host-row path)."""
    import jax

    from ..columnar.table import DeviceTable, StringColumn
    from ..ops.join import DeviceIndex, _bits_for

    row_pos: List[int] = []
    tables = []
    for p, (impl, _) in enumerate(units):
        if impl is None:
            continue
        if impl.dev is None:
            return None
        row_pos.append(p)
        tables.append(impl.dev.table)
    tomb_units = [(p, tombs) for p, (_, tombs) in enumerate(units) if tombs]
    if not tables:
        # nothing but tombstones: the merged tier carries no rows
        return Index(IndexImpl([], key_columns))
    for t in tables:
        for c in t.columns.values():
            if not isinstance(c, StringColumn):
                return None  # typed columns merge on the host path
    names: List[str] = []
    seen = set()
    for t in tables:
        for n in t.columns:
            if n not in seen:
                seen.add(n)
                names.append(n)
    n_rows = [t.nrows for t in tables]
    n_tiers = len(tables)

    # union dictionary per column: one sorted-union pass each.  (A
    # device-lane dictionary settles to its host form here; keeping the
    # merge lane-native is the open half of ROADMAP item 5's note.)
    unions: Dict[str, np.ndarray] = {}
    for name in names:
        dicts = [
            np.asarray(t.columns[name].dictionary)
            for t in tables
            if name in t.columns
        ]
        if any(d.dtype.kind != "S" for d in dicts):
            return None  # non-bytes dictionary: host path
        u = dicts[0]
        for d in dicts[1:]:
            u = np.union1d(u, d)
        unions[name] = u

    key_unions = [unions[c] for c in key_columns]
    bits = [_bits_for(u.size) for u in key_unions]
    packed: Optional[List[np.ndarray]] = None
    shifts: List[int] = []
    if sum(bits) <= 62:
        acc = 0
        for b in reversed(bits):
            shifts.insert(0, acc)
            acc += b
        packed = []
        for t in range(n_tiers):
            k = np.zeros(n_rows[t], dtype=np.int64)
            for c, u, sh in zip(key_columns, key_unions, shifts):
                # key cells are never absent (create_index validated),
                # so the translated codes are all >= 0 and pack cleanly
                k |= _translate_host(tables[t].columns[c], u, n_rows[t]) << sh
            packed.append(k)
    elif mode == "upsert" or tomb_units:
        return None  # per-key shadowing needs the packed comparison

    keep: Optional[List[np.ndarray]] = None
    if mode == "upsert":
        # newest-wins: drop tier t's row when its key appears in ANY
        # newer tier — two searchsorted sweeps per (t, newer) pair
        keep = [np.ones(n_rows[t], dtype=bool) for t in range(n_tiers)]
        for t in range(n_tiers):
            for u_t in range(t + 1, n_tiers):
                lo = np.searchsorted(packed[u_t], packed[t], side="left")
                hi = np.searchsorted(packed[u_t], packed[t], side="right")
                keep[t] &= hi == lo
    if tomb_units and packed is not None:
        # a tombstone unit at position q masks matching rows in every
        # strictly older row unit — same two-probe membership sweep
        if keep is None:
            keep = [np.ones(n_rows[t], dtype=bool) for t in range(n_tiers)]
        for q, tombs in tomb_units:
            tk = _pack_tomb_keys(tombs, key_unions, shifts)
            if tk.size == 0:
                continue
            for t in range(n_tiers):
                if row_pos[t] >= q:
                    continue
                lo = np.searchsorted(tk, packed[t], side="left")
                hi = np.searchsorted(tk, packed[t], side="right")
                keep[t] &= hi == lo

    if packed is not None:
        kept = [
            packed[t][keep[t]] if keep is not None else packed[t]
            for t in range(n_tiers)
        ]
        total = sum(k.size for k in kept)
        g = np.empty(total, dtype=np.int64)
        off = 0
        for t in range(n_tiers):
            pos = np.arange(kept[t].size, dtype=np.int64)
            for u_t in range(n_tiers):
                if u_t == t:
                    continue
                side = "right" if u_t < t else "left"
                pos += np.searchsorted(kept[u_t], kept[t], side=side)
            if keep is not None:
                src = np.flatnonzero(keep[t]).astype(np.int64) + off
            else:
                src = np.arange(n_rows[t], dtype=np.int64) + off
            g[pos] = src
            off += n_rows[t]
    else:
        # >62-bit union key space, pure append, no tombstones: stable
        # lexsort over the translated key-code matrix — same order
        cat_keys = [
            np.concatenate(
                [
                    _translate_host(tables[t].columns[c], u, n_rows[t])
                    for t in range(n_tiers)
                ]
            )
            for c, u in zip(key_columns, key_unions)
        ]
        g = np.lexsort(tuple(reversed(cat_keys)))
        total = int(g.size)

    if total == 0:
        # mirror create_index: an empty result is a host-backed empty
        # index (no device build over zero rows)
        return Index(IndexImpl([], key_columns))

    # rows were dropped (upsert shadowing / tombstones): union
    # dictionary entries only dead rows referenced must not ride into
    # the merged tier — prune to the codes the survivors reference,
    # order-preserving so sortedness and the code order both hold
    prune = keep is not None
    device = tables[0].device
    cols: Dict[str, StringColumn] = {}
    for name in names:
        u = unions[name]
        parts = []
        for t in range(n_tiers):
            col = tables[t].columns.get(name)
            if col is None:
                parts.append(np.full(n_rows[t], -1, dtype=np.int64))
            else:
                parts.append(_translate_host(col, u, n_rows[t]))
        cg = np.concatenate(parts)[g]
        if prune and u.size:
            used = np.unique(cg[cg >= 0])
            if used.size < u.size:
                cg = np.where(cg >= 0, np.searchsorted(used, cg), cg)
                u = u[used]
        cols[name] = StringColumn(
            u, jax.device_put(cg.astype(np.int32), device)
        )
    out_table = DeviceTable(cols, int(total), device)
    dev = DeviceIndex.build(out_table, key_columns)
    return Index(IndexImpl(None, key_columns, dev=dev))


def _merge_host(units: List[Unit], key_columns: List[str], mode: str) -> Index:
    """Correct-by-construction fallback: replay tier events in order
    (a unit's tombstones erase matching keys from everything older,
    then its rows append), apply newest-wins, stable host sort —
    create_index's own ordering over the surviving logical stream."""
    streams: List[List[Row]] = []
    for impl, tombs in units:
        if tombs:
            dead = set(tombs)
            streams = [
                [
                    r for r in rows
                    if tuple(r[c] for c in key_columns) not in dead
                ]
                for rows in streams
            ]
        streams.append(tier_rows(impl) if impl is not None else [])
    if mode == "upsert":
        streams = _upsert_filter(streams, key_columns)
    rows = [Row(r) for s in streams for r in s]
    rows.sort(key=lambda r: tuple(r[c] for c in key_columns))  # stable
    return Index(IndexImpl(rows, key_columns))


def _tier_level(nrows: int, ratio: int) -> int:
    """Size-ratio level: how many times *nrows* divides by *ratio*
    (level 0 = a fresh append batch, each level up is ~ratio× larger)."""
    lvl = 0
    n = int(nrows)
    while n >= ratio:
        n //= ratio
        lvl += 1
    return lvl


def plan_compaction(
    ts: TierSet, ratio: int
) -> Optional[Tuple[str, Tuple[int, int]]]:
    """The size-ratio leveling policy's next move for *ts*, or None.

    Returns ``("full", (0, len(deltas)))`` when the total delta row
    mass is within one *ratio* of the base (folding everything in is
    then amortized), else ``("partial", (i, j))`` for the OLDEST
    contiguous run of at least *ratio* same-level row tiers (pure
    tombstone tiers are levelless and absorb into any run; a run of
    ≥ 2 tombstone-only tiers folds on its own).  Each delta is merged
    O(log_ratio(n)) times before reaching the base — bounded write
    amplification under sustained append load."""
    deltas = ts.deltas
    if not deltas:
        return None
    total = sum(d.nrows for d in deltas)
    if total * ratio >= max(len(ts.base._impl), 1):
        return ("full", (0, len(deltas)))

    start = 0
    cur_lvl: Optional[int] = None  # run's row-tier level (None: tombs only)
    count = 0  # row tiers in the current run
    for idx, d in enumerate(deltas):
        lvl = None if d.index is None else _tier_level(d.nrows, ratio)
        extends = (
            idx == start
            or lvl is None
            or cur_lvl is None
            or lvl == cur_lvl
        )
        if not extends:
            if count >= ratio or (count == 0 and idx - start >= 2):
                return ("partial", (start, idx))
            start = idx
            cur_lvl = None
            count = 0
        if lvl is not None:
            if cur_lvl is None:
                cur_lvl = lvl
            count += 1
    end = len(deltas)
    if count >= ratio or (count == 0 and end - start >= 2):
        return ("partial", (start, end))
    return None


class Compactor:
    """Background compaction thread over one :class:`MutableIndex`.

    ``policy="full"`` folds every delta into the base each pass (the
    r10 behaviour); ``policy="leveled"`` runs the size-ratio policy —
    :meth:`MutableIndex.compact_step` — for bounded write amplification
    under sustained appends; ``policy="readamp"`` (ISSUE 11) schedules
    from OBSERVED read amplification: each pass drains the index's
    :class:`~csvplus_tpu.storage.lsm.ReadAmpTracker` window and
    compacts only while the mean tiers-probed-per-lookup exceeds
    ``readamp_target`` (default ``CSVPLUS_LSM_READAMP_TARGET`` = 4.0)
    — a leveled step first, escalating to a full fold when the ratio
    policy finds nothing due but lookups still pay too many tiers.
    With fences+filters pruning most tiers, a cold tier that no lookup
    ever touches never forces a merge — compaction work tracks what
    readers actually pay, not raw tier counts.

    ``_compact_loop`` is a THREAD001 worker entry: all Compactor state
    mutates under ``self._lock``; the index's own swap discipline lives
    in :meth:`MutableIndex.compact_once`.  A failed pass (including an
    injected ``storage:compact`` fault) leaves the tier set untouched
    and is retried on the next interval — compaction is idempotent
    from any crash point before the swap.
    """

    def __init__(
        self,
        index: MutableIndex,
        *,
        min_deltas: int = 1,
        interval_s: float = 0.02,
        metrics=None,
        index_name: str = "default",
        policy: str = "full",
        ratio: Optional[int] = None,
        readamp_target: Optional[float] = None,
    ):
        if min_deltas < 1:
            raise ValueError("min_deltas must be >= 1")
        if policy not in ("full", "leveled", "readamp"):
            raise ValueError(f"unknown Compactor policy {policy!r}")
        self.index = index
        self.min_deltas = int(min_deltas)
        self.interval_s = float(interval_s)
        self.policy = policy
        self.ratio = ratio
        if readamp_target is None:
            readamp_target = env_float("CSVPLUS_LSM_READAMP_TARGET", 4.0)
        self.readamp_target = float(readamp_target)
        if self.readamp_target < 1.0:
            raise ValueError("readamp_target must be >= 1.0")
        self.last_readamp: Optional[float] = None
        self._metrics = metrics
        self._name = index_name
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compactions = 0
        self.failures = 0
        self.last_error: Optional[BaseException] = None
        self.last_stats: Optional[Dict[str, object]] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Compactor":
        if self._thread is not None:
            return self
        self._stop.clear()
        t = threading.Thread(
            target=self._compact_loop, name="csvplus-storage-compact", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "Compactor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- one pass ----------------------------------------------------------

    def run_once(self) -> Optional[Dict[str, object]]:
        """One compaction pass (also the unit tests' direct entry).
        Exceptions propagate to the caller; the loop catches them."""
        if self.policy == "readamp":
            stats = self._readamp_pass()
        elif self.policy == "leveled":
            stats = self.index.compact_step(ratio=self.ratio)
        else:
            stats = self.index.compact_once()
        if stats is not None:
            with self._lock:
                self.compactions += 1
                self.last_stats = stats
            m = self._metrics
            if m is not None:
                m.on_compact(
                    self._name,
                    int(stats["deltas"]),
                    int(stats["rows_out"]),
                    float(stats["seconds"]),
                    deltas_live=self.index.delta_count,
                )
        return stats

    def _readamp_pass(self) -> Optional[Dict[str, object]]:
        """One read-amp-driven pass: drain the observation window; when
        the mean tiers-probed exceeds the target, run one leveled step
        (bounded write amplification), escalating to a full fold when
        the size-ratio policy has nothing due but readers still pay.
        No lookups since the last pass -> no evidence -> no work."""
        mean = self.index.readamp.take_window()
        if mean is not None:
            with self._lock:
                self.last_readamp = mean
        if mean is None or mean <= self.readamp_target:
            return None
        stats = self.index.compact_step(ratio=self.ratio)
        if stats is None:
            stats = self.index.compact_once()
        return stats

    def _compact_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.index.delta_count >= self.min_deltas:
                    self.run_once()
            except Exception as err:
                # retryable by design: every crash point before the
                # swap leaves the pre-compaction tier set live, so the
                # next interval simply tries again — record and report
                with self._lock:
                    self.failures += 1
                    self.last_error = err
                sys.stderr.write(
                    f"csvplus-storage: compaction pass failed "
                    f"({type(err).__name__}: {err}); tier set unchanged, "
                    f"retrying next interval\n"
                )
            self._stop.wait(self.interval_s)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "policy": self.policy,
                "readamp_target": self.readamp_target,
                "last_readamp": (
                    round(self.last_readamp, 3)
                    if self.last_readamp is not None
                    else None
                ),
                "compactions": self.compactions,
                "failures": self.failures,
                "last_error": (
                    None
                    if self.last_error is None
                    else f"{type(self.last_error).__name__}: {self.last_error}"
                ),
                "last_stats": dict(self.last_stats) if self.last_stats else None,
            }
