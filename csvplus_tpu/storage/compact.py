"""Tier compaction: stable multi-way merge + the background compactor.

The merge is grounded in the cache-efficient sorting design of the
Data-Parallel Graphics (DPG) line (arxiv cs/0308004): instead of a
naive k-way heap merge (one cache-hostile pointer chase per row), every
pass below is a **vectorized sequential sweep** over sorted arrays —
``np.union1d`` for the dictionary unions, one translation gather per
tier per column, and one ``np.searchsorted`` per tier pair to place
rows.  For tier *t*'s row *i* (packed key *k*), the merged position is

    pos = i + Σ_{u<t} searchsorted_right(keys_u, k)
            + Σ_{u>t} searchsorted_left(keys_u, k)

which reproduces the STABLE order of sorting the concatenated logical
stream (older tiers win ties), so the merged index is bitwise-equal to
a from-scratch rebuild — the parity contract the differential harness
enforces at every compaction step.  The final materialization is one
permuted concat per column, landed on device with a single
``device_put`` (no jitted kernels: compaction cannot perturb the
warm-lookup zero-recompile gate).

Tiers that cannot ride the packed path (host-only tiers, typed
``IntColumn`` columns, non-bytes dictionaries, or a >62-bit union key
space in ``upsert`` mode) fall back to a host-row merge that is
correct by construction (stable sort of the same logical stream).
"""

from __future__ import annotations

import sys
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..index import Index, IndexImpl
from ..row import Row
from ..utils.observe import telemetry
from .lsm import MutableIndex, _upsert_filter, tier_rows

__all__ = ["Compactor", "merge_tiers"]


def merge_tiers(
    tiers: Sequence[Index], key_columns: Sequence[str], mode: str = "append"
) -> Index:
    """Merge sorted *tiers* (oldest→newest) into one sorted Index,
    bitwise-equal to rebuilding from the concatenated logical rows."""
    key_columns = list(key_columns)
    impls = [t._impl for t in tiers]
    n_total = sum(len(i) for i in impls)
    with telemetry.stage("storage:merge", n_total) as _t:
        merged = _merge_device(impls, key_columns, mode)
        _t["path"] = "device" if merged is not None else "host"
        _t["tiers"] = len(impls)
        if merged is None:
            merged = _merge_host(impls, key_columns, mode)
        _t["rows_out"] = len(merged._impl)
    return merged


def _translate_host(col, union: np.ndarray, n: int) -> np.ndarray:
    """One tier column's codes in the union dictionary's code space —
    a host translation gather over the cached code mirror (negative
    codes pass through: -1 absent stays -1)."""
    codes = col.codes_host()
    if codes.shape[0] != n:
        codes = codes[:n]
    codes = codes.astype(np.int64)
    d = col.dictionary
    if d.size == 0:
        return codes  # no real values: every code is already negative
    trans = np.searchsorted(union, d).astype(np.int64)
    return np.where(codes >= 0, trans[np.clip(codes, 0, d.size - 1)], codes)


def _merge_device(impls, key_columns: List[str], mode: str) -> Optional[Index]:
    """The packed searchsorted merge; None when any tier/column cannot
    ride it (the caller then takes the host-row path)."""
    import jax

    from ..columnar.table import DeviceTable, StringColumn
    from ..ops.join import DeviceIndex, _bits_for

    tables = []
    for impl in impls:
        if impl.dev is None:
            return None
        tables.append(impl.dev.table)
    for t in tables:
        for c in t.columns.values():
            if not isinstance(c, StringColumn):
                return None  # typed columns merge on the host path
    names: List[str] = []
    seen = set()
    for t in tables:
        for n in t.columns:
            if n not in seen:
                seen.add(n)
                names.append(n)
    n_rows = [t.nrows for t in tables]
    n_tiers = len(tables)

    # union dictionary per column: one sorted-union pass each.  (A
    # device-lane dictionary settles to its host form here; keeping the
    # merge lane-native is the open half of ROADMAP item 5's note.)
    unions: Dict[str, np.ndarray] = {}
    for name in names:
        dicts = [
            np.asarray(t.columns[name].dictionary)
            for t in tables
            if name in t.columns
        ]
        if any(d.dtype.kind != "S" for d in dicts):
            return None  # non-bytes dictionary: host path
        u = dicts[0]
        for d in dicts[1:]:
            u = np.union1d(u, d)
        unions[name] = u

    key_unions = [unions[c] for c in key_columns]
    bits = [_bits_for(u.size) for u in key_unions]
    packed: Optional[List[np.ndarray]] = None
    if sum(bits) <= 62:
        shifts: List[int] = []
        acc = 0
        for b in reversed(bits):
            shifts.insert(0, acc)
            acc += b
        packed = []
        for t in range(n_tiers):
            k = np.zeros(n_rows[t], dtype=np.int64)
            for c, u, sh in zip(key_columns, key_unions, shifts):
                # key cells are never absent (create_index validated),
                # so the translated codes are all >= 0 and pack cleanly
                k |= _translate_host(tables[t].columns[c], u, n_rows[t]) << sh
            packed.append(k)
    elif mode == "upsert":
        return None  # per-key shadowing needs the packed comparison

    keep: Optional[List[np.ndarray]] = None
    if mode == "upsert":
        # newest-wins: drop tier t's row when its key appears in ANY
        # newer tier — two searchsorted sweeps per (t, newer) pair
        keep = [np.ones(n_rows[t], dtype=bool) for t in range(n_tiers)]
        for t in range(n_tiers):
            for u_t in range(t + 1, n_tiers):
                lo = np.searchsorted(packed[u_t], packed[t], side="left")
                hi = np.searchsorted(packed[u_t], packed[t], side="right")
                keep[t] &= hi == lo

    if packed is not None:
        kept = [
            packed[t][keep[t]] if keep is not None else packed[t]
            for t in range(n_tiers)
        ]
        total = sum(k.size for k in kept)
        g = np.empty(total, dtype=np.int64)
        off = 0
        for t in range(n_tiers):
            pos = np.arange(kept[t].size, dtype=np.int64)
            for u_t in range(n_tiers):
                if u_t == t:
                    continue
                side = "right" if u_t < t else "left"
                pos += np.searchsorted(kept[u_t], kept[t], side=side)
            if keep is not None:
                src = np.flatnonzero(keep[t]).astype(np.int64) + off
            else:
                src = np.arange(n_rows[t], dtype=np.int64) + off
            g[pos] = src
            off += n_rows[t]
    else:
        # >62-bit union key space: stable lexsort over the translated
        # key-code matrix — same order, no packing
        cat_keys = [
            np.concatenate(
                [
                    _translate_host(tables[t].columns[c], u, n_rows[t])
                    for t in range(n_tiers)
                ]
            )
            for c, u in zip(key_columns, key_unions)
        ]
        g = np.lexsort(tuple(reversed(cat_keys)))
        total = int(g.size)

    if total == 0:
        # mirror create_index: an empty result is a host-backed empty
        # index (no device build over zero rows)
        return Index(IndexImpl([], key_columns))

    device = tables[0].device
    cols: Dict[str, StringColumn] = {}
    for name in names:
        u = unions[name]
        parts = []
        for t in range(n_tiers):
            col = tables[t].columns.get(name)
            if col is None:
                parts.append(np.full(n_rows[t], -1, dtype=np.int32))
            else:
                parts.append(
                    _translate_host(col, u, n_rows[t]).astype(np.int32)
                )
        cat = np.concatenate(parts)
        cols[name] = StringColumn(u, jax.device_put(cat[g], device))
    out_table = DeviceTable(cols, int(total), device)
    dev = DeviceIndex.build(out_table, key_columns)
    return Index(IndexImpl(None, key_columns, dev=dev))


def _merge_host(impls, key_columns: List[str], mode: str) -> Index:
    """Correct-by-construction fallback: stable host sort over the
    cloned logical row stream (create_index's own ordering)."""
    streams = [tier_rows(i) for i in impls]
    if mode == "upsert":
        streams = _upsert_filter(streams, key_columns)
    rows = [Row(r) for s in streams for r in s]
    rows.sort(key=lambda r: tuple(r[c] for c in key_columns))  # stable
    return Index(IndexImpl(rows, key_columns))


class Compactor:
    """Background compaction thread over one :class:`MutableIndex`.

    ``_compact_loop`` is a THREAD001 worker entry: all Compactor state
    mutates under ``self._lock``; the index's own swap discipline lives
    in :meth:`MutableIndex.compact_once`.  A failed pass (including an
    injected ``storage:compact`` fault) leaves the tier set untouched
    and is retried on the next interval — compaction is idempotent
    from any crash point before the swap.
    """

    def __init__(
        self,
        index: MutableIndex,
        *,
        min_deltas: int = 1,
        interval_s: float = 0.02,
        metrics=None,
        index_name: str = "default",
    ):
        if min_deltas < 1:
            raise ValueError("min_deltas must be >= 1")
        self.index = index
        self.min_deltas = int(min_deltas)
        self.interval_s = float(interval_s)
        self._metrics = metrics
        self._name = index_name
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.compactions = 0
        self.failures = 0
        self.last_error: Optional[BaseException] = None
        self.last_stats: Optional[Dict[str, object]] = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Compactor":
        if self._thread is not None:
            return self
        self._stop.clear()
        t = threading.Thread(
            target=self._compact_loop, name="csvplus-storage-compact", daemon=True
        )
        self._thread = t
        t.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join()
            self._thread = None

    def __enter__(self) -> "Compactor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- one pass ----------------------------------------------------------

    def run_once(self) -> Optional[Dict[str, object]]:
        """One compaction pass (also the unit tests' direct entry).
        Exceptions propagate to the caller; the loop catches them."""
        stats = self.index.compact_once()
        if stats is not None:
            with self._lock:
                self.compactions += 1
                self.last_stats = stats
            m = self._metrics
            if m is not None:
                m.on_compact(
                    self._name,
                    int(stats["deltas"]),
                    int(stats["rows_out"]),
                    float(stats["seconds"]),
                    deltas_live=self.index.delta_count,
                )
        return stats

    def _compact_loop(self) -> None:
        while not self._stop.is_set():
            try:
                if self.index.delta_count >= self.min_deltas:
                    self.run_once()
            except Exception as err:
                # retryable by design: every crash point before the
                # swap leaves the pre-compaction tier set live, so the
                # next interval simply tries again — record and report
                with self._lock:
                    self.failures += 1
                    self.last_error = err
                sys.stderr.write(
                    f"csvplus-storage: compaction pass failed "
                    f"({type(err).__name__}: {err}); tier set unchanged, "
                    f"retrying next interval\n"
                )
            self._stop.wait(self.interval_s)

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {
                "compactions": self.compactions,
                "failures": self.failures,
                "last_error": (
                    None
                    if self.last_error is None
                    else f"{type(self.last_error).__name__}: {self.last_error}"
                ),
                "last_stats": dict(self.last_stats) if self.last_stats else None,
            }
