"""Mesh + sharding helpers.

One flat data axis ("shards") is the natural mesh for a columnar ETL
engine: rows are the only dimension that scales.  Collectives ride ICI
within a slice; a future multi-slice mesh would add a DCN axis and keep
the same named-sharding code (XLA routes per-axis).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shards"


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first *n_devices* devices (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def shard_rows(mesh: Mesh, x: "jax.Array | np.ndarray") -> jax.Array:
    """Place *x* row-sharded over the mesh (dim 0 split across shards)."""
    return jax.device_put(x, NamedSharding(mesh, P(AXIS)))


def replicate(mesh: Mesh, x: "jax.Array | np.ndarray") -> jax.Array:
    """Place *x* fully replicated over the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))


def pad_to_multiple(x: np.ndarray, n: int, fill) -> "tuple[np.ndarray, int]":
    """Pad dim 0 up to a multiple of *n*; returns (padded, original_len)."""
    m = x.shape[0]
    rem = (-m) % n
    if rem == 0:
        return x, m
    pad = np.full((rem,) + x.shape[1:], fill, dtype=x.dtype)
    return np.concatenate([x, pad]), m
