"""Mesh + sharding helpers.

One flat data axis ("shards") is the natural mesh for a columnar ETL
engine: rows are the only dimension that scales.  Collectives ride ICI
within a slice.  For multi-slice deployments :func:`make_mesh_2d` adds
an outer "slice" axis modelling DCN between slices: row shardings then
split over BOTH axes (slice-major), so intra-slice traffic stays on ICI
and only slice-crossing collectives touch DCN — XLA routes per-axis.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "shards"
SLICE_AXIS = "slice"


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """A 1-D mesh over the first *n_devices* devices (default: all)."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            devices = devices[:n_devices]
    return Mesh(np.array(devices), (AXIS,))


def make_mesh_2d(
    n_slices: int, chips_per_slice: int, devices: Optional[Sequence] = None
) -> Mesh:
    """A (slice, chip) mesh: the outer axis models DCN between slices,
    the inner axis ICI within a slice.  ``row_spec(mesh)`` shardings
    split rows over both axes, slice-major."""
    if devices is None:
        devices = jax.devices()
    devices = np.array(devices[: n_slices * chips_per_slice])
    return Mesh(devices.reshape(n_slices, chips_per_slice), (SLICE_AXIS, AXIS))


def row_spec(mesh: Mesh) -> P:
    """The PartitionSpec splitting dim 0 over ALL mesh axes (1-D mesh:
    plain row sharding; 2-D: slice-major over (slice, chip))."""
    return P(tuple(mesh.axis_names))


def shard_rows(mesh: Mesh, x: "jax.Array | np.ndarray") -> jax.Array:
    """Place *x* row-sharded over the mesh (dim 0 split across every
    mesh axis)."""
    return jax.device_put(x, NamedSharding(mesh, row_spec(mesh)))


def replicate(mesh: Mesh, x: "jax.Array | np.ndarray") -> jax.Array:
    """Place *x* fully replicated over the mesh."""
    return jax.device_put(x, NamedSharding(mesh, P()))

