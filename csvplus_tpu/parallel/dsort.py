"""Distributed sample-sort over a device mesh (explicit, all_to_all).

The single-chip index build sorts code arrays with one ``lax.sort``
(ops/sort.py); over a GSPMD-sharded array XLA lowers that to a gather —
correct, but the whole array lands on every chip.  This module is the
explicit scale-out path (SURVEY §2 "distributed index build"): a classic
sample-sort whose only cross-chip traffic is one slot-aligned
``lax.all_to_all`` per lane, the same exchange shape the partitioned
join uses (pjoin.py).

Algorithm (SPMD under ``shard_map``, static shapes):

1. each shard sorts its local block (``lax.sort``);
2. every shard contributes an evenly-spaced sample of its block; an
   ``all_gather`` + sort of the (tiny) sample pool yields N-1 global
   splitters — the classic equal-depth histogram estimate;
3. each element routes to ``searchsorted(splitters, x)``; a stable sort
   by destination + rank scatter fills an ``(N, C)`` slot buffer that one
   ``all_to_all`` redistributes (payload rides a second lane);
4. each shard sorts what it received; sentinel padding sorts to the end.

The result is *range-partitioned and locally sorted*: shard i holds keys
``splitters[i-1] <= k < splitters[i]`` in sorted order — globally sorted
in shard-major read order, and exactly the layout the partitioned join's
build side wants.  Capacity ``C`` is a static parameter; skewed inputs
overflow (detected on device, -1 slot count) and the host wrapper
retries with doubled capacity, mirroring ``partitioned_probe``.

Differential-tested against ``np.sort`` on the 8-device CPU mesh,
including heavy-skew inputs that exercise the retry
(tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

try:  # moved out of experimental in newer jax
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import pad_to_multiple, row_spec

_SENT = np.int32(np.iinfo(np.int32).max)


def _dsort_shard_kernel(
    n_shards: int, capacity: int, samples: int, axes, x, payload, n_true
):
    """Per-shard body: local sort, splitter estimate, route, exchange,
    local sort of the received block.

    Validity is tracked explicitly (an extra exchanged lane) rather than
    by a sentinel VALUE, so INT32_MAX is an ordinary sortable key: the
    host wrapper's padding is identified by global row position >=
    *n_true*, and within the final per-shard sort invalid entries order
    after every valid entry of the same key.
    """
    m = x.shape[0]
    N, C, S = n_shards, capacity, samples

    # global positions identify the wrapper's tail padding; the row dim
    # shards over the axes in mesh-major order (mesh.row_spec)
    flat = jnp.int32(0)
    for ax in axes:
        flat = flat * lax.axis_size(ax) + lax.axis_index(ax)
    my_pos = flat * m + jnp.arange(m, dtype=jnp.int32)
    valid_in = (my_pos < n_true[0]).astype(jnp.int32)

    # 1. local sort (payload + validity ride along; invalid last per key)
    x_s, inv_s, p_s = lax.sort(
        (x, 1 - valid_in, payload), num_keys=2, is_stable=True
    )
    v_s = 1 - inv_s

    # 2. evenly-spaced local sample -> replicated pool -> global splitters
    step = jnp.maximum(m // S, 1)
    take = jnp.minimum(
        jnp.arange(S, dtype=jnp.int32) * step + step // 2, m - 1
    )
    local_sample = jnp.take(x_s, take, axis=0)
    pool = lax.all_gather(local_sample, axes[0], tiled=True)
    for ax in axes[1:]:
        pool = lax.all_gather(pool, ax, tiled=True)
    pool = lax.sort(pool)
    total = pool.shape[0]
    # N-1 equal-depth splitters; shard i owns [splitters[i-1], splitters[i])
    cut = jnp.arange(1, N, dtype=jnp.int32) * (total // N)
    splitters = jnp.take(pool, cut, axis=0)

    # 3. route by destination range (invalid rows go nowhere: dest N)
    dest = jnp.searchsorted(splitters, x_s, side="right").astype(jnp.int32)
    dest = jnp.where(v_s > 0, dest, N)
    pos = jnp.arange(m, dtype=jnp.int32)
    dest_s, x_r, p_r = lax.sort((dest, x_s, p_s), num_keys=1, is_stable=True)
    routed = dest_s < N
    group_start = jnp.searchsorted(
        dest_s, jnp.arange(N + 1, dtype=jnp.int32), side="left"
    )
    rank = pos - group_start[dest_s]
    ok = routed & (rank < C)  # overflow -> counts lane -1, caller retries

    buf_x = jnp.zeros((N, C), jnp.int32)
    buf_p = jnp.zeros((N, C), jnp.int32)
    buf_v = jnp.zeros((N, C), jnp.int32)
    slot = jnp.where(ok, rank, C)
    safe_dest = jnp.minimum(dest_s, N - 1)
    buf_x = buf_x.at[safe_dest, slot].set(x_r, mode="drop")
    buf_p = buf_p.at[safe_dest, slot].set(p_r, mode="drop")
    buf_v = buf_v.at[safe_dest, slot].set(1, mode="drop")
    overflow = jnp.any(routed & (rank >= C))

    # 4. one exchange per lane; then sort the received block (invalid
    # slots order last: sort key (valid-inverted, x) puts every real
    # element first regardless of value — INT32_MAX included)
    recv_x = lax.all_to_all(buf_x, axes, split_axis=0, concat_axis=0, tiled=True)
    recv_p = lax.all_to_all(buf_p, axes, split_axis=0, concat_axis=0, tiled=True)
    recv_v = lax.all_to_all(buf_v, axes, split_axis=0, concat_axis=0, tiled=True)
    rx = recv_x.reshape(-1)
    rp = recv_p.reshape(-1)
    rv = recv_v.reshape(-1)
    inv, out_x, out_p = lax.sort((1 - rv, rx, rp), num_keys=2, is_stable=True)
    n_here = jnp.sum(rv)
    # all-overflow report rides the counts lane as -1
    n_here = jnp.where(overflow, jnp.int32(-1), n_here)
    return out_x, out_p, n_here.reshape(1)


@partial(jax.jit, static_argnames=("mesh", "n_shards", "capacity", "samples"))
def _dsort_spmd(mesh, n_shards, capacity, samples, x, payload, n_true):
    axes = tuple(mesh.axis_names)
    rows = P(axes)
    f = shard_map(
        partial(_dsort_shard_kernel, n_shards, capacity, samples, axes),
        mesh=mesh,
        in_specs=(rows, rows, P()),
        out_specs=(rows, rows, rows),
    )
    return f(x, payload, n_true)


def distributed_sort(
    mesh: Mesh,
    values: np.ndarray,
    payload: "np.ndarray | None" = None,
    capacity: "int | None" = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Globally sort an int32 value array (with an optional int32 payload
    permuted alongside) using the explicit sample-sort.

    Host-facing wrapper: pads to the mesh size, runs the SPMD kernel,
    retries on capacity overflow, and stitches the per-shard sorted
    ranges back into one host array.  Returns ``(sorted_values,
    permuted_payload)``; when *payload* is None it is the sort
    permutation (original indices).
    """
    n_shards = mesh.devices.size
    values = np.asarray(values)
    if values.dtype != np.int32:
        # wide (packed int64) keys need a dual-lane exchange like the
        # partitioned probe's; refuse loudly rather than truncate
        raise TypeError(
            f"distributed_sort: int32 values required, got {values.dtype}"
        )
    n = values.shape[0]
    if payload is None:
        payload = np.arange(n, dtype=np.int32)
    payload = np.asarray(payload)
    if payload.dtype != np.int32:
        # same contract as the keys: refuse loudly rather than truncate
        raise TypeError(
            f"distributed_sort: int32 payload required, got {payload.dtype}"
        )
    if n == 0:
        return values, payload
    x, _ = pad_to_multiple(values, n_shards, _SENT)
    p, _ = pad_to_multiple(payload, n_shards, np.int32(-1))
    m_per_shard = x.shape[0] // n_shards
    if capacity is None:
        # balanced routing sends ~m_per_shard/N to each destination; the
        # retry doubles toward the guaranteed-sufficient m_per_shard
        capacity = max(64, 4 * ((m_per_shard + n_shards - 1) // n_shards))
    capacity = 1 << (int(capacity) - 1).bit_length()
    capacity = min(capacity, 1 << (max(m_per_shard, 1) - 1).bit_length())
    samples = min(64, max(8, m_per_shard))

    rows = NamedSharding(mesh, row_spec(mesh))
    repl = NamedSharding(mesh, P())
    x_dev = jax.device_put(x, rows)
    p_dev = jax.device_put(p, rows)
    n_dev = jax.device_put(np.array([n], dtype=np.int32), repl)
    while True:
        out_x, out_p, counts = _dsort_spmd(
            mesh, n_shards, capacity, samples, x_dev, p_dev, n_dev
        )
        counts_np = np.asarray(counts)
        if not (counts_np < 0).any():
            break
        if capacity >= m_per_shard:
            # C = m_per_shard always suffices (a source shard cannot send
            # more rows than it holds), so this is unreachable — guard
            # against a logic regression rather than a data shape
            raise RuntimeError("distributed_sort: capacity overflow at maximum")
        capacity *= 2
    # stitch: shard i's first counts[i] slots are its sorted range
    ox = np.asarray(out_x).reshape(n_shards, -1)
    op = np.asarray(out_p).reshape(n_shards, -1)
    vals = np.concatenate([ox[i, : counts_np[i]] for i in range(n_shards)])
    pays = np.concatenate([op[i, : counts_np[i]] for i in range(n_shards)])
    assert vals.shape[0] == n, (vals.shape[0], n)
    return vals, pays
