"""Distributed sample-sort over a device mesh (explicit, all_to_all).

The single-chip index build sorts code arrays with one ``lax.sort``
(ops/sort.py); over a GSPMD-sharded array XLA lowers that to a gather —
correct, but the whole array lands on every chip.  This module is the
explicit scale-out path (SURVEY §2 "distributed index build"): a classic
sample-sort whose only cross-chip traffic is one slot-aligned
``lax.all_to_all`` per lane, the same exchange shape the partitioned
join uses (pjoin.py).  ``ops/sort.py:sort_table`` routes mesh-sharded
tables through it (packed key codes as the sort key, the row
permutation as payload), so ``IndexOn``/``UniqueIndexOn`` over a
sharded table never replicate the full array.

Algorithm (SPMD under ``shard_map``, static shapes):

1. each shard sorts its local block (``lax.sort``);
2. every shard contributes an evenly-spaced sample of its block; an
   ``all_gather`` + sort of the (tiny) sample pool yields N-1 global
   splitters — the classic equal-depth histogram estimate;
3. each element routes to ``searchsorted(splitters, x)``; a stable sort
   by destination + rank scatter fills an ``(N, C)`` slot buffer that one
   ``all_to_all`` redistributes (payload and validity ride extra lanes);
4. each shard sorts what it received; invalid slots sort to the end.

The result is *range-partitioned and locally sorted*: shard i holds keys
``splitters[i-1] <= k < splitters[i]`` in sorted order — globally sorted
in shard-major read order, and exactly the layout the partitioned join's
build side wants.  A final device compaction (cumsum over the validity
lanes) packs the per-shard valid prefixes into the first ``n`` slots, so
consumers read a dense, globally sorted array without a host stitch.

Key widths mirror the join tiers: narrow keys are one int32 lane; wide
(<= 62-bit packed) keys travel as TWO nonnegative 31-bit lanes with
every comparison lexicographic over (hi, lo) — no x64 anywhere.

Capacity ``C`` is a static parameter; skewed inputs overflow (detected
on device, -1 counts lane) and the orchestrator retries with doubled
capacity after syncing ONE boolean, mirroring ``partitioned_probe``.

Stability: every sort is ``is_stable=True`` and equal keys route to one
destination shard, so the output permutation preserves source order
within equal-key groups — matching the host executor's stable sort.

Differential-tested against ``np.sort`` on the 8-device CPU mesh,
including heavy-skew inputs that exercise the retry and int64 packed
keys through the dual-lane exchange (tests/test_parallel.py).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

try:  # moved out of experimental in newer jax
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import row_spec
_MASK31 = np.int32((1 << 31) - 1)


def _dsort_shard_kernel(
    n_shards: int, capacity: int, samples: int, n_lanes: int, n_true: int,
    axes, *args
):
    """Per-shard body: local sort, splitter estimate, route, exchange,
    local sort of the received block.  *args* = (*key lanes, payload).

    Validity is tracked explicitly (an extra exchanged lane) rather than
    by a sentinel VALUE, so INT32_MAX is an ordinary sortable key: the
    orchestrator's padding is identified by global row position >=
    *n_true*, and in the final per-shard sort invalid entries order
    after every valid entry regardless of key value.
    """
    from ..ops.join import _searchsorted2

    lanes = args[:n_lanes]
    payload = args[n_lanes]
    m = lanes[0].shape[0]
    N, C, S = n_shards, capacity, samples

    # global positions identify the tail padding; the row dim shards
    # over the axes in mesh-major order (mesh.row_spec)
    flat = jnp.int32(0)
    for ax in axes:
        # lax.axis_size is absent from older jax; psum of 1 over the
        # axis is the same static size
        size = (
            lax.axis_size(ax)
            if hasattr(lax, "axis_size")
            else lax.psum(jnp.int32(1), ax)
        )
        flat = flat * size + lax.axis_index(ax)
    my_pos = flat * m + jnp.arange(m, dtype=jnp.int32)
    valid_in = (my_pos < n_true).astype(jnp.int32)

    # 1. local sort (payload + validity ride along; invalid last per key)
    sorted_ops = lax.sort(
        lanes + (1 - valid_in, payload), num_keys=n_lanes + 1, is_stable=True
    )
    lanes_s = sorted_ops[:n_lanes]
    v_s = 1 - sorted_ops[n_lanes]
    p_s = sorted_ops[n_lanes + 1]

    # 2. evenly-spaced local sample -> replicated pool -> global splitters
    step = jnp.maximum(m // S, 1)
    take = jnp.minimum(
        jnp.arange(S, dtype=jnp.int32) * step + step // 2, m - 1
    )
    pools = []
    for lane in lanes_s:
        pool = jnp.take(lane, take, axis=0)
        for ax in axes:
            pool = lax.all_gather(pool, ax, tiled=True)
        pools.append(pool)
    pools = lax.sort(tuple(pools), num_keys=n_lanes, is_stable=True)
    total = pools[0].shape[0]
    # N-1 equal-depth splitters; shard i owns [splitters[i-1], splitters[i])
    cut = jnp.arange(1, N, dtype=jnp.int32) * (total // N)
    splitters = tuple(jnp.take(p, cut, axis=0) for p in pools)

    # 3. route by destination range (invalid rows go nowhere: dest N)
    if n_lanes == 1:
        dest = jnp.searchsorted(splitters[0], lanes_s[0], side="right")
    else:
        dest = _searchsorted2(
            splitters[0], splitters[1], lanes_s[0], lanes_s[1], side="right"
        )
    dest = jnp.where(v_s > 0, dest.astype(jnp.int32), N)
    pos = jnp.arange(m, dtype=jnp.int32)
    routed_ops = lax.sort(
        (dest,) + lanes_s + (p_s,), num_keys=1, is_stable=True
    )
    dest_s = routed_ops[0]
    lanes_r = routed_ops[1 : 1 + n_lanes]
    p_r = routed_ops[1 + n_lanes]
    routed = dest_s < N
    group_start = jnp.searchsorted(
        dest_s, jnp.arange(N + 1, dtype=jnp.int32), side="left"
    )
    rank = pos - group_start[dest_s]
    ok = routed & (rank < C)  # overflow -> counts lane -1, caller retries

    slot = jnp.where(ok, rank, C)
    safe_dest = jnp.minimum(dest_s, N - 1)
    bufs = []
    for lane in lanes_r + (p_r,):
        bufs.append(
            jnp.zeros((N, C), jnp.int32).at[safe_dest, slot].set(lane, mode="drop")
        )
    buf_v = jnp.zeros((N, C), jnp.int32).at[safe_dest, slot].set(1, mode="drop")
    overflow = jnp.any(routed & (rank >= C))

    # 4. one exchange per lane; then sort the received block (validity
    # first in the key: every real element precedes padding regardless
    # of key value — INT32_MAX included)
    recv = [
        lax.all_to_all(b, axes, split_axis=0, concat_axis=0, tiled=True).reshape(-1)
        for b in bufs
    ]
    rv = lax.all_to_all(
        buf_v, axes, split_axis=0, concat_axis=0, tiled=True
    ).reshape(-1)
    final = lax.sort(
        (1 - rv,) + tuple(recv[:n_lanes]) + (recv[n_lanes],),
        num_keys=1 + n_lanes,
        is_stable=True,
    )
    out_v = 1 - final[0]
    out_lanes = final[1 : 1 + n_lanes]
    out_p = final[1 + n_lanes]
    n_here = jnp.sum(rv)
    # all-overflow report rides the counts lane as -1
    n_here = jnp.where(overflow, jnp.int32(-1), n_here)
    return out_lanes + (out_p, out_v, n_here.reshape(1))


@partial(
    jax.jit,
    static_argnames=("mesh", "n_shards", "capacity", "samples", "n_lanes", "n_true"),
)
def _dsort_spmd(  # analysis: allow[JIT001] — arity fixed per pipeline shape
    mesh, n_shards, capacity, samples, n_lanes, n_true, lanes, payload
):
    """Jitted launcher: pad to mesh divisibility ON DEVICE, shard, run
    the SPMD kernel, compact the valid slots to the first *n_true*
    positions with a global cumsum — no host stitch."""
    m = lanes[0].shape[0]
    pad = (-m) % n_shards
    if pad:
        lanes = tuple(
            jnp.concatenate([l, jnp.full(pad, _MASK31, jnp.int32)]) for l in lanes
        )
        payload = jnp.concatenate([payload, jnp.full(pad, -1, jnp.int32)])
    sharding = NamedSharding(mesh, row_spec(mesh))
    lanes = tuple(jax.lax.with_sharding_constraint(l, sharding) for l in lanes)
    payload = jax.lax.with_sharding_constraint(payload, sharding)

    axes = tuple(mesh.axis_names)
    rows = P(axes)
    f = shard_map(
        partial(
            _dsort_shard_kernel, n_shards, capacity, samples, n_lanes, n_true, axes
        ),
        mesh=mesh,
        in_specs=(rows,) * (n_lanes + 1),
        out_specs=(rows,) * (n_lanes + 2) + (rows,),
    )
    out = f(*lanes, payload)
    out_lanes = out[:n_lanes]
    out_p = out[n_lanes]
    out_v = out[n_lanes + 1]
    counts = out[n_lanes + 2]

    # compaction: shard-major valid prefixes -> dense [0, n_true) range
    tgt = jnp.where(out_v > 0, jnp.cumsum(out_v) - 1, n_true)
    dense_lanes = tuple(
        jnp.zeros(n_true, jnp.int32).at[tgt].set(l, mode="drop") for l in out_lanes
    )
    dense_p = jnp.zeros(n_true, jnp.int32).at[tgt].set(out_p, mode="drop")
    return dense_lanes + (dense_p, jnp.any(counts < 0))


def _capacity_plan(n: int, n_shards: int, capacity: "int | None") -> Tuple[int, int, int]:
    """(initial capacity, max capacity, samples) for *n* global rows."""
    padded = n + ((-n) % n_shards)
    m_per_shard = max(padded // n_shards, 1)
    if capacity is None:
        # balanced routing sends ~m_per_shard/N to each destination; the
        # retry doubles toward the guaranteed-sufficient m_per_shard
        capacity = max(64, 4 * ((m_per_shard + n_shards - 1) // n_shards))
    capacity = 1 << (int(capacity) - 1).bit_length()
    cap_max = 1 << (m_per_shard - 1).bit_length()
    capacity = min(capacity, cap_max)
    samples = min(64, max(8, m_per_shard))
    return capacity, cap_max, samples


def distributed_sort_device(
    mesh: Mesh,
    lanes: Tuple[jax.Array, ...],
    payload: jax.Array,
    capacity: "int | None" = None,
) -> Tuple[Tuple[jax.Array, ...], jax.Array]:
    """Device-resident sample-sort: *lanes* (1 narrow int32 lane, or 2
    nonnegative 31-bit lanes in (hi, lo) order) and an int32 *payload*
    stay on device end to end; the only host sync is one overflow
    boolean per capacity retry.  Returns (sorted lanes, permuted
    payload) as dense device arrays of the input length."""
    from ..utils.observe import telemetry

    n_shards = mesh.devices.size
    n = int(lanes[0].shape[0])
    if n == 0:
        return lanes, payload
    capacity, cap_max, samples = _capacity_plan(n, n_shards, capacity)
    while True:
        out = _dsort_spmd(
            mesh, n_shards, capacity, samples, len(lanes), n, tuple(lanes), payload
        )
        telemetry.count_sync(1)
        if not bool(jax.device_get(out[-1])):  # one O(1) scalar sync/attempt
            return out[: len(lanes)], out[len(lanes)]
        if capacity >= cap_max:
            # C = m_per_shard always suffices (a source shard cannot send
            # more rows than it holds), so this is unreachable — guard
            # against a logic regression rather than a data shape
            raise RuntimeError("distributed_sort: capacity overflow at maximum")
        capacity *= 2


def distributed_sort(
    mesh: Mesh,
    values: np.ndarray,
    payload: "np.ndarray | None" = None,
    capacity: "int | None" = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Globally sort an int32 or int64 (<= 62-bit packed) value array
    (with an optional int32 payload permuted alongside) using the
    explicit sample-sort.

    Host-facing wrapper over :func:`distributed_sort_device`: int64
    keys travel as dual 31-bit lanes, exactly like the wide join tier.
    Returns ``(sorted_values, permuted_payload)``; when *payload* is
    None it is the sort permutation (original indices).
    """
    values = np.asarray(values)
    n = values.shape[0]
    if payload is None:
        payload = np.arange(n, dtype=np.int32)
    payload = np.asarray(payload)
    if payload.dtype != np.int32:
        # payloads are row ids; refuse loudly rather than truncate
        raise TypeError(
            f"distributed_sort: int32 payload required, got {payload.dtype}"
        )
    if n == 0:
        return values, payload
    rows = NamedSharding(mesh, row_spec(mesh)) if n % mesh.devices.size == 0 else None

    def put(a):
        return jax.device_put(a, rows) if rows is not None else jax.device_put(a)

    if values.dtype == np.int64:
        if (values < 0).any() or (values >= (1 << 62)).any():
            raise TypeError("distributed_sort: int64 keys must fit 62 bits")
        from .pjoin import split_lanes

        hi, lo = split_lanes(values)
        lanes, pays = distributed_sort_device(
            mesh, (put(hi), put(lo)), put(payload), capacity
        )
        out_hi, out_lo = (np.asarray(l) for l in lanes)
        vals = (out_hi.astype(np.int64) << 31) | out_lo
        return vals, np.asarray(pays)
    if values.dtype != np.int32:
        raise TypeError(
            f"distributed_sort: int32/int64 values required, got {values.dtype}"
        )
    lanes, pays = distributed_sort_device(mesh, (put(values),), put(payload), capacity)
    return np.asarray(lanes[0]), np.asarray(pays)
