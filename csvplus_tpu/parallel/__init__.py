"""Multi-chip execution: mesh utilities and partitioned joins.

The reference is strictly single-threaded (SURVEY.md §2: no goroutines,
no channels).  This package is the rebuild's first-class replacement for
that absent layer, per BASELINE.json config 5: row-sharded column stores
(``DeviceTable.with_sharding`` — the one sharded-table abstraction)
over a 1-D ``jax.sharding.Mesh``, broadcast joins for small build sides,
and a range-partitioned lookup join whose key shuffle rides ICI
``lax.all_to_all`` inside ``shard_map``.
"""

from .mesh import make_mesh, shard_rows, replicate

__all__ = ["make_mesh", "shard_rows", "replicate"]
