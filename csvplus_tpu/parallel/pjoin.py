"""Partitioned lookup join: ICI all-to-all key shuffle inside shard_map.

This is the rebuild's replacement for the reference's per-row host binary
search (csvplus.go:552-568) at multi-chip scale — BASELINE.json config 5:
"8-way sharded orders.csv join across v5e-8 with ICI all-to-all key
shuffle".

Design (SPMD, static shapes throughout — no data-dependent control flow
inside jit):

* the build side of a device index is **range-partitioned over its
  UNIQUE packed keys**: each shard owns a contiguous equal-size slice of
  the distinct keys, and every key carries its precomputed global answer
  (first-match row, run length) as an int32 payload — duplicates never
  travel;
* each shard routes its local probe keys to the owning shard via a
  one-hot running count that ranks rows within their destination group
  (same slot assignment as a stable sort by dest, ~8x cheaper on CPU,
  and answers come back in original row order so no un-permute scatter)
  + a scatter into an ``(N, C)`` slot buffer + ``lax.all_to_all`` (this
  is the ICI shuffle);
* the owner answers every received probe with ``(global lower bound,
  match count)`` from a vectorized local binary search, and a reverse
  ``all_to_all`` returns answers through the same slots, so no
  permutation metadata ever crosses the wire;
* capacity ``C`` (slots per destination) is a static compile-time
  parameter; overflow is detected on device (-1 sentinel) and the probe
  retries with doubled capacity — the count -> allocate -> fill pattern
  with a geometric backoff instead of a second counting pass.

Skew (ISSUE 15): PROBE-side heavy hitters are detected by a sketch pass
over a bounded strided sample (``_detect_hot``: SpaceSaving count−err
lower bound -> a SOUND heavy predicate, threshold
``CSVPLUS_JOIN_SKEW_THRESHOLD``, default 1/(2·n_shards)) and routed
through a replicated broadcast tier: the few distinct hot keys are
answered once, the answers replicated to every shard, and each shard
resolves its own hot probe rows in place — this IS the JSPIM-style
salted broadcast, with the existing row placement acting as the salt
(a hot key's fact rows stay scattered across shards instead of
collapsing onto the key's range owner) and the positional scatter-back
at emit (``.at[pos].set``) folding the salt out so row order and
checksums stay bitwise-identical to the unsalted path.  The tail rides
the hash-repartition exchange unchanged, with its slot capacity shrunk
by the sketch's hot-share estimate (``_skew_capacity``); residual
imbalance is absorbed by the geometric capacity retry, and
``CSVPLUS_JOIN_SKEW=0`` disables the whole tier (the parity hatch and
skew-naive bench baseline).  BUILD-side skew is eliminated
structurally: because a probe answer is just ``(global lower bound,
run length)`` — the actual match rows are gathered later by global
position — shards never need a heavy key's duplicate copies at all.
The build side is partitioned over its UNIQUE keys, each carrying a
precomputed (lower, count) payload, so a key that owns 50% of the
build rows costs its owner exactly one slot (a build-side
salt-and-merge stays unnecessary under this answer representation).
"""

from __future__ import annotations

import math
import os
from functools import partial
from typing import Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
try:  # moved out of experimental in newer jax
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..obs.recompile import register_kernel
from ..utils.env import env_int, env_str
from .mesh import row_spec, shard_rows

_SENTINEL = np.int32(np.iinfo(np.int32).max)


def partition_tier_selected(
    n_keys: int, *, full_width: bool = True, stream_sharded: bool = True,
    min_keys: "int | None" = None,
) -> bool:
    """The ONE policy predicate for choosing this module's range-
    partitioned ``all_to_all`` probe tier over broadcast replication:
    a full-width probe of at least ``min_keys`` build keys by a
    mesh-sharded stream.  ``DeviceIndex.probe`` (both key-width tiers)
    and the plan verifier's placement domain both call it, so the
    executor and the static model can never disagree about the
    threshold."""
    if min_keys is None:
        from ..ops.join import DeviceIndex

        min_keys = DeviceIndex.PARTITION_MIN_KEYS
    return bool(full_width and stream_sharded and int(n_keys) >= int(min_keys))


# 62-bit sentinel for wide (int64) keys: packed keys keep headroom below
# it (DeviceIndex._bits_for reserves a slot above every code range)
_SENT62 = np.int64((1 << 62) - 1)


def _sentinel_for(dtype) -> "np.int32 | np.int64":
    return _SENT62 if np.dtype(dtype) == np.int64 else _SENTINEL


def split_lanes(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """int64 keys -> two nonnegative 31-bit int32 lanes; -1 -> (-1, -1).

    The 62-bit sentinel maps to (MASK31, MASK31), still the maximum in
    lane order."""
    hi = (x >> 31).astype(np.int32)
    lo = (x & np.int64((1 << 31) - 1)).astype(np.int32)
    neg = x < 0
    if neg.any():
        hi = np.where(neg, np.int32(-1), hi)
        lo = np.where(neg, np.int32(-1), lo)
    return hi, lo


def partition_build_keys(
    keys: np.ndarray, n_shards: int
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Range-partition a sorted build key array (int32 or int64) into
    equal slices of its UNIQUE keys, each key carrying its precomputed
    global answer.

    Returns (uniq_local[(N, k)] padded with the dtype's sentinel,
    lower_local[(N, k)] int32 global first-match row, count_local[(N, k)]
    int32 run length, splits[(N,)] = first unique key per shard).
    Partitioning unique keys makes build-side skew structurally
    impossible: a key's duplicate run contributes one slot regardless of
    its length (see module docstring).
    """
    sent = _sentinel_for(keys.dtype)
    uniq, first, counts = np.unique(keys, return_index=True, return_counts=True)
    u = uniq.shape[0]
    if u == 0:
        return (
            np.full((n_shards, 1), sent, dtype=keys.dtype),
            np.zeros((n_shards, 1), dtype=np.int32),
            np.zeros((n_shards, 1), dtype=np.int32),
            np.full(n_shards, sent, dtype=keys.dtype),
        )
    bounds = (np.arange(n_shards, dtype=np.int64) * u) // n_shards
    ends = np.append(bounds[1:], u)
    sizes = ends - bounds
    k = max(int(sizes.max()), 1)
    local = np.full((n_shards, k), sent, dtype=keys.dtype)
    lower = np.zeros((n_shards, k), dtype=np.int32)
    count = np.zeros((n_shards, k), dtype=np.int32)
    for s in range(n_shards):
        local[s, : sizes[s]] = uniq[bounds[s] : ends[s]]
        lower[s, : sizes[s]] = first[bounds[s] : ends[s]]
        count[s, : sizes[s]] = counts[bounds[s] : ends[s]]
    # splits must be non-decreasing for the routing binary search: an empty
    # shard inherits the NEXT non-empty shard's first key, so equal splits
    # route (via side='right') to the right-most shard — the actual owner.
    splits = np.full(n_shards, sent, dtype=keys.dtype)
    nxt = sent
    for s in range(n_shards - 1, -1, -1):
        if sizes[s] > 0:
            nxt = local[s, 0]
        splits[s] = nxt
    return local, lower, count, splits


def _probe_shard_kernel(
    n_shards: int, capacity: int, axes, qk, uniq_local, lower_local, count_local, splits
):
    """Per-shard body (runs under shard_map): route, exchange, probe,
    route back.  All shapes static.  *axes* is the mesh's full axis-name
    tuple: the exchange spans the whole mesh (ICI within a slice, DCN
    across slices on a 2-D mesh)."""
    N, C = n_shards, capacity

    valid = qk >= 0
    dest = jnp.clip(jnp.searchsorted(splits, qk, side="right") - 1, 0, N - 1)
    # invalid probes (absent keys / hot-key short-circuited) get dest N:
    # they consume NO exchange slots and answer (−1, 0)
    dest = jnp.where(valid, dest, N).astype(jnp.int32)
    routed = valid

    # rank of each query within its destination group, in original row
    # order, via a one-hot running count — N is small (mesh size), so
    # this is one O(m·N) prefix-sum pass.  A stable sort by dest gives
    # the identical rank assignment (first occurrence -> slot 0) but
    # costs ~8x more than the cumsum on CPU at mesh-bench scale, and
    # forces an O(m) un-permute scatter on the way out.
    safe_dest = jnp.minimum(dest, N - 1)  # N (invalid) is dropped via ok
    onehot = (dest[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]).astype(
        jnp.int32
    )
    rank = (
        jnp.take_along_axis(
            jnp.cumsum(onehot, axis=0), safe_dest[:, None], axis=1
        )[:, 0]
        - 1
    )
    ok = routed & (rank < C)  # overflow -> sentinel, caller retries bigger C

    # scatter into (N, C) slot buffer; overflow/invalid drop out of bounds
    buf = jnp.full((N, C), -1, dtype=jnp.int32)
    buf = buf.at[safe_dest, jnp.where(ok, rank, C)].set(qk, mode="drop")

    # ICI shuffle: slot-aligned exchange
    recv = lax.all_to_all(buf, axes, split_axis=0, concat_axis=0, tiled=True)

    # vectorized local search over this shard's unique-key slice; the
    # answer (global lower, run length) is a precomputed per-key payload
    q = recv.reshape(-1)
    idx = jnp.searchsorted(uniq_local, q, side="left")
    idx = jnp.minimum(idx, uniq_local.shape[0] - 1).astype(jnp.int32)
    found = (jnp.take(uniq_local, idx, axis=0) == q) & (q >= 0)
    resp_lo = jnp.where(found, jnp.take(lower_local, idx, axis=0), -1)
    resp_ct = jnp.where(found, jnp.take(count_local, idx, axis=0), 0)

    # answers ride home through the same slots
    back_lo = lax.all_to_all(
        resp_lo.reshape(N, C), axes, split_axis=0, concat_axis=0, tiled=True
    )
    back_ct = lax.all_to_all(
        resp_ct.reshape(N, C), axes, split_axis=0, concat_axis=0, tiled=True
    )

    safe_rank = jnp.clip(rank, 0, C - 1)
    # ranks are per original row order already — no un-permute needed
    got_lo = jnp.where(ok, back_lo[safe_dest, safe_rank], -1)
    # invalid probes answer (lo=-1, ct=0); only routed overflow gets -1
    got_ct = jnp.where(
        routed, jnp.where(ok, back_ct[safe_dest, safe_rank], -1), 0
    )
    return got_lo, got_ct


def _probe_shard_kernel2(
    n_shards: int,
    capacity: int,
    axes,
    qh,
    ql,
    uniq_hi,
    uniq_lo,
    lower_local,
    count_local,
    splits_hi,
    splits_lo,
):
    """Dual-lane (62-bit key) variant of :func:`_probe_shard_kernel`:
    identical routing/exchange structure, with the key carried as two
    nonnegative 31-bit int32 lanes and every comparison lexicographic
    over (hi, lo).  Costs one extra (N, C) exchange for the second lane.
    """
    from ..ops.join import _searchsorted2

    N, C = n_shards, capacity

    valid = qh >= 0
    dest = jnp.clip(
        _searchsorted2(splits_hi, splits_lo, qh, ql, side="right") - 1, 0, N - 1
    )
    dest = jnp.where(valid, dest, N).astype(jnp.int32)
    routed = valid

    # within-destination rank in original row order via one-hot running
    # count — same slot assignment as the stable sort it replaces, ~8x
    # cheaper at mesh-bench scale (see _probe_shard_kernel)
    safe_dest = jnp.minimum(dest, N - 1)
    onehot = (dest[:, None] == jnp.arange(N, dtype=jnp.int32)[None, :]).astype(
        jnp.int32
    )
    rank = (
        jnp.take_along_axis(
            jnp.cumsum(onehot, axis=0), safe_dest[:, None], axis=1
        )[:, 0]
        - 1
    )
    ok = routed & (rank < C)

    slot = jnp.where(ok, rank, C)
    buf_h = jnp.full((N, C), -1, jnp.int32).at[safe_dest, slot].set(qh, mode="drop")
    buf_l = jnp.full((N, C), -1, jnp.int32).at[safe_dest, slot].set(ql, mode="drop")

    recv_h = lax.all_to_all(buf_h, axes, split_axis=0, concat_axis=0, tiled=True)
    recv_l = lax.all_to_all(buf_l, axes, split_axis=0, concat_axis=0, tiled=True)

    q_h = recv_h.reshape(-1)
    q_l = recv_l.reshape(-1)
    idx = _searchsorted2(uniq_hi, uniq_lo, q_h, q_l, side="left")
    idx = jnp.minimum(idx, uniq_hi.shape[0] - 1).astype(jnp.int32)
    found = (
        (jnp.take(uniq_hi, idx, axis=0) == q_h)
        & (jnp.take(uniq_lo, idx, axis=0) == q_l)
        & (q_h >= 0)
    )
    resp_lo = jnp.where(found, jnp.take(lower_local, idx, axis=0), -1)
    resp_ct = jnp.where(found, jnp.take(count_local, idx, axis=0), 0)

    back_lo = lax.all_to_all(
        resp_lo.reshape(N, C), axes, split_axis=0, concat_axis=0, tiled=True
    )
    back_ct = lax.all_to_all(
        resp_ct.reshape(N, C), axes, split_axis=0, concat_axis=0, tiled=True
    )

    safe_rank = jnp.clip(rank, 0, C - 1)
    # ranks are per original row order already — no un-permute needed
    got_lo = jnp.where(ok, back_lo[safe_dest, safe_rank], -1)
    got_ct = jnp.where(
        routed, jnp.where(ok, back_ct[safe_dest, safe_rank], -1), 0
    )
    return got_lo, got_ct


@register_kernel("pjoin.probe_spmd2")
@partial(jax.jit, static_argnames=("mesh", "n_shards", "capacity"))
def _probe_spmd2(
    mesh, n_shards, capacity, qh, ql, uniq_hi, uniq_lo, lower, count, splits_hi,
    splits_lo,
):
    axes = tuple(mesh.axis_names)
    rows = P(axes)
    f = shard_map(
        partial(_probe_shard_kernel2, n_shards, capacity, axes),
        mesh=mesh,
        in_specs=(rows, rows, rows, rows, rows, rows, P(), P()),
        out_specs=(rows, rows),
    )
    return f(qh, ql, uniq_hi, uniq_lo, lower, count, splits_hi, splits_lo)


@register_kernel("pjoin.probe_spmd")
@partial(jax.jit, static_argnames=("mesh", "n_shards", "capacity"))
def _probe_spmd(mesh, n_shards, capacity, qk_sharded, uniq, lower, count, splits):
    axes = tuple(mesh.axis_names)
    rows = P(axes)
    f = shard_map(
        partial(_probe_shard_kernel, n_shards, capacity, axes),
        mesh=mesh,
        in_specs=(rows, rows, rows, rows, P()),
        out_specs=(rows, rows),
    )
    return f(qk_sharded, uniq, lower, count, splits)


def prepare_partitioned(mesh: Mesh, index_keys_sorted: np.ndarray):
    """Range-partition + upload the build keys once; reusable across
    probes (see DeviceIndex._partitioned_for's cache).

    int32 keys -> a 4-tuple (uniq, lower, count, splits); int64 (wide,
    62-bit) keys -> a 6-tuple with the unique keys and splits as dual
    31-bit lanes (uniq_hi, uniq_lo, lower, count, splits_hi, splits_lo).
    """
    from ..utils.observe import telemetry

    n_shards = mesh.devices.size
    rows = NamedSharding(mesh, row_spec(mesh))
    repl = NamedSharding(mesh, P())
    with telemetry.stage(
        "join:partition", int(index_keys_sorted.shape[0])
    ) as _p:
        _p["n_shards"] = n_shards
        if np.dtype(index_keys_sorted.dtype) == np.int64:
            local, lower, count, splits = partition_build_keys(
                index_keys_sorted, n_shards
            )
            lh, ll = split_lanes(local.reshape(-1))
            sh, sl = split_lanes(splits)
            return tuple(
                telemetry.barrier(
                    (
                        jax.device_put(lh, rows),
                        jax.device_put(ll, rows),
                        jax.device_put(lower.reshape(-1), rows),
                        jax.device_put(count.reshape(-1), rows),
                        jax.device_put(sh, repl),
                        jax.device_put(sl, repl),
                    )
                )
            )
        local, lower, count, splits = partition_build_keys(
            index_keys_sorted.astype(np.int32), n_shards
        )
        return tuple(
            telemetry.barrier(
                (
                    jax.device_put(local.reshape(-1), rows),
                    jax.device_put(lower.reshape(-1), rows),
                    jax.device_put(count.reshape(-1), rows),
                    jax.device_put(splits, repl),
                )
            )
        )


def partitioned_probe(
    mesh: Mesh,
    stream_keys: np.ndarray,
    index_keys_sorted: np.ndarray,
    capacity: "int | None" = None,
    prepared=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """All-to-all partitioned probe: for every stream key, the global
    ``[lower, lower+count)`` match range in the sorted index key array.

    Host-facing numpy shim over the device orchestration
    (:func:`partitioned_probe_device` / ``_wide``), which owns the
    padding, hot-key short circuit, and capacity-retry logic — one
    implementation, two entry points.  Keys are packed keys with -1 for
    invalid probes (absent/unmatched dictionary translation): int32 for
    narrow (<= 31-bit) keys, int64 for wide (<= 62-bit) keys — the wide
    tier exchanges dual 31-bit lanes.  *prepared* short-circuits the
    partition+upload with the result of :func:`prepare_partitioned`.
    """
    wide = np.dtype(stream_keys.dtype) == np.int64
    if prepared is None:
        prepared = prepare_partitioned(mesh, index_keys_sorted)
    assert len(prepared) == (6 if wide else 4), "prepared/key dtype mismatch"
    if wide:
        qh, ql = split_lanes(stream_keys)
        lo, ct = partitioned_probe_device_wide(
            mesh, jax.device_put(qh), jax.device_put(ql), prepared, capacity
        )
    else:
        qk = jax.device_put(stream_keys.astype(np.int32))
        lo, ct = partitioned_probe_device(mesh, qk, prepared, capacity)
    return np.asarray(lo), np.asarray(ct)


# -- device-resident orchestration (the executor's multi-chip tier) -------
#
# The host wrapper above (partitioned_probe) syncs the full probe array
# to numpy, pads/samples/uploads on host, and syncs the full counts
# array every capacity retry — O(n) host traffic per probe.  The
# functions below keep the probe keys, answers, hot-key merge, padding,
# and overflow detection ON DEVICE: the only host syncs are a <=4096-
# element hot-key sample and one boolean overflow scalar per retry.


@register_kernel("pjoin.probe_spmd_dev")
@partial(jax.jit, static_argnames=("mesh", "n_shards", "capacity", "n_hot"))
def _probe_spmd_dev(
    mesh, n_shards, capacity, n_hot, qk, uniq, lower, count, splits,
    hot_vals, hot_lo, hot_ct,
):
    """One executable: hot-key mask -> pad -> all_to_all exchange ->
    un-pad -> hot-key merge -> overflow flag.  *n_hot* = 0 compiles the
    variant without the hot path (hot operands are 1-element dummies)
    and returns exactly the historical 3-tuple — the uniform-data
    passthrough contract (same trace, same executable as before the
    skew tier existed).  *n_hot* > 0 additionally returns the number of
    probe rows the broadcast tier answered (the routing-split evidence,
    synced together with the overflow flag — no extra host round)."""
    axes = tuple(mesh.axis_names)
    rows = row_spec(mesh)
    m = qk.shape[0]
    if n_hot:
        idx = jnp.searchsorted(hot_vals, qk, side="left")
        idxc = jnp.minimum(idx, n_hot - 1).astype(jnp.int32)
        hit = (jnp.take(hot_vals, idxc, axis=0) == qk) & (qk >= 0)
        qk_cold = jnp.where(hit, jnp.int32(-1), qk)
    else:
        qk_cold = qk
    pad = (-m) % n_shards
    if pad:
        qk_cold = jnp.concatenate(
            [qk_cold, jnp.full(pad, -1, qk_cold.dtype)]
        )
    qk_cold = jax.lax.with_sharding_constraint(
        qk_cold, NamedSharding(mesh, rows)
    )
    f = shard_map(
        partial(_probe_shard_kernel, n_shards, capacity, axes),
        mesh=mesh,
        in_specs=(rows, rows, rows, rows, P()),
        out_specs=(rows, rows),
    )
    lo, ct = f(qk_cold, uniq, lower, count, splits)
    lo, ct = lo[:m], ct[:m]
    if n_hot:
        h_lo = jnp.take(hot_lo, idxc, axis=0)
        h_ct = jnp.take(hot_ct, idxc, axis=0)
        lo = jnp.where(hit, jnp.where(h_ct > 0, h_lo, -1), lo)
        ct = jnp.where(hit, h_ct, ct)
        return lo, ct, jnp.any(ct < 0), jnp.sum(hit)
    return lo, ct, jnp.any(ct < 0)


@register_kernel("pjoin.probe_spmd_dev2")
@partial(jax.jit, static_argnames=("mesh", "n_shards", "capacity", "n_hot"))
def _probe_spmd_dev2(
    mesh, n_shards, capacity, n_hot, qh, ql,
    uniq_hi, uniq_lo, lower, count, splits_hi, splits_lo,
    hot_hi, hot_lo_lane, hot_ans_lo, hot_ans_ct,
):
    """Wide-key (dual 31-bit lane) variant of :func:`_probe_spmd_dev`."""
    from ..ops.join import _searchsorted2

    axes = tuple(mesh.axis_names)
    rows = row_spec(mesh)
    m = qh.shape[0]
    if n_hot:
        idx = _searchsorted2(hot_hi, hot_lo_lane, qh, ql, side="left")
        idxc = jnp.minimum(idx, n_hot - 1).astype(jnp.int32)
        hit = (
            (jnp.take(hot_hi, idxc, axis=0) == qh)
            & (jnp.take(hot_lo_lane, idxc, axis=0) == ql)
            & (qh >= 0)
        )
        qh_cold = jnp.where(hit, jnp.int32(-1), qh)
        ql_cold = jnp.where(hit, jnp.int32(-1), ql)
    else:
        qh_cold, ql_cold = qh, ql
    pad = (-m) % n_shards
    if pad:
        fill = jnp.full(pad, -1, jnp.int32)
        qh_cold = jnp.concatenate([qh_cold, fill])
        ql_cold = jnp.concatenate([ql_cold, fill])
    sharding = NamedSharding(mesh, rows)
    qh_cold = jax.lax.with_sharding_constraint(qh_cold, sharding)
    ql_cold = jax.lax.with_sharding_constraint(ql_cold, sharding)
    f = shard_map(
        partial(_probe_shard_kernel2, n_shards, capacity, axes),
        mesh=mesh,
        in_specs=(rows, rows, rows, rows, rows, rows, P(), P()),
        out_specs=(rows, rows),
    )
    lo, ct = f(
        qh_cold, ql_cold, uniq_hi, uniq_lo, lower, count, splits_hi, splits_lo
    )
    lo, ct = lo[:m], ct[:m]
    if n_hot:
        h_lo = jnp.take(hot_ans_lo, idxc, axis=0)
        h_ct = jnp.take(hot_ans_ct, idxc, axis=0)
        lo = jnp.where(hit, jnp.where(h_ct > 0, h_lo, -1), lo)
        ct = jnp.where(hit, h_ct, ct)
        return lo, ct, jnp.any(ct < 0), jnp.sum(hit)
    return lo, ct, jnp.any(ct < 0)


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _renamed_rows(mesh: Mesh, x: jax.Array) -> jax.Array:
    """Re-commit a jit output to a row NamedSharding: XLA hands results
    back with an opaque GSPMDSharding (no ``.mesh``), but downstream
    consumers (``_aligned_codes``, the executor's replication caches)
    key off the named mesh.  Same layout -> no data movement.  Lengths
    that don't divide the mesh can't carry a row NamedSharding; they
    keep the opaque sharding and downstream falls back to placement-
    agnostic eager gathers."""
    if x.shape[0] % mesh.devices.size == 0:
        return jax.device_put(x, NamedSharding(mesh, row_spec(mesh)))
    return x


def _default_capacity(m: int, n_shards: int) -> int:
    m_per_shard = (m + n_shards - 1) // n_shards
    return _pow2(max(64, 2 * ((m_per_shard + n_shards - 1) // n_shards)))


def skew_enabled() -> bool:
    """``CSVPLUS_JOIN_SKEW=0`` disables ALL hot-key handling (the
    parity hatch): no detection, no broadcast tier, default tail
    capacity — the skew-naive baseline the bench gate compares
    against.  Read per call so one process can flip it between
    passes (the bench measures both modes in the same run)."""
    return env_str("CSVPLUS_JOIN_SKEW", "1") != "0"


def skew_threshold(n_shards: int) -> float:
    """Heavy-hitter share threshold τ: a probe key is worth
    broadcasting once its estimated share exceeds τ
    (``CSVPLUS_JOIN_SKEW_THRESHOLD``, default ``1/(2·n_shards)``).
    Rationale for the default — the broadcast-vs-repartition cutoff:
    under hash repartition, one key's rows all land on its owner, so a
    key with share τ adds τ·m rows to one shard on top of the shard's
    m/n fair share; at τ = 1/(2n) that's a 50% overload, the point
    where the (N, C) slot buffer must grow a power of two and every
    shard pays the doubled exchange.  Broadcasting such a key instead
    costs one replicated answer slot — O(1) — so the cutoff sits where
    the repartition cost first becomes super-linear."""
    v = env_str("CSVPLUS_JOIN_SKEW_THRESHOLD")
    if v:
        return max(float(v), 1e-6)
    return 1.0 / (2.0 * max(int(n_shards), 1))


def _skew_sample_cap() -> int:
    """Sample-size cap (``CSVPLUS_JOIN_SKEW_SAMPLE``, default 4096 —
    the bound the sync-accounting tests pin).  Detection resolves key
    shares down to ~16/cap, so benches raise it to see deeper into the
    Zipf tail."""
    return max(env_int("CSVPLUS_JOIN_SKEW_SAMPLE", 4096), 64)


def _detect_hot(qk_dev, n_shards: int, wide: bool):
    """Sketch-driven heavy-hitter detection over a bounded strided
    device sample — a data-INDEPENDENT host transfer (bounded by the
    sample cap, not the probe length).

    The sample's (value, count) aggregate feeds a :class:`SpaceSaving`
    sketch with ``k = ceil(4/τ)`` tracked keys; a key is classified
    heavy only when its guaranteed lower bound clears the bar::

        count - err >= max(8, τ·sample/2)

    Soundness: SpaceSaving guarantees any key with sample share > 1/k
    is tracked, with ``err <= observed/k <= τ·observed/4`` — so every
    key whose true sample count reaches ``τ·observed`` survives the
    bar (count ≥ τ·observed, err ≤ τ·observed/4), while any key that
    clears it provably holds ≥ τ/2 of the sample.  The absolute floor
    of 8 sample hits guards the small-sample regime where binomial
    noise dominates.  With fewer distinct sampled keys than *k* the
    sketch counts are exact (err 0) and the predicate reduces to the
    plain frequency threshold.

    Returns ``(hot, hot_share)``: sorted distinct hot values as int64
    (wide) / int32 or None, plus the hot keys' aggregate share of the
    sample — the planner's capacity hint for the tail exchange.

    Fused probe passes (ISSUE 19) need no special handling here: the
    sample is drawn from whatever packed key array reaches the
    partitioned probe, and ``multiway_join_selected`` packs keys
    gathered down to the POST-filter selection — so hot-key detection
    and broadcast routing automatically see only the fact rows that
    survived the absorbed filters, exactly the rows the exchange would
    carry."""
    from ..obs.sketch import SpaceSaving
    from ..utils.observe import telemetry

    if not skew_enabled():
        return None, 0.0
    m = int(qk_dev[0].shape[0] if wide else qk_dev.shape[0])
    if m < 4 * n_shards:
        return None, 0.0
    tau = skew_threshold(n_shards)
    with telemetry.stage("join:skew-detect", m) as _d:
        cap = _skew_sample_cap()
        step = max(1, -(-m // cap))  # ceil: the sample stays <= cap elements
        # EXPLICIT device_get: the transfer-guard differential test pins
        # that the device path performs no *implicit* device->host
        # transfers
        if wide:
            hi = jax.device_get(qk_dev[0][::step])
            lo = jax.device_get(qk_dev[1][::step])
            telemetry.count_sync(hi.size + lo.size)
            sample = (hi.astype(np.int64) << 31) | np.where(lo >= 0, lo, 0)
            sample = sample[hi >= 0]
        else:
            sample = jax.device_get(qk_dev[::step])
            telemetry.count_sync(sample.size)
            sample = sample[sample >= 0]
        _d["threshold"] = round(tau, 6)
        _d["sample"] = int(sample.size)
        _d["hot_keys"] = 0
        if not sample.size:
            return None, 0.0
        vals, cnts = np.unique(sample, return_counts=True)
        sk = SpaceSaving(k=min(max(int(math.ceil(4.0 / tau)), 8), 4096))
        sk.offer_counts(vals, cnts)
        bar = max(8.0, tau * sample.size / 2.0)
        hot_list = [key for key, c, e in sk.topk() if (c - e) >= bar]
        _d["hot_keys"] = len(hot_list)
        if not hot_list:
            return None, 0.0
        hot = np.sort(np.asarray(hot_list, dtype=np.int64 if wide else np.int32))
        # hot share from the EXACT sample counts (not the sketch
        # estimates): the tail-capacity hint must never overshoot
        hot_share = float(cnts[np.isin(vals, hot)].sum()) / float(sample.size)
        _d["hot_share"] = round(hot_share, 4)
        return hot, hot_share


def _skew_capacity(m: int, n_shards: int, hot_share: float) -> int:
    """Sketch-informed tail capacity: the broadcast tier removes
    ``hot_share`` of the probe rows from the exchange, so the (N, C)
    slot buffer only needs to cover the tail.  1.5x slack over the
    uniform per-(src, dest) expectation absorbs residual tail skew
    (the heaviest un-broadcast key holds < τ of the rows by the
    detection guarantee); an undershoot costs one geometric retry,
    never correctness.  Clamped to the skew-naive default so a bad
    share estimate can only shrink the exchange, and floored like the
    default."""
    tail = max(1.0 - hot_share, 0.0)
    m_per_shard = (m + n_shards - 1) // n_shards
    want = int(math.ceil(1.5 * tail * m_per_shard / n_shards))
    return min(_pow2(max(64, want)), _default_capacity(m, n_shards))


def _note_skew(
    label, m: int, hot_keys: int, rows_broadcast: int, capacity: int,
    threshold: float,
) -> None:
    """The routing-split evidence for one skew-engaged probe: a
    ``join:skew`` row in the span stage table (so ``obs diff`` can
    attribute the win) plus the process-global counters
    ``TelemetryPlane`` exports.  ``seconds=0``: this row is an
    accounting record — detection and hot-answer time are already
    attributed to ``join:skew-detect`` / ``join:broadcast`` — so the
    stage table's time shares stay undistorted."""
    from ..obs.joinskew import joinskew
    from ..utils.observe import telemetry

    rows_repartitioned = int(m) - int(rows_broadcast)
    telemetry.add_stage(
        "join:skew", m, m, 0.0,
        hot_keys=int(hot_keys),
        rows_broadcast=int(rows_broadcast),
        rows_repartitioned=rows_repartitioned,
        capacity=int(capacity),
        threshold=round(float(threshold), 6),
    )
    joinskew.on_join(
        label or "packed", int(hot_keys), int(rows_broadcast),
        rows_repartitioned,
    )


def _hot_answers_device(mesh, hot: np.ndarray, prepared, wide: bool):
    """Answer the (few, distinct) hot values themselves through the same
    SPMD exchange — tiny arrays, so capacity = the full hot count can
    never overflow.  Returns device (vals..., lo, ct) padded to pow2
    with never-matching sentinels (padded to a mesh multiple first)."""
    n_shards = mesh.devices.size
    n_hot = _pow2(hot.size)
    padded = max(n_hot, n_shards) if n_hot % n_shards else n_hot
    padded = padded + ((-padded) % n_shards)
    cap = _pow2(padded)  # worst case: every hot value routes to one shard
    if wide:
        hv = np.full(padded, -1, dtype=np.int64)
        hv[: hot.size] = hot
        qh, ql = split_lanes(hv)
        qh_d = shard_rows(mesh, qh)
        ql_d = shard_rows(mesh, ql)
        uh, ul, lower, count, sh, sl = prepared
        lo, ct = _probe_spmd2(
            mesh, n_shards, cap, qh_d, ql_d, uh, ul, lower, count, sh, sl
        )
    else:
        hv = np.full(padded, -1, dtype=np.int32)
        hv[: hot.size] = hot
        qk_d = shard_rows(mesh, hv)
        uniq, lower, count, splits = prepared
        lo, ct = _probe_spmd(mesh, n_shards, cap, qk_d, uniq, lower, count, splits)
    repl = NamedSharding(mesh, P())
    # hot value lanes for the main kernel's membership search: sorted,
    # padded by REPEATING the last real value — duplicates at the tail
    # keep the array sorted, and searchsorted-left always lands on the
    # first (real, correctly-answered) slot, so a probe key equal to
    # any conceivable pad value can never be answered from a pad slot
    if wide:
        hh, hl = split_lanes(hot)
        pad_hi = np.full(n_hot, hh[-1], np.int32)
        pad_lo = np.full(n_hot, hl[-1], np.int32)
        pad_hi[: hot.size] = hh
        pad_lo[: hot.size] = hl
        vals = (jax.device_put(pad_hi, repl), jax.device_put(pad_lo, repl))
    else:
        pad_v = np.full(n_hot, hot[-1], np.int32)
        pad_v[: hot.size] = hot
        vals = (jax.device_put(pad_v, repl),)
    ans_lo = jax.device_put(jnp.asarray(lo[: hot.size]), repl)
    ans_ct = jax.device_put(jnp.asarray(ct[: hot.size]), repl)
    # pad answers to n_hot so gather indices stay in range
    if hot.size < n_hot:
        fill = jnp.full(n_hot - hot.size, -1, jnp.int32)
        ans_lo = jnp.concatenate([ans_lo, fill])
        ans_ct = jnp.concatenate([ans_ct, jnp.zeros(n_hot - hot.size, jnp.int32)])
        ans_lo = jax.device_put(ans_lo, repl)
        ans_ct = jax.device_put(ans_ct, repl)
    return vals, ans_lo, ans_ct


def _retry_probe_device(mesh: Mesh, m: int, capacity: "int | None", launch):
    """Shared retry driver for the device wrappers: geometric capacity
    doubling keyed off ONE overflow boolean per attempt (the only host
    sync in the loop), results re-committed to the named mesh.

    Returns ``((lo, ct), rows_broadcast, capacity)``: when the launch
    carries the hot tier (4-tuple results) the broadcast row count
    rides the same device_get as the overflow flag — still one host
    round per attempt."""
    from ..utils.observe import telemetry

    n_shards = mesh.devices.size
    if capacity is None:
        capacity = _default_capacity(m, n_shards)
    padded_m = m + ((-m) % n_shards)
    retries = 0
    # the exchange stage covers the whole shard_map launch: all_to_all
    # key shuffle + per-shard local probe + answer return + hot merge
    # (one fused SPMD executable, not separable from outside)
    with telemetry.stage("join:all_to_all", m) as _x:
        while True:
            res = launch(capacity)
            lo, ct, overflow = res[0], res[1], res[2]
            if len(res) > 3:
                ov, hits = jax.device_get((overflow, res[3]))
                telemetry.count_sync(2)
                overflowed, rows_broadcast = bool(ov), int(hits)
            else:
                telemetry.count_sync(1)
                # one O(1) scalar sync per attempt
                overflowed, rows_broadcast = bool(jax.device_get(overflow)), 0
            if not overflowed:
                _x["capacity"] = capacity
                _x["retries"] = retries
                out = _renamed_rows(mesh, lo), _renamed_rows(mesh, ct)
                telemetry.barrier(out)
                return out, rows_broadcast, capacity
            if capacity >= max(padded_m, 1):
                raise RuntimeError(
                    "partitioned probe: capacity overflow at maximum"
                )
            capacity *= 2
            retries += 1


def _note_part_info(info, capacity, hot, rows_broadcast) -> None:
    """Accumulate one partitioned probe's outcome into the multiway
    join's shared *info* dict (the sharded-multiway contract, ISSUE 17):
    ``capacity`` is the max settled exchange capacity so far — the next
    dimension's probe seeds its FIRST attempt with it, so similar
    fanouts pay at most one geometric retry round across ALL dimensions
    instead of one per dimension — and the hot-routing tallies sum over
    dimensions (hot keys of EITHER dimension ride the broadcast tier;
    the tail crosses the exchange once per dimension over the original
    fact rows, never over a materialized intermediate)."""
    if info is None:
        return
    info["capacity"] = max(int(capacity), int(info.get("capacity") or 0))
    info["dims"] = info.get("dims", 0) + 1
    info["hot_keys"] = info.get("hot_keys", 0) + (
        int(hot.size) if hot is not None else 0
    )
    info["rows_broadcast"] = info.get("rows_broadcast", 0) + int(rows_broadcast)


def partitioned_probe_device(
    mesh: Mesh, qk: jax.Array, prepared, capacity: "int | None" = None,
    label: "str | None" = None, info: "dict | None" = None,
) -> Tuple[jax.Array, jax.Array]:
    """Device-resident narrow-key partitioned probe: *qk* (int32, -1 =
    invalid) stays on device end to end; answers come back as device
    arrays ready for the device fan-out expansion and fused gathers.

    Host syncs per call: one bounded hot-key sample + one O(1) scalar
    sync per capacity attempt (VERDICT round-2 weak #3).  *label*
    names the probed index in the skew-routing evidence
    (``csvplus_join_*`` counters, ``join:skew`` stage row).  *info*
    accumulates this probe's settled capacity and hot-routing split for
    the multiway join's cross-dimension sharing (:func:`_note_part_info`)."""
    n_shards = mesh.devices.size
    uniq, lower, count, splits = prepared
    m = int(qk.shape[0])

    hot, hot_share = _detect_hot(qk, n_shards, wide=False)
    if hot is not None:
        from ..utils.observe import telemetry

        with telemetry.stage("join:broadcast", int(hot.size)) as _b:
            # the static lane width is the pow2 bucket of the hot COUNT
            # (shape-derived, log-bounded distinct values), never the
            # hot values themselves
            n_hot = _pow2(hot.size)
            (hot_vals,), hot_lo, hot_ct = _hot_answers_device(
                mesh, hot, prepared, wide=False
            )
            _b["n_hot"] = n_hot
            telemetry.barrier((hot_vals, hot_lo, hot_ct))
        if capacity is None:
            capacity = _skew_capacity(m, n_shards, hot_share)
    else:
        z = jnp.zeros(1, jnp.int32)
        hot_vals = hot_lo = hot_ct = z
        n_hot = 0

    def launch(cap):
        return _probe_spmd_dev(
            mesh, n_shards, cap, n_hot,
            qk, uniq, lower, count, splits, hot_vals, hot_lo, hot_ct,
        )

    out, rows_broadcast, cap_used = _retry_probe_device(mesh, m, capacity, launch)
    if hot is not None:
        _note_skew(
            label, m, int(hot.size), rows_broadcast, cap_used,
            skew_threshold(n_shards),
        )
    _note_part_info(info, cap_used, hot, rows_broadcast)
    return out


def partitioned_probe_device_wide(
    mesh: Mesh,
    q_hi: jax.Array,
    q_lo: jax.Array,
    prepared,
    capacity: "int | None" = None,
    label: "str | None" = None,
    info: "dict | None" = None,
) -> Tuple[jax.Array, jax.Array]:
    """Device-resident wide-key (62-bit dual-lane) partitioned probe.
    Invalid probes carry (-1, -1) lanes."""
    n_shards = mesh.devices.size
    uh, ul, lower, count, sh, sl = prepared
    m = int(q_hi.shape[0])

    hot, hot_share = _detect_hot((q_hi, q_lo), n_shards, wide=True)
    if hot is not None:
        from ..utils.observe import telemetry

        with telemetry.stage("join:broadcast", int(hot.size)) as _b:
            n_hot = _pow2(hot.size)  # pow2 bucket: log-bounded statics
            (hot_hi, hot_lo_lane), hot_ans_lo, hot_ans_ct = (
                _hot_answers_device(mesh, hot, prepared, wide=True)
            )
            _b["n_hot"] = n_hot
            telemetry.barrier((hot_hi, hot_lo_lane, hot_ans_lo, hot_ans_ct))
        if capacity is None:
            capacity = _skew_capacity(m, n_shards, hot_share)
    else:
        z = jnp.zeros(1, jnp.int32)
        hot_hi = hot_lo_lane = hot_ans_lo = hot_ans_ct = z
        n_hot = 0

    def launch(cap):
        return _probe_spmd_dev2(
            mesh, n_shards, cap, n_hot, q_hi, q_lo,
            uh, ul, lower, count, sh, sl,
            hot_hi, hot_lo_lane, hot_ans_lo, hot_ans_ct,
        )

    out, rows_broadcast, cap_used = _retry_probe_device(mesh, m, capacity, launch)
    if hot is not None:
        _note_skew(
            label, m, int(hot.size), rows_broadcast, cap_used,
            skew_threshold(n_shards),
        )
    _note_part_info(info, cap_used, hot, rows_broadcast)
    return out


@jax.jit
def broadcast_probe(index_keys, qk_sharded):
    """Small-build-side fast path: the sorted key array is replicated to
    every shard (the analogue of the reference keeping the whole index in
    memory) and each shard binary-searches its own row slice; XLA
    parallelizes over the row sharding with zero collectives in the probe
    itself."""
    lower = jnp.searchsorted(index_keys, qk_sharded, side="left")
    upper = jnp.searchsorted(index_keys, qk_sharded, side="right")
    counts = jnp.where(qk_sharded >= 0, upper - lower, 0)
    return lower.astype(jnp.int32), counts.astype(jnp.int32)
