"""Row-sharded columnar tables.

The sharded column store from SURVEY.md §2's rebuild table: the same
dictionary-encoded columns as :class:`~csvplus_tpu.columnar.table
.DeviceTable`, but with code arrays laid out row-sharded over a 1-D mesh
(``NamedSharding(mesh, P("shards"))``).  Rows are padded to a multiple of
the shard count with code -1 (absent), and a validity cutoff tracks the
true length — padding never leaks into results.

Dictionaries stay on the host and are replicated conceptually: they are
only consulted for encode/decode and value->code translation, which are
host operations by design.
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from ..columnar.table import DeviceTable, StringColumn, encode_strings
from ..row import Row
from .mesh import pad_to_multiple, shard_rows


class ShardedTable:
    """Equal-length dictionary-encoded columns, row-sharded over a mesh."""

    def __init__(
        self,
        mesh: Mesh,
        columns: Dict[str, StringColumn],
        nrows: int,
        padded: int,
    ):
        self.mesh = mesh
        self.columns = columns  # codes arrays are sharded, length `padded`
        self.nrows = nrows  # true row count (<= padded)
        self.padded = padded

    @property
    def n_shards(self) -> int:
        return self.mesh.devices.size

    @classmethod
    def from_table(cls, table: DeviceTable, mesh: Mesh) -> "ShardedTable":
        """Re-lay a single-device table across the mesh."""
        n = mesh.devices.size
        cols = {}
        padded = table.nrows
        for name, col in table.columns.items():
            codes, _ = pad_to_multiple(np.asarray(col.codes), n, np.int32(-1))
            padded = codes.shape[0]
            cols[name] = StringColumn(col.dictionary, shard_rows(mesh, codes))
        return cls(mesh, cols, table.nrows, padded)

    @classmethod
    def from_pylists(
        cls, data: Dict[str, Sequence[str]], mesh: Mesh
    ) -> "ShardedTable":
        n = mesh.devices.size
        cols = {}
        nrows = padded = 0
        for name, values in data.items():
            dictionary, codes = encode_strings(values)
            nrows = codes.shape[0]
            codes, _ = pad_to_multiple(codes, n, np.int32(-1))
            padded = codes.shape[0]
            cols[name] = StringColumn(dictionary, shard_rows(mesh, codes))
        return cls(mesh, cols, nrows, padded)

    def to_table(self) -> DeviceTable:
        """Gather back to one device (drops padding)."""
        cols = {}
        for name, col in self.columns.items():
            codes = np.asarray(col.codes)[: self.nrows]
            cols[name] = StringColumn(col.dictionary, jnp.asarray(codes))
        return DeviceTable(cols, self.nrows, jax.devices()[0])

    def to_rows(self) -> List[Row]:
        return self.to_table().to_rows()

    def column_codes(self, name: str) -> jax.Array:
        return self.columns[name].codes
