"""Symbolic row-transform expressions for Map/Transform stages.

The reference's ``Map`` takes an opaque Go closure (csvplus.go:290-296,
e.g. README.md:25 renames a value in place).  Opaque callbacks cannot run
on a TPU, so common transforms get symbolic counterparts: callable objects
that work exactly like a hand-written ``row -> row`` function on the host
path, while the device executor lowers them to columnar metadata updates
or vectorized kernels (renaming a column on a columnar table is free; a
constant write is a broadcast).
"""

from __future__ import annotations

from typing import Callable, Mapping

from .row import Row


class RowExpr:
    """Base: a callable row transform that is also a symbolic expr."""

    __plan_expr__ = True
    __slots__ = ()

    def __call__(self, row: Row) -> Row:  # pragma: no cover - abstract
        raise NotImplementedError


class SetValue(RowExpr):
    """Set ``row[column] = value`` (the README.md:25 idiom: replace the
    value under an existing or new column)."""

    __slots__ = ("column", "value")

    def __init__(self, column: str, value: str):
        self.column = column
        self.value = value

    def __call__(self, row: Row) -> Row:
        row[self.column] = self.value
        return row

    def __repr__(self) -> str:
        return f"SetValue({self.column!r}, {self.value!r})"


class Rename(RowExpr):
    """Rename columns: mapping of old name -> new name."""

    __slots__ = ("mapping",)

    def __init__(self, mapping: Mapping[str, str]):
        if not mapping:
            raise ValueError("empty mapping in Rename()")
        self.mapping = dict(mapping)

    def __call__(self, row: Row) -> Row:
        for old, new in self.mapping.items():
            if old in row:
                row[new] = row.pop(old)
        return row

    def __repr__(self) -> str:
        return f"Rename({self.mapping!r})"


class Update(RowExpr):
    """Chain several symbolic transforms left to right."""

    __slots__ = ("exprs",)

    def __init__(self, *exprs: Callable[[Row], Row]):
        self.exprs = tuple(exprs)

    def __call__(self, row: Row) -> Row:
        for e in self.exprs:
            row = e(row)
        return row

    def __repr__(self) -> str:
        return f"Update{self.exprs!r}"

    @property
    def symbolic(self) -> bool:
        return all(getattr(e, "__plan_expr__", False) for e in self.exprs)
