"""Abstract domain for the plan-IR static verifier.

Relational compilers validate column resolution, arity, and type flow
over the plan before codegen (arXiv:2502.06988); this module defines the
lattices that analysis runs over:

* **Presence** — what the schema says about one column name at one plan
  node: every row has the cell (``PRESENT``), some rows may lack it
  (``MAYBE``), or the name is not in the schema at all (``ABSENT``).
  The distinction matters because the host path's errors are *per
  streamed row* (csvplus.go:511-525): selecting an ``ABSENT`` column is
  an error only if a row actually streams, so the verifier must weigh
  presence against cardinality rather than reject outright.
* **Card** — the node's row-count lattice point: statically zero rows
  (``EMPTY``), possibly zero (``MAYBE_EMPTY``), or at least one row
  guaranteed (``NONEMPTY``).  ``EMPTY`` is the exact lattice point the
  round-5 differential suite exposed (empty selection + missing-column
  select), so every operator's transfer function is checked against it.
* **lane** — the physical column representation the device executor
  would lower against: dictionary codes (``"str"``) or typed affix
  int32 value lanes (``"int"``).  Placeholder columns (installed by
  ``SelectCols`` of a missing name over an empty selection) are tracked
  explicitly: they are 0-length and must never be gathered with live
  row ids.
* **Placement** — WHERE the column's backing array lives: ``host``
  (numpy), ``device`` (one accelerator), or ``sharded(axis)`` (a
  GSPMD-sharded array over a named mesh).  Seeded from array
  ``.sharding`` metadata exactly like the lane domain is seeded from
  column kinds — no device sync, ``.sharding`` is free to read.  The
  lattice bottom is ``unknown`` (synthetic states, fakes): unknown
  placements are never diagnosed.

The domain is deliberately cheap: states are built from table/column
*metadata* only (no device syncs — a column whose ``has_absent`` is not
yet cached is conservatively ``MAYBE``), so verification is O(plan
nodes x columns) and can run before every lowering.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Dict, Tuple


@dataclass(frozen=True)
class Placement:
    """Where a column's backing array lives.  ``axes`` names the mesh
    axes a ``sharded`` array is split over (empty when the sharding
    carries no named mesh)."""

    kind: str  # "unknown" | "host" | "device" | "sharded"
    axes: Tuple[str, ...] = ()

    _RANK = {"unknown": 0, "host": 1, "device": 2, "sharded": 3}

    def __repr__(self) -> str:
        if self.kind == "sharded" and self.axes:
            return f"sharded({','.join(self.axes)})"
        return self.kind

    @property
    def known(self) -> bool:
        return self.kind != "unknown"

    @property
    def on_device(self) -> bool:
        return self.kind in ("device", "sharded")

    @property
    def is_sharded(self) -> bool:
        return self.kind == "sharded"

    @property
    def rank(self) -> int:
        return self._RANK[self.kind]


PLACE_UNKNOWN = Placement("unknown")
PLACE_HOST = Placement("host")
PLACE_DEVICE = Placement("device")


def sharded_placement(axes: Tuple[str, ...] = ()) -> Placement:
    return Placement("sharded", tuple(str(a) for a in axes))


def placement_of_array(arr) -> Placement:
    """Placement from one backing array's metadata (never syncs).

    jax arrays expose ``.sharding``; more than one device in its
    ``device_set`` means GSPMD-sharded, one means single-device.  numpy
    arrays (no ``.sharding``) are host-resident."""
    if arr is None:
        return PLACE_UNKNOWN
    sh = getattr(arr, "sharding", None)
    if sh is None:
        return PLACE_HOST if hasattr(arr, "dtype") else PLACE_UNKNOWN
    try:
        n_dev = len(sh.device_set)
    except Exception:
        return PLACE_UNKNOWN
    if n_dev > 1:
        mesh = getattr(sh, "mesh", None)
        axes = tuple(getattr(mesh, "axis_names", ())) if mesh is not None else ()
        return sharded_placement(axes)
    return PLACE_DEVICE


def placement_of_column(column) -> Placement:
    """Placement from a live column's metadata.  An explicit
    ``column.placement`` attribute (a :class:`Placement` or kind
    string) overrides — the hook synthetic states and tests seed
    through; real columns are read from their backing arrays
    (``IntColumn.values`` / ``StringColumn`` codes)."""
    explicit = getattr(column, "placement", None)
    if isinstance(explicit, Placement):
        return explicit
    if isinstance(explicit, str):
        return Placement(explicit)
    if getattr(column, "kind", "str") == "int":
        return placement_of_array(getattr(column, "values", None))
    state = getattr(column, "_codes_state", None)
    if state:
        return placement_of_array(state[0])
    return PLACE_UNKNOWN


class Presence(enum.Enum):
    PRESENT = "present"  # every row has the cell
    MAYBE = "maybe"  # some rows may lack the cell
    ABSENT = "absent"  # name not in the schema at all

    def __repr__(self) -> str:  # compact diagnostics
        return self.value


class Card(enum.Enum):
    """Row-count lattice: EMPTY <= MAYBE_EMPTY, NONEMPTY <= MAYBE_EMPTY."""

    EMPTY = "empty"  # statically zero rows
    MAYBE_EMPTY = "maybe-empty"  # could be zero
    NONEMPTY = "nonempty"  # at least one row guaranteed

    def __repr__(self) -> str:
        return self.value

    @property
    def may_be_empty(self) -> bool:
        return self is not Card.NONEMPTY

    def narrowed(self) -> "Card":
        """The cardinality after any row-dropping operator (filter,
        windowing cut, anti-join): a NONEMPTY input may come out empty,
        an EMPTY input stays empty."""
        return Card.EMPTY if self is Card.EMPTY else Card.MAYBE_EMPTY


@dataclass(frozen=True)
class ColInfo:
    """What the verifier knows about one column at one plan node."""

    lane: str  # "str" (dictionary codes) | "int" (typed int32 lanes)
    presence: Presence
    placeholder: bool = False  # 0-length stand-in from select-of-missing
    placement: Placement = PLACE_UNKNOWN

    def __repr__(self) -> str:
        tag = f"{self.lane}/{self.presence.value}"
        if self.placement.known:
            tag += f"/{self.placement!r}"
        return f"<{tag}{'/placeholder' if self.placeholder else ''}>"


@dataclass
class NodeState:
    """The abstract relation flowing OUT of one plan node."""

    schema: Dict[str, ColInfo] = field(default_factory=dict)
    card: Card = Card.MAYBE_EMPTY

    def copy(self) -> "NodeState":
        return NodeState(dict(self.schema), self.card)

    def presence(self, name: str) -> Presence:
        info = self.schema.get(name)
        return info.presence if info is not None else Presence.ABSENT

    def with_card(self, card: Card) -> "NodeState":
        return NodeState(dict(self.schema), card)

    def row_placement(self) -> Placement:
        """Where the relation's rows predominantly live: the most
        distributed known column placement (sharded > device > host).
        This is the layout the executor materializes stage outputs on,
        so it is what downstream transfer functions compare against."""
        best = PLACE_UNKNOWN
        for info in self.schema.values():
            if info.placement.rank > best.rank:
                best = info.placement
        return best


def col_info_for(column) -> ColInfo:
    """ColInfo from a live table column, using only cached metadata.

    ``IntColumn`` never holds absent cells (typed.py's representation
    contract), so typed lanes are always PRESENT.  ``StringColumn``
    presence comes from the ``_has_absent`` cache when already known;
    an uncached value stays MAYBE rather than forcing a device sync.
    """
    place = placement_of_column(column)
    if getattr(column, "kind", "str") == "int":
        return ColInfo("int", Presence.PRESENT, placement=place)
    cached = getattr(column, "_has_absent", None)
    if cached is False:
        return ColInfo("str", Presence.PRESENT, placement=place)
    if cached is True:
        return ColInfo("str", Presence.MAYBE, placement=place)
    return ColInfo("str", Presence.MAYBE, placement=place)


def scan_state(table) -> NodeState:
    """The abstract state of a ``Scan`` node's device table."""
    schema = {name: col_info_for(col) for name, col in table.columns.items()}
    nrows = int(getattr(table, "nrows", 0))
    card = Card.NONEMPTY if nrows > 0 else Card.EMPTY
    return NodeState(schema, card)


def placeholder_col() -> ColInfo:
    """The 0-length placeholder ``SelectCols`` installs for a missing
    name over an empty selection (columnar/exec.py ``_apply_select``)."""
    return ColInfo("str", Presence.MAYBE, placeholder=True)


def demoted(info: ColInfo) -> ColInfo:
    """Lane state after a typed column is demoted to dictionary codes."""
    return replace(info, lane="str") if info.lane == "int" else info
