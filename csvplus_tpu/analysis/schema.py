"""Abstract domain for the plan-IR static verifier.

Relational compilers validate column resolution, arity, and type flow
over the plan before codegen (arXiv:2502.06988); this module defines the
lattices that analysis runs over:

* **Presence** — what the schema says about one column name at one plan
  node: every row has the cell (``PRESENT``), some rows may lack it
  (``MAYBE``), or the name is not in the schema at all (``ABSENT``).
  The distinction matters because the host path's errors are *per
  streamed row* (csvplus.go:511-525): selecting an ``ABSENT`` column is
  an error only if a row actually streams, so the verifier must weigh
  presence against cardinality rather than reject outright.
* **Card** — the node's row-count lattice point: statically zero rows
  (``EMPTY``), possibly zero (``MAYBE_EMPTY``), or at least one row
  guaranteed (``NONEMPTY``).  ``EMPTY`` is the exact lattice point the
  round-5 differential suite exposed (empty selection + missing-column
  select), so every operator's transfer function is checked against it.
* **lane** — the physical column representation the device executor
  would lower against: dictionary codes (``"str"``) or typed affix
  int32 value lanes (``"int"``).  Placeholder columns (installed by
  ``SelectCols`` of a missing name over an empty selection) are tracked
  explicitly: they are 0-length and must never be gathered with live
  row ids.

The domain is deliberately cheap: states are built from table/column
*metadata* only (no device syncs — a column whose ``has_absent`` is not
yet cached is conservatively ``MAYBE``), so verification is O(plan
nodes x columns) and can run before every lowering.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field, replace
from typing import Dict


class Presence(enum.Enum):
    PRESENT = "present"  # every row has the cell
    MAYBE = "maybe"  # some rows may lack the cell
    ABSENT = "absent"  # name not in the schema at all

    def __repr__(self) -> str:  # compact diagnostics
        return self.value


class Card(enum.Enum):
    """Row-count lattice: EMPTY <= MAYBE_EMPTY, NONEMPTY <= MAYBE_EMPTY."""

    EMPTY = "empty"  # statically zero rows
    MAYBE_EMPTY = "maybe-empty"  # could be zero
    NONEMPTY = "nonempty"  # at least one row guaranteed

    def __repr__(self) -> str:
        return self.value

    @property
    def may_be_empty(self) -> bool:
        return self is not Card.NONEMPTY

    def narrowed(self) -> "Card":
        """The cardinality after any row-dropping operator (filter,
        windowing cut, anti-join): a NONEMPTY input may come out empty,
        an EMPTY input stays empty."""
        return Card.EMPTY if self is Card.EMPTY else Card.MAYBE_EMPTY


@dataclass(frozen=True)
class ColInfo:
    """What the verifier knows about one column at one plan node."""

    lane: str  # "str" (dictionary codes) | "int" (typed int32 lanes)
    presence: Presence
    placeholder: bool = False  # 0-length stand-in from select-of-missing

    def __repr__(self) -> str:
        tag = f"{self.lane}/{self.presence.value}"
        return f"<{tag}{'/placeholder' if self.placeholder else ''}>"


@dataclass
class NodeState:
    """The abstract relation flowing OUT of one plan node."""

    schema: Dict[str, ColInfo] = field(default_factory=dict)
    card: Card = Card.MAYBE_EMPTY

    def copy(self) -> "NodeState":
        return NodeState(dict(self.schema), self.card)

    def presence(self, name: str) -> Presence:
        info = self.schema.get(name)
        return info.presence if info is not None else Presence.ABSENT

    def with_card(self, card: Card) -> "NodeState":
        return NodeState(dict(self.schema), card)


def col_info_for(column) -> ColInfo:
    """ColInfo from a live table column, using only cached metadata.

    ``IntColumn`` never holds absent cells (typed.py's representation
    contract), so typed lanes are always PRESENT.  ``StringColumn``
    presence comes from the ``_has_absent`` cache when already known;
    an uncached value stays MAYBE rather than forcing a device sync.
    """
    if getattr(column, "kind", "str") == "int":
        return ColInfo("int", Presence.PRESENT)
    cached = getattr(column, "_has_absent", None)
    if cached is False:
        return ColInfo("str", Presence.PRESENT)
    if cached is True:
        return ColInfo("str", Presence.MAYBE)
    return ColInfo("str", Presence.MAYBE)


def scan_state(table) -> NodeState:
    """The abstract state of a ``Scan`` node's device table."""
    schema = {name: col_info_for(col) for name, col in table.columns.items()}
    nrows = int(getattr(table, "nrows", 0))
    card = Card.NONEMPTY if nrows > 0 else Card.EMPTY
    return NodeState(schema, card)


def placeholder_col() -> ColInfo:
    """The 0-length placeholder ``SelectCols`` installs for a missing
    name over an empty selection (columnar/exec.py ``_apply_select``)."""
    return ColInfo("str", Presence.MAYBE, placeholder=True)


def demoted(info: ColInfo) -> ColInfo:
    """Lane state after a typed column is demoted to dictionary codes."""
    return replace(info, lane="str") if info.lane == "int" else info
