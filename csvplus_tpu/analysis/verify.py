"""Static verifier for the symbolic plan IR.

Walks a plan chain (:mod:`csvplus_tpu.plan`) BEFORE device lowering and
checks, per node, against the abstract domain in :mod:`.schema`:

* **resolution** — every column named by ``SelectCols``, predicate
  stages, and ``Join``/``Except`` keys resolves in the inferred schema,
  with the host path's per-streamed-row semantics: a missing name over
  a statically empty relation is NOT an error (it normalizes to an
  empty result with a placeholder column, csvplus.go:511-525), over a
  provably nonempty relation it is a deterministic runtime error, and
  in between it is a data-dependent risk.  The verifier never turns a
  host-runtime error into a static rejection — parity wins — it makes
  the outcome *known* before lowering.
* **lane-flow** — dictionary-code vs typed-int32 lanes are tracked
  through every operator so lowering never meets an impossible
  combination unannounced (e.g. a rename-merge of a typed lane onto a
  dictionary column, or a typed stream key probing a packed dictionary
  index — both force demotion).
* **empty-relation** — every operator is evaluated at the ``nrows == 0``
  lattice point against an explicit :class:`ExecutorModel` of the
  executor's empty-input guarantees.  The round-5 differential crash
  (empty selection + placeholder columns + a predicate gather) is
  exactly a violation of this rule under the pre-fix model.
* **placement-flow** — per-column placement (host / single-device /
  sharded, :class:`~.schema.Placement`) is tracked through every
  operator and cross-placement hazards are predicted BEFORE lowering:
  a sharded stream probing a single-device packed index (info when the
  build side merely replicates, warn when the partitioned tier implies
  a full ``all_to_all`` reshard of the probe keys — threshold shared
  with the executor via ``parallel.pjoin.partition_tier_selected``), a
  rename-merge across placements, and a host-placed stage sandwiched
  between device stages (an implied gather + re-upload).  Unknown
  placements (synthetic states, fakes) are never diagnosed.
* **divergence-risk** — plan shapes with no *random* differential
  coverage (stage kinds, chain depth, typed lanes under predicates) are
  flagged as info so the harness's blind spots are visible per plan.

Verdict contract with the differential harness: on any plan,

* no ``error``/``warn`` diagnostics  =>  host and device both succeed;
* ``predicts_empty``                 =>  both produce zero rows;
* a host-side runtime column error   =>  a ``resolution`` diagnostic
  exists (the verifier anticipated it).

``verify_before_lower`` is the executor hook: unlowerable plans raise
:class:`~csvplus_tpu.columnar.exec.UnsupportedPlan` up front (same
fallback the executor would take mid-plan, minus the wasted device
work).  ``CSVPLUS_VERIFY=0`` disables the hook.
"""

from __future__ import annotations

import enum
import os
from dataclasses import dataclass, field, replace
from typing import Any, Dict, List, Optional, Tuple

from .. import plan as P
from ..exprs import Rename, SetValue, Update
from ..predicates import All, Any_, Like, Not
from ..utils.env import env_str
from .schema import (
    PLACE_UNKNOWN,
    Card,
    ColInfo,
    NodeState,
    Presence,
    demoted,
    placeholder_col,
    scan_state,
)

__all__ = [
    "Diagnostic",
    "ExecutorModel",
    "EXECUTOR_MODEL",
    "PlanReport",
    "verify_plan",
    "verify_before_lower",
]


# The random differential generator's coverage envelope
# (tests/test_differential.py ``stages()``): anything outside it gets a
# divergence-risk note.
DIFF_COVERED_STAGES = frozenset(
    [
        "Filter",
        "SelectCols",
        "DropCols",
        "Top",
        "DropRows",
        "MapExpr",
        "TakeWhile",
        "DropWhile",
        "Join",
        "Except",
        "Validate",
    ]
)
DIFF_MAX_STAGES = 4


@dataclass(frozen=True)
class ExecutorModel:
    """The empty-input guarantees the device executor is modelled to
    uphold; each flag names a concrete code location.  Tests pin the
    pre-round-6 executor by flipping flags off — the verifier then
    reports the exact historical crash as an ``empty-relation`` error.
    """

    # columnar/exec.py _sel_mask: an empty selection short-circuits to an
    # empty mask instead of padding with row id 0 (the round-5 crash).
    empty_selection_masks: bool = True
    # ops/join.py join_tables: nrows == 0 stream returns an empty result
    # before any key validation (csvplus.go:553-556 parity).
    join_empty_total: bool = True
    # ops/join.py except_mask reached through a 0-row key view is total.
    except_empty_total: bool = True
    # parallel/pjoin.py partitioned_probe: the all_to_all tier
    # answers on the mesh (O(1) scalar syncs only).  A stale False pins
    # the pre-device-orchestration tier that synced answers through
    # host — the verifier then warns on every partitioned-tier probe.
    partitioned_probe_device_resident: bool = True
    # ops/join.py _lanes_for/_aligned_codes: below the partition
    # threshold the build side replicates onto the
    # probe mesh with no host hop.  A stale False makes every
    # sharded-stream broadcast probe a placement-flow warn — which the
    # differential verdict contract then falsifies (the sharded random
    # suite executes those plans with no host fallback).
    broadcast_replication_on_device: bool = True


EXECUTOR_MODEL = ExecutorModel()


@dataclass(frozen=True)
class Diagnostic:
    rule: str  # "resolution" | "lane-flow" | "placement-flow" | "empty-relation" | "divergence-risk" | "unlowerable"
    severity: str  # "error" | "warn" | "info"
    stage: str  # e.g. "Filter[2]" — node type + 0-based chain position
    message: str

    def __str__(self) -> str:
        return f"[{self.severity}] {self.rule} @ {self.stage}: {self.message}"


class _Truth(enum.Enum):
    FALSE = 0
    TRUE = 1
    UNKNOWN = 2


def _pred_truth(pred, state: NodeState) -> _Truth:
    """Constant-fold a DSL predicate against the abstract schema.

    The only static facts are structural: ``Like`` over an ABSENT column
    is constant-false for every row (host semantics: a row without the
    key never matches, csvplus.go:1284-1292).  Everything else is
    data-dependent and stays UNKNOWN.
    """
    if isinstance(pred, Like):
        if any(state.presence(c) is Presence.ABSENT for c in pred.match):
            return _Truth.FALSE
        return _Truth.UNKNOWN
    if isinstance(pred, All):
        vals = [_pred_truth(p, state) for p in pred.preds]
        if _Truth.FALSE in vals:
            return _Truth.FALSE
        return _Truth.TRUE if all(v is _Truth.TRUE for v in vals) else _Truth.UNKNOWN
    if isinstance(pred, Any_):
        vals = [_pred_truth(p, state) for p in pred.preds]
        if _Truth.TRUE in vals:
            return _Truth.TRUE
        return _Truth.FALSE if vals and all(v is _Truth.FALSE for v in vals) else _Truth.UNKNOWN
    if isinstance(pred, Not):
        v = _pred_truth(pred.pred, state)
        if v is _Truth.FALSE:
            return _Truth.TRUE
        if v is _Truth.TRUE:
            return _Truth.FALSE
    return _Truth.UNKNOWN


@dataclass
class PlanReport:
    """Everything the verifier derived from one plan."""

    diagnostics: List[Diagnostic] = field(default_factory=list)
    # abstract state AFTER each chain node, aligned with plan.linearize
    states: List[NodeState] = field(default_factory=list)

    @property
    def final(self) -> NodeState:
        return self.states[-1]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warn"]

    def by_rule(self, rule: str) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.rule == rule]

    @property
    def predicts_empty(self) -> bool:
        """True when the verifier proves the plan yields zero rows on
        the success path AND no deterministic/ data-dependent error was
        flagged — i.e. host and device must both return exactly []."""
        return self.final.card is Card.EMPTY and not self.errors and not self.warnings

    @property
    def ok(self) -> bool:
        return not self.errors

    def describe(self) -> str:
        if not self.diagnostics:
            return "(plan verifies clean)"
        return "\n".join(str(d) for d in self.diagnostics)


class _Verifier:
    def __init__(self, model: ExecutorModel):
        self.model = model
        self.report = PlanReport()
        self._stage_label = "Scan[0]"

    def diag(self, rule: str, severity: str, message: str) -> None:
        self.report.diagnostics.append(
            Diagnostic(rule, severity, self._stage_label, message)
        )

    # ---- per-rule helpers -------------------------------------------

    def _resolve_required(self, state: NodeState, name: str, what: str) -> None:
        """Resolution rule for a column the host path demands per
        streamed row (SelectCols / Join / Except keys)."""
        presence = state.presence(name)
        if presence is Presence.ABSENT:
            if state.card is Card.NONEMPTY:
                self.diag(
                    "resolution",
                    "warn",
                    f'{what} of missing column "{name}" over a provably nonempty '
                    "relation — deterministic runtime error on host and device",
                )
            elif state.card is Card.MAYBE_EMPTY:
                self.diag(
                    "resolution",
                    "warn",
                    f'{what} of column "{name}" absent from the schema — errors '
                    "on the first streamed row if any row survives upstream",
                )
            else:
                self.diag(
                    "resolution",
                    "info",
                    f'{what} of missing column "{name}" over a statically empty '
                    "relation normalizes to an empty result (placeholder column)",
                )
        elif presence is Presence.MAYBE:
            self.diag(
                "resolution",
                "info",
                f'{what} of column "{name}" with possibly-absent cells — '
                "data-dependent per-row error",
            )

    def _check_pred(self, state: NodeState, pred, what: str) -> Optional[List[str]]:
        """Shared predicate checks; returns referenced columns or None
        when the predicate is unlowerable."""
        from ..ops.filter import predicate_columns

        cols = predicate_columns(pred)
        if cols is None:
            self.diag(
                "unlowerable",
                "error",
                f"{what} predicate {pred!r} cannot be lowered to a device mask",
            )
            return None
        for c in cols:
            info = state.schema.get(c)
            if info is None:
                # host semantics: Like over a missing column is False —
                # legal, and often the source of a statically empty branch
                self.diag(
                    "resolution",
                    "info",
                    f'{what} references column "{c}" absent from the schema '
                    "(constant-false Like term — host semantics)",
                )
            else:
                if info.placeholder:
                    self._check_empty_gather(state, c, what)
                # typed int32 lanes under predicates are inside the random
                # differential envelope since the typed-ingest generator
                # (tests/test_differential.py) — no divergence note
        return cols

    def _check_empty_gather(self, state: NodeState, name: str, what: str) -> None:
        """Empty-relation rule: a predicate gather over a placeholder
        column is only defined when the executor short-circuits empty
        selections (the round-5 differential crash when it did not)."""
        if self.model.empty_selection_masks:
            self.diag(
                "empty-relation",
                "info",
                f'{what} over placeholder column "{name}" at the nrows==0 '
                "lattice point — normalized by the executor's empty-selection "
                "short-circuit (_sel_mask)",
            )
        else:
            self.diag(
                "empty-relation",
                "error",
                f'{what} over 0-length placeholder column "{name}" with an '
                "empty selection: the narrow-selection pad gathers row 0 from "
                "an empty axis (device crash; host returns no rows)",
            )

    # ---- transfer functions -----------------------------------------

    def transfer(self, node: P.PlanNode, state: NodeState, is_last: bool) -> NodeState:
        if isinstance(node, P.Filter):
            cols = self._check_pred(state, node.pred, "Filter")
            if cols is None:
                return state.with_card(state.card.narrowed())
            t = _pred_truth(node.pred, state)
            if t is _Truth.FALSE:
                return state.with_card(Card.EMPTY)
            if t is _Truth.TRUE:
                return state
            return state.with_card(state.card.narrowed())

        if isinstance(node, P.Validate):
            if not is_last:
                self.diag(
                    "unlowerable",
                    "error",
                    "Validate is device-lowered only as the last stage "
                    "(host push semantics upstream of other stages)",
                )
            self._check_pred(state, node.pred, "Validate")
            # unless the predicate is statically TRUE (or no row can
            # reach it), a clean report does NOT imply the run succeeds:
            # validation aborts are data-dependent by design
            if (
                state.card is not Card.EMPTY
                and _pred_truth(node.pred, state) is not _Truth.TRUE
            ):
                self.diag(
                    "data-dependent",
                    "info",
                    "Validate may abort the pipeline on any failing row "
                    "(identical error on both executors)",
                )
            return state

        if isinstance(node, (P.TakeWhile, P.DropWhile)):
            kind = type(node).__name__
            self._check_pred(state, node.pred, kind)
            t = _pred_truth(node.pred, state)
            if isinstance(node, P.TakeWhile):
                if t is _Truth.FALSE:  # cut at row 0
                    return state.with_card(Card.EMPTY)
                if t is _Truth.TRUE:
                    return state
            else:
                if t is _Truth.TRUE:  # drops every row
                    return state.with_card(Card.EMPTY)
                if t is _Truth.FALSE:
                    return state
            return state.with_card(state.card.narrowed())

        if isinstance(node, P.Top):
            if node.n <= 0:
                return state.with_card(Card.EMPTY)
            return state  # top(n>=1) preserves NONEMPTY

        if isinstance(node, P.DropRows):
            if node.n <= 0:
                return state
            return state.with_card(state.card.narrowed())

        if isinstance(node, P.SelectCols):
            for c in node.columns:
                self._resolve_required(state, c, "select_columns")
            out: Dict[str, ColInfo] = {}
            card = state.card
            for c in node.columns:
                info = state.schema.get(c)
                if info is None:
                    out[c] = placeholder_col()
                    # the success path of select-of-missing is the empty
                    # relation (per-row error otherwise)
                    card = Card.EMPTY
                else:
                    # success implies every streamed row had the cell
                    out[c] = replace(info, presence=Presence.PRESENT)
            return NodeState(out, card)

        if isinstance(node, P.DropCols):
            out = {
                n: i for n, i in state.schema.items() if n not in set(node.columns)
            }
            return NodeState(out, state.card)

        if isinstance(node, P.MapExpr):
            return self._transfer_map(node.expr, state)

        if isinstance(node, P.Join):
            return self._transfer_join(node, state)

        if isinstance(node, P.MultiwayJoin):
            return self._transfer_multiway(node, state)

        if isinstance(node, P.FusedProbe):
            return self._transfer_fused(node, state)

        if isinstance(node, P.Except):
            return self._transfer_except(node, state)

        self.diag(
            "unlowerable",
            "error",
            f"no device lowering for {type(node).__name__}",
        )
        return state

    def _transfer_map(self, expr, state: NodeState) -> NodeState:
        if isinstance(expr, Update):
            for e in expr.exprs:
                state = self._transfer_map(e, state)
            return state
        if isinstance(expr, SetValue):
            out = dict(state.schema)
            prev = out.get(expr.column)
            if prev is not None and prev.lane == "int":
                self.diag(
                    "lane-flow",
                    "info",
                    f'SetValue replaces typed int32 lane "{expr.column}" with a '
                    "dictionary constant column",
                )
            # the constant column materializes on the stream's layout
            out[expr.column] = ColInfo(
                "str", Presence.PRESENT, placement=state.row_placement()
            )
            return NodeState(out, state.card)
        if isinstance(expr, Rename):
            out = dict(state.schema)
            for old, new in expr.mapping.items():
                if old not in out:
                    continue  # host: row-level no-op when the cell is absent
                moved = out.pop(old)
                existing = out.pop(new, None)
                if existing is not None and moved.presence is not Presence.PRESENT:
                    # exec merges with fallback only when the moved column
                    # can have absent cells; mixed lanes demote to codes
                    if moved.lane != existing.lane:
                        self.diag(
                            "lane-flow",
                            "warn",
                            f'rename "{old}"->"{new}" merges a {moved.lane} lane '
                            f"onto a {existing.lane} lane — demotion to "
                            "dictionary codes at lowering",
                        )
                        moved = demoted(moved)
                    if (
                        moved.placement.known
                        and existing.placement.known
                        and moved.placement != existing.placement
                    ):
                        self.diag(
                            "placement-flow",
                            "warn",
                            f'rename "{old}"->"{new}" merges a '
                            f"{moved.placement!r}-placed column onto a "
                            f"{existing.placement!r}-placed column — the "
                            "fallback merge implies a transfer to one layout",
                        )
                out[new] = moved
            return NodeState(out, state.card)
        self.diag(
            "unlowerable", "error", f"cannot lower map expression {expr!r} to device"
        )
        return state

    def _index_info(
        self, index, kind: str
    ) -> "Optional[Tuple[Dict[str, str], Tuple[str, ...], bool, Optional[dict]]]":
        from ..ops.join import device_index_static_info

        info = device_index_static_info(index)
        if info is None or not info[2]:
            self.diag(
                "unlowerable",
                "error",
                f"{kind} build side has no packed device index",
            )
            return None
        return info

    def _check_placement_probe(
        self, state: NodeState, meta: "Optional[dict]", what: str
    ) -> None:
        """placement-flow rule for a probe (Join/Except) stage: compare
        where the stream rows live against where the build side's packed
        keys live and predict the executor's tier choice."""
        if meta is None:
            return
        stream = state.row_placement()
        idx_place = meta.get("placement", PLACE_UNKNOWN)
        if not stream.known or not idx_place.known:
            return
        if stream.is_sharded and not idx_place.is_sharded:
            from ..parallel.pjoin import partition_tier_selected

            n_keys = meta.get("packed_keys")
            min_keys = meta.get("partition_min_keys") or 0
            if n_keys is not None and partition_tier_selected(
                n_keys, stream_sharded=True, min_keys=min_keys
            ):
                if self.model.partitioned_probe_device_resident:
                    self.diag(
                        "placement-flow",
                        "warn",
                        f"sharded stream probes a {idx_place.kind}-placed "
                        f"{what} index of {n_keys} keys — the partitioned "
                        "tier implies a full all_to_all reshard of the "
                        "probe keys",
                    )
                else:
                    self.diag(
                        "placement-flow",
                        "warn",
                        f"sharded stream probes a {idx_place.kind}-placed "
                        f"{what} index of {n_keys} keys — modelled "
                        "partitioned tier syncs answers through host "
                        "(full gather)",
                    )
            elif self.model.broadcast_replication_on_device:
                self.diag(
                    "placement-flow",
                    "info",
                    f"sharded stream probes a {idx_place.kind}-placed "
                    f"{what} index — build side replicates onto the probe "
                    "mesh (benign broadcast, no host hop)",
                )
            else:
                self.diag(
                    "placement-flow",
                    "warn",
                    f"sharded stream probes a {idx_place.kind}-placed "
                    f"{what} index — modelled broadcast tier gathers the "
                    "probe keys to one device",
                )
        elif stream.kind == "host" and idx_place.on_device:
            self.diag(
                "placement-flow",
                "warn",
                f"host-placed stream probes a {idx_place!r} {what} index — "
                "implied full upload of the probe keys at lowering",
            )
        elif stream.on_device and idx_place.kind == "host":
            self.diag(
                "placement-flow",
                "warn",
                f"{stream!r} stream probes a host-placed {what} index — "
                "implied full gather of the probe keys at lowering",
            )
        elif stream.kind == "device" and idx_place.is_sharded:
            self.diag(
                "placement-flow",
                "info",
                f"single-device stream probes a {idx_place!r} {what} index "
                "— answers replicate back to the stream device (benign)",
            )

    def _check_keys(
        self, columns, state: NodeState, what: str, index_kinds
    ) -> None:
        for c in columns:
            self._resolve_required(state, c, f"{what} key")
            info = state.schema.get(c)
            if info is not None:
                if info.placeholder:
                    self.diag(
                        "divergence-risk",
                        "info",
                        f'placeholder column "{c}" flows into a {what} key — '
                        "no differential coverage for this shape",
                    )
                if info.lane == "int" and index_kinds is not None:
                    # packed index keys are dictionary-coded by build
                    # (DeviceIndex.build demands code order == value order)
                    self.diag(
                        "lane-flow",
                        "warn",
                        f'typed int32 stream key "{c}" probes a packed '
                        f"dictionary {what} index — demotion (or host "
                        "fallback) at lowering",
                    )

    def _join_schema_step(
        self, index, columns, state: NodeState, what: str
    ) -> NodeState:
        """One build side's full join transfer: key resolution, probe
        placement, empty-stream model check, and the output schema.  The
        unit ``Join`` applies once and ``MultiwayJoin`` folds per
        dimension IN SPEC ORDER — the fused operator's abstract
        semantics are exactly the cascade's (same card lattice walk,
        same presence/lane/placement outcomes), which is what makes the
        rewriter's verdict-equivalence re-check hold by construction."""
        info = self._index_info(index, what)
        index_kinds = info[0] if info is not None else None
        self._check_keys(columns, state, what, index_kinds)
        self._check_placement_probe(
            state, info[3] if info is not None else None, what
        )
        if not self.model.join_empty_total and state.card.may_be_empty:
            self.diag(
                "empty-relation",
                "error",
                f"{what} over a possibly-empty stream requires the executor's "
                "nrows==0 early-out (join_tables)",
            )
        # the joined relation materializes on the STREAM's layout (the
        # build side replicates or answers through the partitioned
        # shuffle; either way output columns follow the probe rows)
        stream_place = state.row_placement()
        out: Dict[str, ColInfo] = {}
        if index_kinds is not None:
            for n, kind in index_kinds.items():
                out[n] = ColInfo(kind, Presence.MAYBE, placement=stream_place)
        for n, i in state.schema.items():
            if n in out and out[n].lane != i.lane:
                # stream-wins merge across lanes settles on codes
                out[n] = ColInfo("str", Presence.MAYBE, placement=stream_place)
            else:
                out[n] = replace(i, presence=Presence.MAYBE)
        for c in columns:
            if c in out:
                out[c] = replace(out[c], presence=Presence.PRESENT)
        card = Card.EMPTY if state.card is Card.EMPTY else Card.MAYBE_EMPTY
        return NodeState(out, card)

    def _transfer_join(self, node: P.Join, state: NodeState) -> NodeState:
        return self._join_schema_step(node.index, node.columns, state, "join")

    def _transfer_multiway(
        self, node: P.MultiwayJoin, state: NodeState
    ) -> NodeState:
        for index, columns in node.joins:
            state = self._join_schema_step(index, columns, state, "join")
        return state

    def _transfer_fused(
        self, node: P.FusedProbe, state: NodeState
    ) -> NodeState:
        """The fused probe pass (ISSUE 19) folds its absorbed ops'
        transfers via ``fused_op_node`` — each op's abstract step IS its
        standalone stage's, BY CONSTRUCTION — then the join schema step
        per dimension like MultiwayJoin.  The rewriter's verdict
        re-check therefore holds structurally: fusing a licensed run
        folds exactly the transfers the staged chain folded, in the
        same order (diagnostics attribute to the FusedProbe's label)."""
        for kind, payload in node.ops:
            sub = P.fused_op_node(kind, payload)
            if sub is None:
                self.diag(
                    "unlowerable",
                    "error",
                    f"no device lowering for fused op {kind!r}",
                )
                continue
            state = self.transfer(sub, state, is_last=False)
        for index, columns in node.joins:
            state = self._join_schema_step(index, columns, state, "join")
        return state

    def _transfer_except(self, node: P.Except, state: NodeState) -> NodeState:
        info = self._index_info(node.index, "except")
        index_kinds = info[0] if info is not None else None
        self._check_keys(node.columns, state, "except", index_kinds)
        self._check_placement_probe(
            state, info[3] if info is not None else None, "except"
        )
        if not self.model.except_empty_total and state.card.may_be_empty:
            self.diag(
                "empty-relation",
                "error",
                "except over a possibly-empty stream requires a total "
                "empty-input anti-join mask (except_mask)",
            )
        return state.with_card(state.card.narrowed())

    # ---- driver ------------------------------------------------------

    def run(self, root: P.PlanNode) -> PlanReport:
        chain = P.linearize(root)
        scan = chain[0]
        assert isinstance(scan, (P.Scan, P.Lookup))
        state = scan_state(scan.table)
        if isinstance(scan, P.Lookup):
            # the leaf is a statically-known [lower, upper) row range of
            # the index table: its cardinality is exact, not the table's
            state = state.with_card(
                Card.NONEMPTY if scan.upper > scan.lower else Card.EMPTY
            )
        self.report.states.append(state)
        n_stages = len(chain) - 1
        for pos, node in enumerate(chain[1:], start=1):
            self._stage_label = P.stage_label(pos, node)
            state = self.transfer(node, state, is_last=pos == n_stages)
            self.report.states.append(state)
        self._host_sandwich(chain)
        self._divergence_risk(chain)
        self._publish_counters()
        return self.report

    def _host_sandwich(self, chain: List[P.PlanNode]) -> None:
        """placement-flow rule: a host-placed stage output between two
        device-placed ones means the lowered pipeline would gather off
        the device mid-chain and re-upload — the one placement shape
        that costs TWO transfers instead of zero."""
        places = [s.row_placement() for s in self.report.states]
        on_dev = [p.on_device for p in places]
        for i in range(1, len(places) - 1):
            if places[i].kind != "host":
                continue
            if any(on_dev[:i]) and any(on_dev[i + 1 :]):
                self._stage_label = P.stage_label(i, chain[i])
                self.diag(
                    "placement-flow",
                    "warn",
                    "host-placed stage sandwiched between device stages — "
                    "implied mid-chain gather + re-upload at lowering",
                )

    def _divergence_risk(self, chain: List[P.PlanNode]) -> None:
        self._stage_label = "plan"
        n_stages = len(chain) - 1
        if n_stages > DIFF_MAX_STAGES:
            self.diag(
                "divergence-risk",
                "info",
                f"chain of {n_stages} stages exceeds the random differential "
                f"vocabulary (max {DIFF_MAX_STAGES})",
            )
        uncovered = sorted(
            {
                type(n).__name__
                for n in chain[1:]
                if type(n).__name__ not in DIFF_COVERED_STAGES
            }
        )
        for name in uncovered:
            self.diag(
                "divergence-risk",
                "info",
                f"stage {name} has no random differential coverage "
                "(fixed-shape tests only)",
            )

    def _publish_counters(self) -> None:
        from ..utils.observe import telemetry

        telemetry.count("verify.plans")
        for d in self.report.diagnostics:
            telemetry.count(f"verify.{d.rule}.{d.severity}")


def verify_plan(
    root: P.PlanNode, model: ExecutorModel = EXECUTOR_MODEL
) -> PlanReport:
    """Statically verify a plan chain; see the module docstring for the
    rule set and the verdict contract."""
    return _Verifier(model).run(root)


def _verify_enabled() -> bool:
    return env_str("CSVPLUS_VERIFY", "1") != "0"


def verify_before_lower(root: P.PlanNode) -> "Optional[PlanReport]":
    """Executor hook: verify *root* and raise ``UnsupportedPlan`` for
    plans the executor could not lower anyway — BEFORE any device work.

    Resolution/lane/empty findings never raise here: their runtime
    outcome (including exact host-parity error row numbers) belongs to
    the executor.  ``CSVPLUS_VERIFY=0`` bypasses verification entirely.
    """
    if not _verify_enabled():
        return None
    report = verify_plan(root)
    unlowerable = report.by_rule("unlowerable")
    if unlowerable:
        from ..columnar.exec import UnsupportedPlan

        raise UnsupportedPlan(unlowerable[0].message)
    return report
