"""Machine-readable analysis reports for CI: the ``--json`` CLI payload.

``json_payload`` bundles (a) the repo AST lint over the package tree and
(b) plan-IR verifier reports for a fixed set of example chains mirroring
``examples/quickstart.py`` and ``examples/sharded_join.py`` — the same
stage shapes users actually run, built over tiny deterministic corpora
so the payload is stable and committable.  ``make analyze`` compares the
payload against ``tests/data/analyze_snapshot.json`` so diagnostic drift
(a new rule firing, a transfer function changing a verdict) shows up as
a reviewable diff instead of silently shifting runtime behavior.

The mesh-sharded chain needs 8 visible devices (the hermetic CPU mesh:
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``,
exactly what ``make analyze`` and tests/conftest.py set up); with fewer
devices it is skipped and ``plans`` notes why.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

from .astlint import lint_paths
from .verify import PlanReport, verify_plan

SCHEMA_VERSION = 1

_PACKAGE_DIR = Path(__file__).resolve().parent.parent
_REPO_ROOT = _PACKAGE_DIR.parent


def default_lint_paths() -> List[Path]:
    """The package tree itself, resolved from THIS file — not the cwd —
    so ``make lint`` can never miss a newly added module."""
    return [_PACKAGE_DIR]


def lint_json(paths: Optional[List] = None) -> List[dict]:
    findings = lint_paths(paths if paths is not None else default_lint_paths())
    out = []
    for f in findings:
        p = Path(f.path)
        try:
            rel = p.resolve().relative_to(_REPO_ROOT).as_posix()
        except ValueError:
            rel = p.as_posix()
        out.append(
            {"code": f.code, "path": rel, "line": f.line, "message": f.message}
        )
    return out


def report_json(report: PlanReport) -> dict:
    return {
        "diagnostics": [
            {
                "rule": d.rule,
                "severity": d.severity,
                "stage": d.stage,
                "message": d.message,
            }
            for d in report.diagnostics
        ],
        "final_card": report.final.card.value,
        "row_placement": repr(report.final.row_placement()),
        "predicts_empty": report.predicts_empty,
        "ok": report.ok,
    }


def _mini_corpus():
    people = [
        {"id": str(i), "name": n, "surname": s}
        for i, (n, s) in enumerate(
            [("Amelia", "Smith"), ("Amelia", "Jones"), ("Jack", "Taylor")]
        )
    ]
    stock = [
        {"prod_id": "0", "product": "orange", "price": "0.03"},
        {"prod_id": "1", "product": "apple", "price": "0.02"},
    ]
    orders = [
        {
            "order_id": str(i),
            "cust_id": str(i % 3),
            "prod_id": str(i % 2),
            "qty": str(i % 9 + 1),
        }
        for i in range(64)
    ]
    return people, stock, orders


def example_plan_reports() -> Dict[str, object]:
    """Verifier reports (or a skip-reason string) per example chain."""
    import jax

    from .. import plan as P
    from ..columnar.table import DeviceTable
    from ..exprs import SetValue
    from ..predicates import Like
    from ..row import Row
    from ..source import take_rows

    people, stock, orders = _mini_corpus()

    def index_on(rows, *cols):
        idx = take_rows([Row(r) for r in rows]).index_on(*cols)
        idx.on_device("cpu")
        return idx

    people_t = DeviceTable.from_rows(people, device="cpu")
    orders_t = DeviceTable.from_rows(orders, device="cpu")
    cust_idx = index_on(people, "id")
    prod_idx = index_on(stock, "prod_id")

    out: Dict[str, object] = {}
    # examples/quickstart.py example 1: filter + map + projection
    out["quickstart-filter-map"] = verify_plan(
        P.SelectCols(
            P.MapExpr(
                P.Filter(P.Scan(people_t), Like({"name": "Amelia"})),
                SetValue("name", "Julia"),
            ),
            ("name", "surname"),
        )
    )
    # examples/quickstart.py example 2: the 3-table join
    out["quickstart-join"] = verify_plan(
        P.Join(P.Join(P.Scan(orders_t), cust_idx, ("cust_id",)), prod_idx, ())
    )
    # examples/sharded_join.py: mesh-sharded stream probing a
    # single-device index (the benign-replication placement shape)
    if len(jax.devices()) >= 8:
        from ..parallel.mesh import make_mesh

        sharded_t = orders_t.with_sharding(make_mesh(8))
        out["sharded-join"] = verify_plan(
            P.Top(
                P.Filter(
                    P.Join(
                        P.SelectCols(P.Scan(sharded_t), ("cust_id", "qty")),
                        cust_idx,
                        ("cust_id",),
                    ),
                    Like({"name": "Amelia"}),
                ),
                5,
            )
        )
    else:
        out["sharded-join"] = "skipped: fewer than 8 visible devices"
    # the r08 serving tier's plan-query shape: a Lookup leaf (one
    # contiguous index range) with a downstream filter + projection —
    # exactly what the plan-executable cache admits, so the snapshot
    # pins the verdict the cache's admission check relies on.  Needs a
    # lazy device index (eager ones carry no Lookup plan), hence the
    # on_device-then-index_on build order.
    serve_idx = take_rows([Row(r) for r in people]).on_device("cpu").index_on("id")
    lookup_plan = serve_idx.find("1").plan
    if lookup_plan is not None:
        out["serve-lookup-filter"] = verify_plan(
            P.SelectCols(
                P.Filter(lookup_plan, Like({"name": "Amelia"})),
                ("name", "surname"),
            )
        )
    else:
        out["serve-lookup-filter"] = "skipped: index has no device plan"
    return out


def json_payload(paths: Optional[List] = None) -> dict:
    """The full ``--json`` CLI payload (see docs/ANALYSIS.md schema)."""
    plans = {}
    for name, rep in sorted(example_plan_reports().items()):
        plans[name] = (
            {"skipped": rep} if isinstance(rep, str) else report_json(rep)
        )
    return {
        "schema": SCHEMA_VERSION,
        "lint": lint_json(paths),
        "plans": plans,
    }
