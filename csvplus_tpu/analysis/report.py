"""Machine-readable analysis reports for CI: the ``--json`` CLI payload.

``json_payload`` bundles (a) the repo AST lint over the package tree and
(b) per-plan analysis for a fixed set of example chains mirroring
``examples/quickstart.py`` and ``examples/sharded_join.py`` — the same
stage shapes users actually run, built over tiny deterministic corpora
so the payload is stable and committable.  Each plan entry carries the
verifier report, the provenance table (:mod:`.provenance` — per-stage
column footprints and shape bits), the cost table (:mod:`.cost` —
cardinality and per-placement bytes; sketches pinned empty so the
payload never depends on process history), and the rewrite decision
(:mod:`.rewrite` — what applied, what was blocked and by which stage).
``make analyze`` compares the payload against
``tests/data/analyze_snapshot.json`` so diagnostic drift (a new rule
firing, a transfer function changing a verdict, a rewrite flipping
between applied and blocked) shows up as a reviewable diff instead of
silently shifting runtime behavior.

The mesh-sharded chain needs 8 visible devices (the hermetic CPU mesh:
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``,
exactly what ``make analyze`` and tests/conftest.py set up); with fewer
devices it is skipped and ``plans`` notes why.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional

from .astlint import lint_paths
from .verify import PlanReport, verify_plan

SCHEMA_VERSION = 3

_PACKAGE_DIR = Path(__file__).resolve().parent.parent
_REPO_ROOT = _PACKAGE_DIR.parent


def default_lint_paths() -> List[Path]:
    """The package tree itself, resolved from THIS file — not the cwd —
    so ``make lint`` can never miss a newly added module."""
    return [_PACKAGE_DIR]


def lint_json(paths: Optional[List] = None) -> List[dict]:
    # global checks (allowlist staleness, ENV registry drift) only make
    # sense over the whole package tree — explicit path subsets would
    # report spurious "stale allowlist entry" findings for files not
    # being linted
    findings = lint_paths(
        paths if paths is not None else default_lint_paths(),
        global_checks=paths is None,
    )
    out = []
    for f in findings:
        p = Path(f.path)
        try:
            rel = p.resolve().relative_to(_REPO_ROOT).as_posix()
        except ValueError:
            rel = p.as_posix()
        out.append(
            {"code": f.code, "path": rel, "line": f.line, "message": f.message}
        )
    return out


def report_json(report: PlanReport) -> dict:
    return {
        "diagnostics": [
            {
                "rule": d.rule,
                "severity": d.severity,
                "stage": d.stage,
                "message": d.message,
            }
            for d in report.diagnostics
        ],
        "final_card": report.final.card.value,
        "row_placement": repr(report.final.row_placement()),
        "predicts_empty": report.predicts_empty,
        "ok": report.ok,
    }


def _mini_corpus():
    people = [
        {"id": str(i), "name": n, "surname": s}
        for i, (n, s) in enumerate(
            [("Amelia", "Smith"), ("Amelia", "Jones"), ("Jack", "Taylor")]
        )
    ]
    stock = [
        {"prod_id": "0", "product": "orange", "price": "0.03"},
        {"prod_id": "1", "product": "apple", "price": "0.02"},
    ]
    orders = [
        {
            "order_id": str(i),
            "cust_id": str(i % 3),
            "prod_id": str(i % 2),
            "qty": str(i % 9 + 1),
        }
        for i in range(64)
    ]
    return people, stock, orders


def example_plans() -> Dict[str, object]:
    """Plan roots (or a skip-reason string) per example chain name —
    the corpus ``--json`` and ``explain`` both analyze."""
    import jax

    from .. import plan as P
    from ..columnar.table import DeviceTable
    from ..exprs import SetValue
    from ..predicates import Like
    from ..row import Row
    from ..source import take_rows

    people, stock, orders = _mini_corpus()

    def index_on(rows, *cols):
        idx = take_rows([Row(r) for r in rows]).index_on(*cols)
        idx.on_device("cpu")
        return idx

    people_t = DeviceTable.from_rows(people, device="cpu")
    orders_t = DeviceTable.from_rows(orders, device="cpu")
    cust_idx = index_on(people, "id")
    prod_idx = index_on(stock, "prod_id")

    out: Dict[str, object] = {}
    # examples/quickstart.py example 1: filter + map + projection
    out["quickstart-filter-map"] = P.SelectCols(
        P.MapExpr(
            P.Filter(P.Scan(people_t), Like({"name": "Amelia"})),
            SetValue("name", "Julia"),
        ),
        ("name", "surname"),
    )
    # examples/quickstart.py example 2: the 3-table join
    out["quickstart-join"] = P.Join(
        P.Join(P.Scan(orders_t), cust_idx, ("cust_id",)), prod_idx, ()
    )
    # ISSUE 19: the probe-fusion shape — a filter + map run on the fact
    # side absorbed into the probe pass (pass 5); the snapshot pins the
    # pricing rule's fuse-vs-staged decision and the fused recipe step
    out["fused-probe-chain"] = P.Join(
        P.MapExpr(
            P.Filter(P.Scan(orders_t), Like({"qty": "3"})),
            SetValue("src", "bench"),
        ),
        cust_idx,
        ("cust_id",),
    )
    # examples/sharded_join.py: mesh-sharded stream probing a
    # single-device index (the benign-replication placement shape)
    if len(jax.devices()) >= 8:
        from ..parallel.mesh import make_mesh

        sharded_t = orders_t.with_sharding(make_mesh(8))
        out["sharded-join"] = P.Top(
            P.Filter(
                P.Join(
                    P.SelectCols(P.Scan(sharded_t), ("cust_id", "qty")),
                    cust_idx,
                    ("cust_id",),
                ),
                Like({"name": "Amelia"}),
            ),
            5,
        )
    else:
        out["sharded-join"] = "skipped: fewer than 8 visible devices"
    # the r08 serving tier's plan-query shape: a Lookup leaf (one
    # contiguous index range) with a downstream filter + projection —
    # exactly what the plan-executable cache admits, so the snapshot
    # pins the verdict the cache's admission check relies on.  Needs a
    # lazy device index (eager ones carry no Lookup plan), hence the
    # on_device-then-index_on build order.
    serve_idx = take_rows([Row(r) for r in people]).on_device("cpu").index_on("id")
    lookup_plan = serve_idx.find("1").plan
    if lookup_plan is not None:
        out["serve-lookup-filter"] = P.SelectCols(
            P.Filter(lookup_plan, Like({"name": "Amelia"})),
            ("name", "surname"),
        )
    else:
        out["serve-lookup-filter"] = "skipped: index has no device plan"
    return out


def example_plan_reports() -> Dict[str, object]:
    """Verifier reports (or a skip-reason string) per example chain."""
    return {
        name: p if isinstance(p, str) else verify_plan(p)
        for name, p in example_plans().items()
    }


def provenance_json(root) -> List[dict]:
    """The provenance table: one dict per chain slot (None = unknown
    footprint — the conservative lattice top)."""
    from . import provenance as PV

    def cols(s):
        return None if s is None else sorted(s)

    return [
        {
            "stage": f.label,
            "reads": cols(f.reads),
            "writes": cols(f.writes),
            "removes": cols(f.removes),
            "keeps_only": cols(f.keeps_only),
            "fallback_writes": cols(f.fallback_writes),
            "row_linear": f.row_linear,
            "order_preserving": f.order_preserving,
            "multiplicity": f.multiplicity,
            "may_error": f.may_error,
            "aborting": f.aborting,
            "barrier": f.barrier,
        }
        for f in PV.plan_facts(root)
    ]


def cost_json(root) -> List[dict]:
    """The cost table: one estimate dict per chain slot.  Sketches are
    pinned EMPTY so the payload never depends on what joins this
    process happened to run (the live-sketch path is exercised by the
    rewriter and its tests, not the committed snapshot)."""
    from .cost import estimate_plan

    return [e.as_dict() for e in estimate_plan(root, sketches={})]


def rewrite_json(root, report) -> dict:
    """The rewrite decision: what applied, what each blocked rule was
    stopped by, and the replayable recipe (sketches pinned empty, as in
    :func:`cost_json`)."""
    from .rewrite import RewriteVerdictMismatch, optimize_plan

    try:
        result = optimize_plan(root, report, sketches={})
    except RewriteVerdictMismatch as exc:  # prover bug: keep it visible
        return {"error": str(exc)}
    recipe = None
    if result.recipe is not None:
        recipe = {
            # fuse_joins carries scalar args; permute/drop carry tuples
            "steps": [
                [step[0]]
                + [list(a) if isinstance(a, (list, tuple)) else a
                   for a in step[1:]]
                for step in result.recipe.steps
            ],
            "require_present": list(result.recipe.require_present),
            "join_order": list(result.recipe.join_order),
        }
    return {
        "applied": list(result.applied),
        "blocked": [
            {"rule": d.rule, "stage": d.stage, "message": d.message}
            for d in result.blocked
        ],
        "recipe": recipe,
    }


def plan_analysis_json(root) -> dict:
    """Everything the suite knows about one plan: verifier verdict,
    provenance table, cost table, join-order ranking, rewrite decision.
    The per-plan payload entry and the ``explain --json`` body."""
    from .cost import choose_fusion, choose_join_operator, rank_join_orders

    report = verify_plan(root)
    d = report_json(report)
    d["provenance"] = provenance_json(root)
    d["cost"] = cost_json(root)
    d["join_orders"] = rank_join_orders(root, report, sketches={})
    d["join_operator"] = choose_join_operator(root, sketches={})
    d["fusion"] = choose_fusion(root, sketches={})
    d["rewrite"] = rewrite_json(root, report)
    return d


def _colset(v) -> str:
    if v is None:
        return "?"
    return ",".join(v) if v else "-"


def explain_text(name: str, root) -> str:
    """Human-readable per-node provenance/cost/placement tables for one
    plan — the ``explain`` CLI's default output (same fixed-width table
    idiom as ``obs diff``)."""
    d = plan_analysis_json(root)
    lines = [
        f"explain: {name}",
        f"verdict: ok={d['ok']} predicts_empty={d['predicts_empty']}"
        f" final_card={d['final_card']} rows@{d['row_placement']}",
        "",
        f"{'stage':<16} {'reads':<18} {'writes':<12} {'removes':<12}"
        f" {'mult':<5} flags",
    ]
    for row in d["provenance"]:
        flags = [
            k
            for k, on in (
                ("may-error", row["may_error"]),
                ("aborting", row["aborting"]),
                ("barrier", row["barrier"]),
                ("nonlinear", not row["row_linear"]),
                ("unordered", not row["order_preserving"]),
            )
            if on
        ]
        writes = _colset(row["writes"])
        if row["fallback_writes"]:
            writes += f"(+{_colset(row['fallback_writes'])})"
        removes = _colset(row["removes"])
        if row["keeps_only"] is not None:
            removes = f"keep:{_colset(row['keeps_only'])}"
        lines.append(
            f"{row['stage']:<16} {_colset(row['reads']):<18} {writes:<12}"
            f" {removes:<12} {row['multiplicity']:<5}"
            f" {','.join(flags) or '-'}"
        )
    lines += [
        "",
        f"{'stage':<16} {'rows':>10} {'host B':>10} {'device B':>10}"
        f" {'repl B':>10} {'sel':>8}  note",
    ]
    for row in d["cost"]:
        sel = "-" if "selectivity" not in row else f"{row['selectivity']:.4f}"
        lines.append(
            f"{row['stage']:<16} {row['rows']:>10.1f} {row['bytes_host']:>10.1f}"
            f" {row['bytes_device']:>10.1f} {row['bytes_replicated']:>10.1f}"
            f" {sel:>8}  {row.get('note', '')}"
        )
    if d["join_orders"]:
        lines += ["", "join orders (est Σ intermediate rows; * = submitted):"]
        for cand in d["join_orders"]:
            mark = "*" if cand["submitted"] else (
                "provable" if cand["provable"] else "unprovable")
            lines.append(
                f"  {' -> '.join(cand['order']):<48}"
                f" {cand['est_intermediate_rows']:>12.1f}  {mark}"
            )
    op = d.get("join_operator")
    if op is not None:
        lines += [
            "",
            "physical join operator (cascaded vs single-pass multiway):",
            f"  run: {' -> '.join(op['run'])} ({op['dims']} dims, "
            f"est {op['est_rows_in']:.0f} rows in -> "
            f"{op['est_rows_out']:.0f} out)",
            f"  cascaded   : {op['cascade_intermediate_bytes']:>14.1f} B "
            f"intermediate tables + per-level bounds",
            f"  multiway   : {op['multiway_bytes']:>14.1f} B per-dimension "
            f"bounds, no intermediate",
            f"  chosen     : {op['chosen']}",
        ]
    fu = d.get("fusion")
    if fu is not None:
        lines += [
            "",
            "probe-pass fusion (staged materialize vs fused key gathers):",
            f"  run: {' -> '.join(fu['run'])} ({len(fu['ops'])} op(s) + "
            f"{fu['dims']}-dim probe, est {fu['est_rows_in']:.0f} rows in"
            f" -> {fu['est_rows_selected']:.0f} selected)",
            f"  staged     : {fu['staged_bytes_host']:>14.1f} B host /"
            f" {fu['staged_bytes_device']:>14.1f} B device materialized",
            f"  fused      : {fu['fused_bytes_host']:>14.1f} B host /"
            f" {fu['fused_bytes_device']:>14.1f} B device key gathers",
            f"  chosen     : {fu['chosen']} ({fu['note']})",
        ]
        if fu.get("blocked_by"):
            lines.append(f"  blocked by : {fu['blocked_by']}")
    rw = d["rewrite"]
    lines.append("")
    if "error" in rw:
        lines.append(f"rewrite ERROR: {rw['error']}")
    else:
        lines.append(
            "rewrite: " + ("; ".join(rw["applied"]) or "nothing applied"))
        for b in rw["blocked"]:
            lines.append(f"  blocked {b['rule']} by {b['stage']}: {b['message']}")
        if rw["recipe"] is not None:
            steps = ", ".join(
                s[0] + "(" + ",".join(
                    "[" + ",".join(map(str, a)) + "]"
                    if isinstance(a, list) else str(a)
                    for a in s[1:]
                ) + ")"
                for s in rw["recipe"]["steps"]
            )
            lines.append(
                f"  recipe: {steps}; require_present="
                f"{rw['recipe']['require_present']}"
            )
    return "\n".join(lines)


def plancert_json() -> dict:
    """A small-N plan-space certification summary for the payload:
    deterministic counts only (no timing), at a fixed N=2 so the
    snapshot stays cheap to regenerate — the full default-N sweep runs
    as ``make plan-cert``.  The budget is pinned effectively-infinite
    here because the payload must not depend on machine speed."""
    from .plancert import certify, summary_json

    return summary_json(certify(n=2, budget_s=1e9))


def json_payload(paths: Optional[List] = None) -> dict:
    """The full ``--json`` CLI payload (see docs/ANALYSIS.md schema)."""
    plans = {}
    for name, p in sorted(example_plans().items()):
        plans[name] = (
            {"skipped": p} if isinstance(p, str) else plan_analysis_json(p)
        )
    return {
        "schema": SCHEMA_VERSION,
        "lint": lint_json(paths),
        "plans": plans,
        "plan_cert": plancert_json(),
    }
