"""Cost domain over the plan IR: cardinality + per-placement bytes.

Per chain stage, estimate the OUTPUT cardinality and the bytes the
stage's output pins per placement class — host, device, and
*replicated* (a broadcast join build side is materialized once per
shard, the r06 failure mode: pricing work alone said "fuse everything"
while mesh RSS went 7.2→11.8GB).  Estimates are seeded from real
statistics when the process has them and schema defaults otherwise:

* column distinct counts come from dictionary sizes
  (``StringColumn.dict_size`` — a metadata read, never a device sync);
* join build-side key distributions come from the SpaceSaving sketches
  the partitioned join already feeds (``obs/joinskew.py``): the
  expected per-probe fanout under a probe-follows-build workload is
  ``n_build × Σ share²`` — the self-join-size estimator — which the
  sketch's tracked shares bound without holding the key stream;
* everything else falls back to documented default selectivities.

The domain is advisory: it RANKS candidate plans (Filter ordering, Join
orderings) for the rewriter and the ``explain`` CLI.  Proofs of safety
live in :mod:`csvplus_tpu.analysis.provenance`; nothing here may make a
rewrite legal, only cheap.  Like the verifier, every input is metadata
the plan already holds — ``estimate_plan`` is O(plan), not O(rows).
"""

from __future__ import annotations

from dataclasses import dataclass
from itertools import permutations
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import plan as P
from ..predicates import All, Any_, Like, Not
from ..ops.join import device_index_static_info
from . import provenance as PV
from .schema import placement_of_column

__all__ = [
    "CostEstimate",
    "choose_fusion",
    "choose_join_operator",
    "estimate_plan",
    "predicate_selectivity",
    "rank_join_orders",
]

#: Bytes per row per column: int32 codes / int32 typed lanes.
BYTES_PER_CELL = 4.0
#: Distinct-count default when no dictionary metadata exists.
DEFAULT_DISTINCT = 32
#: Selectivity floor/defaults.
MIN_SELECTIVITY = 1e-4
OPAQUE_SELECTIVITY = 0.33  # unlowerable predicate: assume 1-in-3
WHILE_SELECTIVITY = 0.5  # TakeWhile/DropWhile prefix split
EXCEPT_SELECTIVITY = 0.5  # anti-join survival rate
DEFAULT_ROWS = 1024.0  # leaf with no table metadata (structural plans)


@dataclass(frozen=True)
class CostEstimate:
    """Estimated output of one chain stage."""

    stage: str
    rows: float
    bytes_host: float
    bytes_device: float
    bytes_replicated: float
    selectivity: Optional[float] = None  # narrowing stages only
    note: str = ""

    def as_dict(self) -> Dict[str, Any]:
        d = {
            "stage": self.stage,
            "rows": round(self.rows, 1),
            "bytes_host": round(self.bytes_host, 1),
            "bytes_device": round(self.bytes_device, 1),
            "bytes_replicated": round(self.bytes_replicated, 1),
        }
        if self.selectivity is not None:
            d["selectivity"] = round(self.selectivity, 6)
        if self.note:
            d["note"] = self.note
        return d


def _distinct_of(col) -> int:
    """Distinct-value estimate from column metadata (no device sync)."""
    try:
        n = int(getattr(col, "dict_size"))
        return max(1, n)
    except (AttributeError, TypeError, ValueError):
        return DEFAULT_DISTINCT


def _match_share(col: str, value, distinct: Dict[str, int],
                 sketches: Optional[Dict[str, Any]]) -> float:
    """Pass fraction of ``col == value``.  When a live SpaceSaving
    sketch exists under the single-column label (the r14/r15 build-side
    sketches — ``offer_build_sample`` decodes single-column keys to the
    raw values, so a ``Like`` literal looks up directly), use the
    value's OBSERVED share: tracked values take ``count/observed``;
    untracked ones split the residual tail uniformly over the remaining
    distinct values.  No sketch or an empty one falls back to the
    static uniform ``1/distinct`` guess (ROADMAP item 1: cost estimates
    should consult workload evidence, not just metadata)."""
    d = float(distinct.get(col, DEFAULT_DISTINCT))
    sk = sketches.get(col) if sketches else None
    observed = getattr(sk, "observed", 0) if sk is not None else 0
    if observed <= 0:
        return 1.0 / d
    top = sk.topk()
    for key, count, _err in top:
        if key == value:
            return count / observed
    tail_share = max(0.0, 1.0 - sum(c for _, c, _ in top) / observed)
    tail_keys = max(1, int(d) - len(top))
    return tail_share / tail_keys


def predicate_selectivity(
    pred,
    distinct: Dict[str, int],
    sketches: Optional[Dict[str, Any]] = None,
) -> float:
    """Estimated pass fraction of *pred* given per-column distinct
    counts: a ``Like`` equality keeps the value's sketch-observed share
    when a live single-column sketch covers it (:func:`_match_share`),
    else ~1/distinct per referenced column; ``All``/``Any``/``Not``
    compose under independence.  Advisory only — selectivity feeds the
    rewriter's PRICING, never its licensing, so a wild estimate can
    cost performance but not correctness."""
    if isinstance(pred, Like):
        s = 1.0
        for col, value in pred.match.items():
            s *= _match_share(col, value, distinct, sketches)
        return max(MIN_SELECTIVITY, s)
    if isinstance(pred, All):
        s = 1.0
        for q in pred.preds:
            s *= predicate_selectivity(q, distinct, sketches)
        return max(MIN_SELECTIVITY, s)
    if isinstance(pred, Any_):
        miss = 1.0
        for q in pred.preds:
            miss *= 1.0 - predicate_selectivity(q, distinct, sketches)
        return max(MIN_SELECTIVITY, 1.0 - miss)
    if isinstance(pred, Not):
        return max(
            MIN_SELECTIVITY,
            1.0 - predicate_selectivity(pred.pred, distinct, sketches),
        )
    return OPAQUE_SELECTIVITY


def _sketch_fanout(sketch, n_build: float, d_build: int) -> Tuple[float, str]:
    """Expected per-probe match count from a build-side SpaceSaving
    sketch: ``n_build × Σ share²`` over tracked keys, with the untracked
    tail spread uniformly over the remaining distinct keys.  Falls back
    to the uniform ``n_build / d_build`` when the sketch is empty."""
    observed = sketch.observed
    if observed <= 0:
        return (n_build / max(1, d_build), "uniform (empty sketch)")
    shares = [c / observed for _, c, _ in sketch.topk()]
    sum_sq = sum(s * s for s in shares)
    tail_share = max(0.0, 1.0 - sum(shares))
    tail_keys = max(1, d_build - len(shares))
    sum_sq += (tail_share * tail_share) / tail_keys
    return (n_build * sum_sq, f"sketch ({len(shares)} tracked keys)")


def _probe_cost(index, sketches) -> Tuple[float, float, str, Optional[tuple]]:
    """Price one build side's probe: expected per-row fanout, replicated
    bytes when the broadcast tier pins the build table per shard, a
    human note, and the ``device_index_static_info`` tuple.  Shared by
    the unit ``Join`` estimate and the per-dimension fold of the fused
    ``MultiwayJoin`` — one pricing model, two physical operators."""
    info = device_index_static_info(index)
    dev = getattr(index, "device_table", None)
    n_build = float(getattr(getattr(dev, "table", None), "nrows", 0) or 0)
    meta = info[3] if info is not None else None
    d_build = (meta or {}).get("packed_keys") or max(
        1, int(n_build) or DEFAULT_DISTINCT)
    label = ",".join(info[1]) if info is not None and info[1] else None
    sk = sketches.get(label) if label else None
    if sk is not None:
        fanout, note = _sketch_fanout(sk, n_build, d_build)
    else:
        fanout = n_build / max(1, d_build)
        note = "uniform build keys (no sketch)"
    replicated = 0.0
    # Broadcast-tier build sides are replicated once per shard (the r06
    # memory lesson): below the partition threshold the build table
    # rides every device.
    pmin = (meta or {}).get("partition_min_keys")
    if pmin is not None and d_build < pmin and dev is not None:
        tbl = getattr(dev, "table", None)
        ncols = len(getattr(tbl, "columns", {}) or {})
        replicated = n_build * ncols * BYTES_PER_CELL
        note += "; broadcast-tier build (replicated per shard)"
    return fanout, replicated, note, info


def _placement_bucket(col) -> str:
    kind = placement_of_column(col).kind
    if kind in ("device", "sharded"):
        return "device"
    if kind == "host":
        return "host"
    return "device"  # unknown: price it at the expensive tier


def estimate_plan(
    root: P.PlanNode,
    sketches: Optional[Dict[str, Any]] = None,
) -> List[CostEstimate]:
    """One :class:`CostEstimate` per :func:`~csvplus_tpu.plan.linearize`
    slot.  *sketches* maps join-key labels (``",".join(key_columns)``,
    the ``offer_build_sample`` convention) to SpaceSaving sketches; when
    ``None`` the process-global :data:`~csvplus_tpu.obs.joinskew.joinskew`
    registry is consulted."""
    if sketches is None:
        from ..obs.joinskew import joinskew

        sketches = joinskew.build_sketches()
    chain = P.linearize(root)
    facts = [PV.stage_facts(i, n) for i, n in enumerate(chain)]
    out: List[CostEstimate] = []

    # Rolling state: rows, per-column distinct counts, per-column
    # placement buckets ("host"/"device").  Schema evolution follows the
    # provenance facts so the two domains can never disagree on it.
    leaf = chain[0]
    table = getattr(leaf, "table", None)
    distinct: Dict[str, int] = {}
    bucket: Dict[str, str] = {}
    if table is not None and getattr(table, "columns", None):
        rows = float(getattr(table, "nrows", 0))
        for name, col in table.columns.items():
            distinct[name] = _distinct_of(col)
            bucket[name] = _placement_bucket(col)
    else:
        rows = DEFAULT_ROWS
    if isinstance(leaf, P.Lookup):
        rows = float(max(0, leaf.upper - leaf.lower))
    replicated = 0.0

    def snapshot(pos: int, sel: Optional[float], note: str) -> CostEstimate:
        bh = sum(rows * BYTES_PER_CELL for b in bucket.values() if b == "host")
        bd = sum(rows * BYTES_PER_CELL for b in bucket.values() if b == "device")
        return CostEstimate(
            facts[pos].label, rows, bh, bd, replicated, sel, note)

    out.append(snapshot(0, None, "" if table is not None else
                        "no table metadata: default cardinality"))

    for pos in range(1, len(chain)):
        node, f = chain[pos], facts[pos]
        sel: Optional[float] = None
        note = ""
        if isinstance(node, P.Filter):
            sel = predicate_selectivity(node.pred, distinct, sketches)
            rows *= sel
        elif isinstance(node, (P.TakeWhile, P.DropWhile)):
            sel = WHILE_SELECTIVITY
            rows *= sel
        elif isinstance(node, P.Top):
            rows = min(rows, float(node.n))
        elif isinstance(node, P.DropRows):
            rows = max(0.0, rows - float(node.n))
        elif isinstance(node, P.Except):
            sel = EXCEPT_SELECTIVITY
            rows *= sel
            note = "default anti-join survival"
        elif isinstance(node, P.Join):
            fanout, rep, note, info = _probe_cost(node.index, sketches)
            rows *= max(fanout, MIN_SELECTIVITY)
            replicated += rep
            # Index columns joining the schema.
            if info is not None:
                kinds, meta = info[0], info[3]
                place = (meta or {}).get("placement")
                b = "device" if place is None or place.kind != "host" else "host"
                for name in kinds:
                    bucket.setdefault(name, b)
                    distinct.setdefault(name, DEFAULT_DISTINCT)
        elif isinstance(node, P.MultiwayJoin):
            # One chain slot, N build sides: fanouts compose
            # multiplicatively (exactly the cascade's row count — the
            # fused operator is bitwise-equal by contract) but NO
            # interior slot ever materializes, which is the whole point;
            # choose_join_operator prices that difference explicitly.
            dim_notes = []
            for index, _cols in node.joins:
                fanout, rep, dnote, info = _probe_cost(index, sketches)
                rows *= max(fanout, MIN_SELECTIVITY)
                replicated += rep
                dim_notes.append(dnote)
                if info is not None:
                    kinds, meta = info[0], info[3]
                    place = (meta or {}).get("placement")
                    b = ("device" if place is None or place.kind != "host"
                         else "host")
                    for name in kinds:
                        bucket.setdefault(name, b)
                        distinct.setdefault(name, DEFAULT_DISTINCT)
            note = f"multiway x{len(node.joins)}: " + " | ".join(dim_notes)
        elif isinstance(node, P.FusedProbe):
            # Absorbed filters narrow first (that is the fused win: the
            # selection shrinks BEFORE the fan-out), then the probe
            # dimensions fold exactly like MultiwayJoin; the absorbed
            # projection/map footprint rides the generic facts-based
            # schema evolution below.
            sels: List[float] = []
            for kind, payload in node.ops:
                if kind == "filter":
                    s = predicate_selectivity(payload, distinct, sketches)
                    sels.append(s)
                    rows *= s
            dim_notes = []
            for index, _cols in node.joins:
                fanout, rep, dnote, info = _probe_cost(index, sketches)
                rows *= max(fanout, MIN_SELECTIVITY)
                replicated += rep
                dim_notes.append(dnote)
                if info is not None:
                    kinds, meta = info[0], info[3]
                    place = (meta or {}).get("placement")
                    b = ("device" if place is None or place.kind != "host"
                         else "host")
                    for name in kinds:
                        bucket.setdefault(name, b)
                        distinct.setdefault(name, DEFAULT_DISTINCT)
            if sels:
                sel = 1.0
                for s in sels:
                    sel *= s
            note = (f"fused probe x{len(node.joins)}: "
                    + " | ".join(dim_notes))

        # Schema evolution from provenance facts.
        if f.keeps_only is not None:
            for name in list(bucket):
                if name not in f.keeps_only:
                    bucket.pop(name)
                    distinct.pop(name, None)
        for name in f.removes:
            bucket.pop(name, None)
            distinct.pop(name, None)
        for name in f.writes:
            bucket.setdefault(name, "device")
            if f.op == "MapExpr":
                distinct[name] = 1  # constant write / renamed column
            else:
                distinct.setdefault(name, DEFAULT_DISTINCT)
        out.append(snapshot(pos, sel, note))
    return out


def _stage_multiplier(node: P.PlanNode, est: CostEstimate,
                      prev_rows: float) -> float:
    if prev_rows <= 0:
        return 1.0
    return est.rows / prev_rows


def rank_join_orders(
    root: P.PlanNode,
    report=None,
    sketches: Optional[Dict[str, Any]] = None,
) -> List[Dict[str, Any]]:
    """Rank orderings of the longest consecutive ``Join``/``Except`` run
    in *root* by total intermediate cardinality (the classic Σ-of-
    intermediates objective, multipliers taken from
    :func:`estimate_plan`).

    Each candidate is marked ``provable``: reachable from the submitted
    order purely by provenance-proven swaps — i.e. the relative order of
    row-EXPANDING stages is preserved (reordering two expansions changes
    the bitwise row layout) and every NARROWING stage moved earlier
    proves :func:`~csvplus_tpu.analysis.provenance.prove_swap_before`
    against each stage it crosses.  The rewriter applies only provable
    orderings; the rest are advisory output for ``explain``.
    """
    chain = P.linearize(root)
    facts = [PV.stage_facts(i, n) for i, n in enumerate(chain)]
    ests = estimate_plan(root, sketches=sketches)

    # Longest consecutive run of probe stages.
    best_run: Tuple[int, int] = (0, 0)
    i = 1
    while i < len(chain):
        if isinstance(chain[i], (P.Join, P.Except)):
            j = i
            while j + 1 < len(chain) and isinstance(
                    chain[j + 1], (P.Join, P.Except)):
                j += 1
            if j + 1 - i > best_run[1] - best_run[0]:
                best_run = (i, j + 1)
            i = j + 1
        else:
            i += 1
    lo, hi = best_run
    if hi - lo < 2:
        return []

    run = list(range(lo, hi))
    rows_in = ests[lo - 1].rows
    mult = {p: _stage_multiplier(chain[p], ests[p], ests[p - 1].rows)
            for p in run}

    def presence_ok(_col: str) -> bool:
        # Without a verifier report we cannot prove presence; with one,
        # PRESENT at the run's entry state covers every position inside
        # the run a narrowing stage can move to.
        if report is None:
            return False
        from .schema import Presence

        state = report.states[lo - 1]
        info = state.schema.get(_col)
        return info is not None and info.presence == Presence.PRESENT

    def provable(perm: Sequence[int]) -> bool:
        expanders = [p for p in perm if facts[p].multiplicity == PV.EXPAND]
        if expanders != [p for p in run
                         if facts[p].multiplicity == PV.EXPAND]:
            return False
        for idx, p in enumerate(perm):
            if facts[p].multiplicity != PV.NARROW:
                continue
            # Stages it now precedes but originally followed.
            for q in perm[idx + 1:]:
                if q < p and PV.prove_swap_before(
                        "join-order", facts[p], facts[q],
                        presence_ok) is not None:
                    return False
        return True

    perms = (list(permutations(run)) if len(run) <= 4
             else [tuple(run), tuple(sorted(run, key=lambda p: mult[p]))])
    ranked = []
    for perm in perms:
        total = 0.0
        r = rows_in
        for p in perm:
            r *= mult[p]
            total += r
        ranked.append({
            "order": [facts[p].label for p in perm],
            # Original-chain slot indices in execution order — the
            # executor-facing form: the rewriter turns the best provable
            # entry into a ("permute", ...) recipe step (ISSUE 17).
            "slots": list(perm),
            "run": list(run),
            "est_intermediate_rows": round(total, 1),
            "provable": provable(perm),
            "submitted": list(perm) == run,
        })
    ranked.sort(key=lambda d: d["est_intermediate_rows"])
    return ranked


def choose_join_operator(
    root: P.PlanNode,
    sketches: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Price the longest consecutive run of ``Join`` stages both ways —
    cascaded (every interior intermediate table materializes: its full
    estimated row count times its column count) versus the fused
    single-pass multiway operator (per dimension, one int32
    ``(lower, count)`` bounds pair per INPUT row, plus the expansion's
    row-id vectors at the OUTPUT cardinality; no intermediate table) —
    and return the cheaper physical operator.

    Advisory like everything in this module: the rewriter only FUSES
    when provenance licenses it (later keys PRESENT before the run) and
    this function says the fused form is cheaper; ``explain`` renders
    the comparison either way.  Returns ``None`` when the plan has no
    run of two or more consecutive ``Join`` stages.
    """
    if sketches is None:
        from ..obs.joinskew import joinskew

        sketches = joinskew.build_sketches()
    chain = P.linearize(root)
    best: Tuple[int, int] = (0, 0)
    i = 1
    while i < len(chain):
        if isinstance(chain[i], P.Join):
            j = i
            while j + 1 < len(chain) and isinstance(chain[j + 1], P.Join):
                j += 1
            if j + 1 - i > best[1] - best[0]:
                best = (i, j + 1)
            i = j + 1
        else:
            i += 1
    lo, hi = best
    n_dims = hi - lo
    if n_dims < 2:
        return None
    ests = estimate_plan(root, sketches=sketches)
    facts = [PV.stage_facts(i, n) for i, n in enumerate(chain)]
    rows_in = ests[lo - 1].rows
    rows_out = ests[hi - 1].rows
    # Cascade: slots lo..hi-2 each materialize a full intermediate table
    # (the run's FINAL output exists under both operators — excluded),
    # and every level probes bounds (an int32 ``(lower, count)`` pair)
    # over the rows ENTERING that level — which grow with each fanout.
    cascade_bytes = sum(
        ests[p].bytes_host + ests[p].bytes_device for p in range(lo, hi - 1)
    ) + sum(
        ests[p - 1].rows * 2.0 * BYTES_PER_CELL for p in range(lo, hi)
    )
    # Multiway: every dimension probes bounds over the ORIGINAL input
    # rows; nothing else materializes beyond the final output both
    # operators share.  (This is also why the cascade can win: when an
    # early dimension drops most rows, its later levels probe fewer
    # rows than the fused pass, which always probes all of rows_in.)
    multiway_bytes = rows_in * 2.0 * BYTES_PER_CELL * n_dims
    chosen = "multiway" if multiway_bytes < cascade_bytes else "cascade"
    return {
        "run": [facts[p].label for p in range(lo, hi)],
        "slots": list(range(lo, hi)),
        "dims": n_dims,
        "est_rows_in": round(rows_in, 1),
        "est_rows_out": round(rows_out, 1),
        "cascade_intermediate_bytes": round(cascade_bytes, 1),
        "multiway_bytes": round(multiway_bytes, 1),
        "chosen": chosen,
    }


def choose_fusion(
    root: P.PlanNode,
    sketches: Optional[Dict[str, Any]] = None,
) -> Optional[Dict[str, Any]]:
    """Price the maximal absorbable Filter/Map/projection run ending at
    the chain's first probe (``Join``/``MultiwayJoin``) both ways —
    staged (the executor materializes the selected stream FULL-WIDTH
    before probing: every live column gathers down to the selection)
    versus fused (``FusedProbe``: only the distinct key columns gather
    for probing; everything else rides the emit gather both operators
    share) — and return the per-placement comparison.

    The decision is per placement lane, the r06 lesson (whole-program
    fusion regressed mesh RSS while total-bytes pricing approved it):
    ``chosen == "fuse"`` only when the fused bytes are <= the staged
    bytes on EVERY lane and strictly smaller in total.  The replicated
    lane is identical under both operators (the same build sides
    broadcast either way) and is excluded.  A run whose staged
    materialize is provably a passthrough (identity selection over
    unpadded storage, no absorbed filter and nothing narrowing above
    it) is refused outright — fusing it saves nothing.

    Advisory like everything in this module: the rewriter only fuses
    when provenance licenses every absorbed op (``analysis/rewrite.py``
    pass 5); ``explain`` renders the comparison either way.  Returns
    ``None`` when the chain has no probe; ``blocked_by`` names the
    opaque Filter/Map op bounding the run from below, if one does.
    """
    if sketches is None:
        from ..obs.joinskew import joinskew

        sketches = joinskew.build_sketches()
    chain = P.linearize(root)
    facts = [PV.stage_facts(i, n) for i, n in enumerate(chain)]
    probe = None
    for i in range(1, len(chain)):
        if isinstance(chain[i], (P.Join, P.MultiwayJoin)):
            probe = i
            break
    if probe is None:
        return None

    def absorbable(f: PV.StageFacts) -> bool:
        # the provenance license, purely structural: a known-footprint,
        # row-linear, non-aborting op of an absorbable kind
        return (
            f.op in ("Filter", "MapExpr", "SelectCols", "DropCols")
            and not f.barrier
            and f.reads is not None
            and f.row_linear
            and not f.aborting
        )

    start = probe
    while start - 1 >= 1 and absorbable(facts[start - 1]):
        start -= 1
    blocked_by = None
    if start - 1 >= 1 and facts[start - 1].op in (
        "Filter", "MapExpr", "SelectCols", "DropCols"
    ):
        # an op of an absorbable KIND that failed the license: an
        # opaque predicate/expr bounds the run from below
        blocked_by = facts[start - 1].label

    _KINDS = {
        P.Filter: "filter", P.MapExpr: "map",
        P.SelectCols: "select", P.DropCols: "drop",
    }
    ops = [_KINDS[type(n)] for n in chain[start:probe]]
    pnode = chain[probe]
    joins = (
        pnode.joins if isinstance(pnode, P.MultiwayJoin)
        else ((pnode.index, tuple(pnode.columns)),)
    )
    ests = estimate_plan(root, sketches=sketches)
    rows_in = ests[start - 1].rows
    rows_selected = ests[probe - 1].rows

    out: Dict[str, Any] = {
        "run": [facts[p].label for p in range(start, probe + 1)],
        "slots": list(range(start, probe + 1)),
        "ops": ops,
        "dims": len(joins),
        "est_rows_in": round(rows_in, 1),
        "est_rows_selected": round(rows_selected, 1),
        "blocked_by": blocked_by,
    }

    # Staged leg: the pre-probe materialize gathers every live column
    # down to the selection — exactly the bytes of the chain state
    # entering the probe, per placement lane.
    staged_host = ests[probe - 1].bytes_host
    staged_device = ests[probe - 1].bytes_device

    # Fused leg: only the distinct key columns gather for probing.
    key_cols: set = set()
    for _idx, cols in joins:
        key_cols |= set(cols)
    leaf = chain[0]
    table = getattr(leaf, "table", None)
    leaf_cols = getattr(table, "columns", None) or {}
    fused_host = fused_device = 0.0
    for c in sorted(key_cols):
        col = leaf_cols.get(c)
        b = _placement_bucket(col) if col is not None else "device"
        if b == "host":
            fused_host += rows_selected * BYTES_PER_CELL
        else:
            fused_device += rows_selected * BYTES_PER_CELL

    out.update({
        "staged_bytes_host": round(staged_host, 1),
        "staged_bytes_device": round(staged_device, 1),
        "fused_bytes_host": round(fused_host, 1),
        "fused_bytes_device": round(fused_device, 1),
    })

    if not ops:
        out.update({"chosen": "staged",
                    "note": "no absorbable run before the probe"})
        return out

    # Is the staged materialize real?  materialize() passes through on
    # an identity selection over unpadded storage; it is a real gather
    # only when something narrowed the selection (an absorbed filter or
    # a narrowing stage above the leaf) or the storage is padded /
    # range-restricted.
    nrows = int(getattr(table, "nrows", 0) or 0)
    stored = nrows
    if leaf_cols:
        try:
            stored = len(next(iter(leaf_cols.values())))
        except TypeError:
            stored = nrows
    padded_leaf = table is not None and stored != nrows
    partial_lookup = isinstance(leaf, P.Lookup) and (
        leaf.lower != 0 or leaf.upper != nrows
    )
    narrowed_before = any(
        facts[p].multiplicity == PV.NARROW for p in range(1, start)
    )
    if not ("filter" in ops or padded_leaf or partial_lookup
            or narrowed_before):
        out.update({"chosen": "staged",
                    "note": "identity stream: staged materialize is free"})
        return out

    per_lane_ok = (
        fused_host <= staged_host and fused_device <= staged_device
    )
    strictly_cheaper = (
        fused_host + fused_device < staged_host + staged_device
    )
    if per_lane_ok and strictly_cheaper:
        out.update({
            "chosen": "fuse",
            "note": (f"fused probe gathers {len(key_cols)} key column(s) "
                     "for the selection; the staged materialize of every "
                     "live column never happens"),
        })
    else:
        out.update({
            "chosen": "staged",
            "note": ("staged materialize prices no worse than the fused "
                     "key gathers on some placement lane"),
        })
    return out
