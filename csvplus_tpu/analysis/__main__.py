"""CLI for the static analysis suite.

``python -m csvplus_tpu.analysis [paths...]``
    AST lint; with no paths it walks the INSTALLED PACKAGE TREE (resolved
    from the package itself, not the cwd), so a newly added module can
    never silently bypass the gate.  Prints ``path:line: CODE message``
    per finding; exit 1 when any finding survives suppression — the
    ``make lint`` contract.

``python -m csvplus_tpu.analysis --json [--snapshot FILE]``
    Machine-readable payload (lint findings + per-plan analysis —
    verifier report, provenance/cost tables, rewrite decision — over the
    example chains; schema in docs/ANALYSIS.md).  ``--snapshot``
    compares the payload against a committed expected-diagnostics file
    and exits 3 on drift; ``--write-snapshot`` regenerates it.  The
    ``make analyze`` contract.

``python -m csvplus_tpu.analysis explain [name...] [--json]``
    Render the per-node provenance/cost/placement tables, the ranked
    join orders, the multiway-vs-cascaded physical-operator cost
    comparison (which form the rewriter chooses and why), and the
    rewrite decision for the named example chains (all of them with no
    names; ``--list`` prints the names) — the same fixed-width-table
    CLI shape as ``obs diff``.  Unknown names exit 2.

``python -m csvplus_tpu.analysis lint [--json] [paths...]``
    Explicit lint entry point: same behavior as the bare invocation but
    with a ``--json`` mode that prints just the findings list (the
    lint slice of the full payload) for diffable lint snapshots.

``python -m csvplus_tpu.analysis env [--write FILE]``
    Render the environment-variable registry (utils/env.py) as the
    docs/ENV.md table; ``--write`` regenerates the committed file the
    ENV001-R lint checks for drift.

``python -m csvplus_tpu.analysis plan-cert [--json]``
    Exhaustively certify the plan space up to ``CSVPLUS_PLANCERT_N``
    (see analysis/plancert.py: verdict equality, licensed recipe
    steps, bitwise execution parity, real refusal stages).  Exit 1 if
    any obligation fails or the wall-clock budget is exceeded — the
    ``make plan-cert`` contract.
"""

from __future__ import annotations

import json
import sys


def _explain(args) -> int:
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    list_only = "--list" in args
    if list_only:
        args.remove("--list")

    from .report import example_plans, explain_text, plan_analysis_json

    plans = example_plans()
    if list_only:
        for name in sorted(plans):
            print(name)
        return 0
    names = args or sorted(plans)
    unknown = [n for n in names if n not in plans]
    if unknown:
        print(
            f"unknown plan(s): {', '.join(unknown)} — known: "
            f"{', '.join(sorted(plans))}",
            file=sys.stderr,
        )
        return 2
    payload = {}
    blocks = []
    for name in names:
        p = plans[name]
        if isinstance(p, str):
            payload[name] = {"skipped": p}
            blocks.append(f"explain: {name}\n{p}")
        else:
            payload[name] = plan_analysis_json(p)
            blocks.append(explain_text(name, p))
    if as_json:
        print(json.dumps(payload, indent=2, sort_keys=True))
    else:
        print("\n\n".join(blocks))
    return 0


def _lint(args, as_json: bool) -> int:
    paths = args or None
    if as_json:
        from .report import lint_json

        findings = lint_json(paths)
        print(json.dumps(findings, indent=2, sort_keys=True))
        return 1 if findings else 0
    from .astlint import lint_paths
    from .report import default_lint_paths

    findings = lint_paths(
        paths if paths is not None else default_lint_paths(),
        global_checks=paths is None,
    )
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
    return 1 if findings else 0


def _env(args) -> int:
    from ..utils.env import render_env_md

    text = render_env_md()
    if "--write" in args:
        i = args.index("--write")
        target = args[i + 1]
        with open(target, "w", encoding="utf-8") as fh:
            fh.write(text)
        print(f"wrote {target}", file=sys.stderr)
        return 0
    print(text, end="")
    return 0


def _plan_cert(args) -> int:
    from .plancert import certify, summary_json

    summary = certify()
    if "--json" in args:
        print(json.dumps(summary_json(summary), indent=2, sort_keys=True))
    else:
        print(summary.describe())
    return 0 if summary.ok else 1


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    if args and args[0] == "explain":
        return _explain(args[1:])
    if args and args[0] == "lint":
        rest = args[1:]
        as_json = "--json" in rest
        if as_json:
            rest.remove("--json")
        return _lint(rest, as_json)
    if args and args[0] == "env":
        return _env(args[1:])
    if args and args[0] == "plan-cert":
        return _plan_cert(args[1:])
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    snapshot = write_snapshot = None
    if "--snapshot" in args:
        i = args.index("--snapshot")
        snapshot = args[i + 1]
        del args[i : i + 2]
    if "--write-snapshot" in args:
        i = args.index("--write-snapshot")
        write_snapshot = args[i + 1]
        del args[i : i + 2]
    paths = args or None

    if not (as_json or snapshot or write_snapshot):
        from .astlint import lint_paths
        from .report import default_lint_paths

        findings = lint_paths(
            paths if paths is not None else default_lint_paths(),
            global_checks=paths is None,
        )
        for f in findings:
            print(f)
        if findings:
            print(f"{len(findings)} finding(s)", file=sys.stderr)
            return 1
        return 0

    from .report import json_payload

    payload = json_payload(paths)
    text = json.dumps(payload, indent=2, sort_keys=True)
    if write_snapshot:
        with open(write_snapshot, "w", encoding="utf-8") as fh:
            fh.write(text + "\n")
        print(f"wrote {write_snapshot}", file=sys.stderr)
    if as_json:
        print(text)
    rc = 1 if payload["lint"] else 0
    if snapshot:
        with open(snapshot, "r", encoding="utf-8") as fh:
            expected = json.load(fh)
        if expected != payload:
            print(
                f"analysis payload drifted from {snapshot} — review and "
                "regenerate with --write-snapshot",
                file=sys.stderr,
            )
            return 3
        print(f"payload matches {snapshot}", file=sys.stderr)
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
