"""CLI for the repo AST lint: ``python -m csvplus_tpu.analysis <paths>``.

Prints one ``path:line: CODE message`` per finding and exits nonzero
when any finding survives suppression — the ``make lint`` contract.
"""

from __future__ import annotations

import sys

from .astlint import lint_paths


def main(argv=None) -> int:
    paths = (sys.argv[1:] if argv is None else argv) or ["csvplus_tpu"]
    findings = lint_paths(paths)
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
