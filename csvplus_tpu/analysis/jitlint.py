"""Dataflow lints over the jit boundary: RETRACE002 and SYNC001.

Both rules run an INTRAPROCEDURAL taint dataflow per function, seeded
from the module's own jitted kernels (the same decorator shapes
``obs/recompile.register_kernel`` stacks over: ``@jax.jit`` and
``@partial(jax.jit, static_argnames=...)``), and prove facts about how
device values flow — the two load-bearing contracts the benches only
check dynamically (RecompileWatch / ``host_sync_elements``):

* **RETRACE002** — the static-argument boundary (the r06 retrace bug
  class).  For every module-level jitted kernel, each call site's
  STATIC arguments must derive only from shapes/dtypes/constants/
  bounded enums.  A static computed from device DATA (``int(x.sum())``
  passed as ``total_bits``) retraces per distinct value — the exact
  regression r06 measured at 7x.  Sanctioned laundering, which clears
  taint because it maps unbounded data into a log-bounded enum, is the
  repo's pow2-bucket idiom: ``1 << max(total - 1, 0).bit_length()``
  (and ``.shape``/``.ndim``/``.dtype``/``.size``/``len()``/
  comparisons/``bool()`` — all shape-derived or bounded).

* **SYNC001** — the host-sync boundary.  In hot-path modules (``ops/``,
  ``columnar/``, ``parallel/``, ``serve/``), an implicit device->host
  sync — ``np.asarray(x)``/``np.array(x)``/``bool(x)``/``int(x)``/
  ``float(x)``/``x.item()``/``x.tolist()``/``len(x)`` on a provably
  JAX value ``x`` — blocks on the device stream where the caller sees
  only an innocent conversion.  Deliberate syncs are legal ONLY when
  accounted: either the enclosing function calls
  ``telemetry.count_sync(...)`` (the ``host_sync_elements`` ledger —
  visible accounting in the same scope), or the site is pinned in
  :data:`SYNC001_ALLOWED` with a written citation of its accounting.
  Unexplained allowances are themselves findings: a stale or
  citation-free allowlist entry fails lint.

Device taint sources (per function): results of calls rooted at
``jnp``/``jax``/``lax``, results of same-module jitted kernel calls,
names passed positionally to a jnp/lax/kernel call (a kernel argument
IS a device value — upload wrappers ``asarray``/``array``/
``device_put`` excluded, since their argument is the host side), and
``isinstance(x, jax.Array)`` guards.  Data taint additionally follows
device values THROUGH a sync (``int(dev)`` is host data derived from
device data) — that is what RETRACE002 forbids in static positions.

Both analyses are intraprocedural and same-module by design: function
parameters are untainted (callers are checked at their own sites), so
every finding is a provable local derivation, not a may-alias guess.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .astlint import (
    LintFinding,
    _allow_key,
    _enclosing_function,
    _is_jit_decorator,
    _root_name,
)

__all__ = [
    "SYNC001_ALLOWED",
    "RETRACE002_ALLOWED",
    "jitlint_findings",
    "allowlist_global_findings",
]

#: Pinned allowlist for DELIBERATE device->host syncs:
#: ``"<file basename>:<function>" -> citation``.  Every entry MUST cite
#: where its elements land in the ``host_sync_elements`` ledger (or why
#: no transfer happens); an empty citation or an entry matching no
#: current finding is itself a SYNC001 finding — allowances stay
#: explained or they fail lint.
SYNC001_ALLOWED: Dict[str, str] = {
    # -- columnar ------------------------------------------------------
    "exec.py:_exec_stage": (
        "deliberate O(1) scalar control syncs (Validate failure probe, "
        "TakeWhile/DropWhile cut index) — one scalar per stage "
        "execution, no transfer of row data"
    ),
    "exec.py:first_missing_cell": (
        "error path only: scalar row-number syncs while the pipeline "
        "aborts; no transfer in steady state"
    ),
    "ingest.py:_assemble_rows_sharded": (
        "no transfer: len() reads host Python lists of shard segments "
        "(run/pieces), never a device array"
    ),
    "ingest.py:link_rtt_ms": (
        "deliberate: the RTT probe IS a measured sync (8-element array, "
        "3 samples, cached once per process); no transfer of table data"
    ),
    "table.py:has_absent": (
        "deliberate cached scalar presence probe, once per column "
        "lifetime; no transfer of cell data"
    ),
    "table.py:sync": (
        "THE deliberate completion sync: one scalar round trip "
        "replacing per-buffer readiness pings; no transfer of column "
        "data"
    ),
    "typed.py:_demote": (
        "deliberate dictionary-build transfer of the UNIQUE values "
        "only, accounted as typed:demote stage elements; outside the "
        "host_sync_elements steady-state transfer guard by design"
    ),
    # -- ops -----------------------------------------------------------
    "join.py:build": (
        "deliberate one-time host int64 key mirror at index BUILD "
        "(serves point_bounds and the partitioned-path preparation); "
        "the probe path does no transfer"
    ),
    "join.py:point_bounds": (
        "serve-tier point read: O(1) scalar bound syncs per lookup ARE "
        "the operation's answer; no transfer of table rows"
    ),
    "join.py:point_bounds_many": (
        "serve-tier batched point read: one 2m-scalar bounds transfer "
        "per batch — the answer itself, no transfer of table rows"
    ),
    "join.py:probe": (
        "no transfer: len() reads the host list of key-code arrays, "
        "not a device value"
    ),
    "join.py:expand_matches_device": (
        "deliberate: the one O(1) total sync sizing the static output "
        "shape (see docstring); no transfer of match data"
    ),
    "join.py:_checked_probe_cols": (
        "error path only: one scalar argmax sync while raising "
        "DataSourceError; no transfer on the happy path"
    ),
    "join.py:join_tables": (
        "deliberate stats-sync fast path: (total, max run) in ONE "
        "2-scalar transfer decides the unique fast paths; the "
        "unique-partial mask transfer is the _host_compact_ids trade "
        "(cheaper than the serialized device scatter it replaces), "
        "accounted as join:expand stage elements alongside the "
        "host_sync_elements guard"
    ),
    "join.py:_compact_unique_partial": (
        "multiway unique-partial host compaction (see "
        "_host_compact_ids): deliberate mask transfer replacing the "
        "serialized device scatter, accounted as join:expand stage "
        "elements; the host_sync_elements guard excludes this "
        "stats-synced path by design"
    ),
    "join.py:multiway_join": (
        "deliberate multiway stats sync: (total, max fanout, rows "
        "avoided) in ONE 3-scalar transfer; no transfer of row data"
    ),
    "join.py:multiway_join_selected": (
        "deliberate multiway stats sync on the fused path: one "
        "3-scalar transfer; no transfer of row data"
    ),
    "lanes.py:union_device": (
        "deliberate: the one scalar union-SIZE sync needed for the "
        "static output slice (see docstring); no transfer of lane data"
    ),
    "lanes.py:translate_lanes": (
        "no transfer: len() reads lane-tuple arity (host tuples), not "
        "a device value"
    ),
    "parse.py:encode_column_device": (
        "deliberate dictionary-build syncs: unique count + first-row "
        "ids so the host touches ONLY unique values; accounted as "
        "ingest stage elements, outside the host_sync_elements "
        "steady-state guard"
    ),
    "sort.py:find_adjacent_duplicate": (
        "deliberate validation scalars (any_dup flag + first index) — "
        "two O(1) syncs per index build; no transfer of key data"
    ),
    "sort.py:run_starts": (
        "host bool run-starts mask is this helper's CONTRACT (feeds "
        "host grouping); deliberate O(n) transfer at index-build time, "
        "outside the host_sync_elements steady-state guard"
    ),
}
#: RETRACE002's allowlist, same key/citation contract as
#: :data:`SYNC001_ALLOWED` — a data-derived static argument is only
#: legal with a written retrace-cost accounting.  Starts (and should
#: stay) empty: the pow2-bucket idiom launders every sanctioned case.
RETRACE002_ALLOWED: Dict[str, str] = {}

_HOT_DIRS = ("ops", "columnar", "parallel", "serve")

# calls whose RESULT is a host value even when the argument is a device
# value — the implicit-sync sinks SYNC001 flags (np.asarray/np.array by
# attribute, the rest by bare name / method)
_SINK_NP_ATTRS = frozenset({"asarray", "array"})
_SINK_BUILTINS = frozenset({"bool", "int", "float", "len"})
_SINK_METHODS = frozenset({"item", "tolist"})

# attribute reads that launder device taint: shape metadata, not data
_META_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})

# upload wrappers whose ARGUMENT is host-side: excluded from the
# "positional arg of a jnp call is a device value" evidence rule
_UPLOAD_ATTRS = frozenset({"asarray", "array", "device_put"})

# jax-rooted calls whose RESULT is host metadata, not a device array
_HOST_META_CALLS = frozenset(
    {"devices", "local_devices", "device_count", "local_device_count",
     "default_backend", "process_index", "block_until_ready"}
)

# array CONSTRUCTORS whose arguments are shapes/fill scalars, not device
# values: their result is a device array (dev_expr still says so) but
# their arguments carry no evidence — `jnp.full(k_pad - k, ...)` must
# not mark `k` as a device value.  The *_like variants take an array
# and are deliberately NOT here.
_SHAPE_CTOR_ATTRS = frozenset(
    {"zeros", "ones", "full", "empty", "arange", "linspace", "eye",
     "iota", "identity"}
)


def _is_hot_path(path: str) -> bool:
    return any(d in _HOT_DIRS for d in Path(path).parts[:-1])


def _jit_static_params(
    dec: ast.expr, params: Sequence[str]
) -> Optional[Set[str]]:
    """The static parameter NAMES a jit decorator declares, or None when
    *dec* is not a jit decorator.  Handles ``@jax.jit`` (no statics) and
    ``@partial(jax.jit, static_argnames=..., static_argnums=...)``."""
    if not _is_jit_decorator(dec):
        return None
    statics: Set[str] = set()
    if isinstance(dec, ast.Call):
        for kw in dec.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        statics.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        if 0 <= n.value < len(params):
                            statics.add(params[n.value])
    return statics


def _params_of(func: ast.AST) -> List[str]:
    a = func.args
    return [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]


def _kernel_table(tree: ast.Module) -> Dict[str, Tuple[List[str], Set[str]]]:
    """``{kernel name: (parameter names, static parameter names)}`` for
    every jitted def in the module (module-level or nested — nested
    kernels are still called by bare name) plus module-level
    ``name = jax.jit(fn, static_argnames=...)`` bindings."""
    out: Dict[str, Tuple[List[str], Set[str]]] = {}
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs[node.name] = node
            params = _params_of(node)
            statics: Optional[Set[str]] = None
            for dec in node.decorator_list:
                s = _jit_static_params(dec, params)
                if s is not None:
                    statics = (statics or set()) | s
            if statics is not None:
                out[node.name] = (params, statics)
    for stmt in tree.body:
        if not (
            isinstance(stmt, ast.Assign)
            and len(stmt.targets) == 1
            and isinstance(stmt.targets[0], ast.Name)
            and isinstance(stmt.value, ast.Call)
        ):
            continue
        call = stmt.value
        f = call.func
        is_jit = (isinstance(f, ast.Attribute) and f.attr == "jit") or (
            isinstance(f, ast.Name) and f.id == "jit"
        )
        if not is_jit or not call.args:
            continue
        inner = call.args[0]
        params = []
        if isinstance(inner, ast.Name) and inner.id in defs:
            params = _params_of(defs[inner.id])
        statics = set()
        for kw in call.keywords:
            if kw.arg == "static_argnames":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, str):
                        statics.add(n.value)
            elif kw.arg == "static_argnums":
                for n in ast.walk(kw.value):
                    if isinstance(n, ast.Constant) and isinstance(n.value, int):
                        if 0 <= n.value < len(params):
                            statics.add(params[n.value])
        out[stmt.targets[0].id] = (params, statics)
    return out


def _call_root(call: ast.Call) -> Optional[str]:
    return _root_name(call.func)


def _is_device_call(call: ast.Call, kernels: Dict) -> bool:
    """A call whose RESULT is a device value: rooted at jnp/jax/lax, or
    a same-module jitted kernel.  Host-metadata helpers
    (``jax.devices()``, ``jax.default_backend()``, ...) excluded."""
    f = call.func
    if isinstance(f, ast.Name) and f.id in kernels:
        return True
    if isinstance(f, ast.Attribute) and f.attr in _HOST_META_CALLS:
        return False
    root = _call_root(call)
    return root in ("jnp", "jax", "lax")


def _is_meta_expr(e: ast.expr) -> bool:
    """Provably shape-metadata: ``x.shape``, ``x.shape[0]``, constants."""
    if isinstance(e, ast.Constant):
        return True
    if isinstance(e, ast.Attribute):
        return e.attr in _META_ATTRS
    if isinstance(e, ast.Subscript):
        return _is_meta_expr(e.value)
    return False


def _sink_kind(call: ast.Call) -> Optional[Tuple[str, ast.expr]]:
    """``(description, synced argument)`` when *call* is one of the
    implicit-sync forms, else None."""
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in _SINK_NP_ATTRS
        and isinstance(f.value, ast.Name)
        and f.value.id == "np"
        and call.args
    ):
        return (f"np.{f.attr}(...)", call.args[0])
    if isinstance(f, ast.Name) and f.id in _SINK_BUILTINS and len(call.args) == 1:
        return (f"{f.id}(...)", call.args[0])
    if isinstance(f, ast.Attribute) and f.attr in _SINK_METHODS and not call.args:
        return (f".{f.attr}()", f.value)
    return None


class _Taint:
    """Per-function device/data taint over simple assignments, run to a
    fixpoint.  ``dev`` holds names provably bound to JAX values; ``data``
    additionally holds host scalars DERIVED from device values through a
    sync sink (what RETRACE002 forbids in static positions)."""

    def __init__(self, func: ast.AST, kernels: Dict) -> None:
        self.kernels = kernels
        self.dev: Set[str] = set()
        self.data: Set[str] = set()
        self._seed_evidence(func)
        self._fixpoint(func)

    # -- evidence: names the function itself treats as device values ----
    def _seed_evidence(self, func: ast.AST) -> None:
        # names provably bound to shape metadata (`n = keys.shape[0]`)
        # are host ints everywhere — a later appearance inside a device
        # call's arguments (a clip bound, a slice width) is not evidence
        meta_names: Set[str] = set()
        for sub in ast.walk(func):
            if isinstance(sub, ast.Assign) and _is_meta_expr(sub.value):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name):
                        meta_names.add(tgt.id)
        for sub in ast.walk(func):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            # isinstance(x, jax.Array) marks x as a device value
            if (
                isinstance(f, ast.Name)
                and f.id == "isinstance"
                and len(sub.args) == 2
                and isinstance(sub.args[0], ast.Name)
                and "jax" in ast.unparse(sub.args[1])
            ):
                self.dev.add(sub.args[0].id)
                continue
            if not _is_device_call(sub, self.kernels):
                continue
            if isinstance(f, ast.Attribute) and f.attr in _UPLOAD_ATTRS:
                continue  # upload wrappers take HOST arguments
            if isinstance(f, ast.Attribute) and f.attr in _SHAPE_CTOR_ATTRS:
                continue  # shape constructors take shapes/fill scalars
            statics: Set[str] = set()
            params: List[str] = []
            if isinstance(f, ast.Name) and f.id in self.kernels:
                params, statics = self.kernels[f.id]
            for i, a in enumerate(sub.args):
                if params and i < len(params) and params[i] in statics:
                    continue
                # only BARE names (incl. inside arithmetic/comparison/
                # starred wrapping) — NOT attribute roots: in
                # `k(self.packed)` the device value is the attribute,
                # not `self`.  Names inside a NESTED shape-ctor/upload
                # call (`concatenate([x, zeros(n - k)])`) are that
                # call's host-side arguments, not device values.
                skip = {
                    id(n.value)
                    for n in ast.walk(a)
                    if isinstance(n, ast.Attribute)
                }
                for n in ast.walk(a):
                    if (
                        isinstance(n, ast.Call)
                        and isinstance(n.func, ast.Attribute)
                        and n.func.attr in (_SHAPE_CTOR_ATTRS | _UPLOAD_ATTRS)
                    ):
                        skip.update(
                            id(m) for m in ast.walk(n)
                            if isinstance(m, ast.Name)
                        )
                for n in ast.walk(a):
                    if (
                        isinstance(n, ast.Name)
                        and id(n) not in skip
                        and n.id not in meta_names
                    ):
                        self.dev.add(n.id)

    # -- expression taint ----------------------------------------------
    def dev_expr(self, e: ast.expr) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.dev
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in _META_ATTRS:
                return False
            return self.dev_expr(e.value)
        if isinstance(e, ast.Call):
            if _sink_kind(e) is not None:
                return False  # the sink's result lives on host
            return _is_device_call(e, self.kernels)
        if isinstance(e, ast.Subscript):
            return self.dev_expr(e.value)
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self.dev_expr(x) for x in e.elts)
        if isinstance(e, ast.BinOp):
            return self.dev_expr(e.left) or self.dev_expr(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.dev_expr(e.operand)
        if isinstance(e, ast.IfExp):
            return self.dev_expr(e.body) or self.dev_expr(e.orelse)
        if isinstance(e, ast.Starred):
            return self.dev_expr(e.value)
        if isinstance(e, ast.Compare):
            # dev <op> x is itself a device boolean array
            return self.dev_expr(e.left) or any(
                self.dev_expr(c) for c in e.comparators
            )
        return False

    def data_expr(self, e: ast.expr) -> bool:
        """Data-derived (RETRACE002 sense): contains device data or a
        synced derivative, NOT laundered through shape/dtype/bit_length/
        comparison/bool."""
        if isinstance(e, ast.Name):
            return e.id in self.data or e.id in self.dev
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in _META_ATTRS:
                return False
            return self.data_expr(e.value)
        if isinstance(e, ast.Call):
            f = e.func
            # laundering calls: shape-derived or bounded-enum results
            if isinstance(f, ast.Attribute) and f.attr == "bit_length":
                return False
            if isinstance(f, ast.Name) and f.id in ("len", "bool"):
                return False
            sink = _sink_kind(e)
            if sink is not None:
                # int(x)/np.asarray(x)/x.item()/... — data survives the
                # hop to host
                return self.data_expr(sink[1])
            if _is_device_call(e, self.kernels):
                return True
            return any(self.data_expr(a) for a in e.args) or any(
                self.data_expr(kw.value) for kw in e.keywords
            )
        if isinstance(e, (ast.Compare, ast.BoolOp)):
            return False  # bounded enum (a bool), the sanctioned class
        if isinstance(e, ast.Subscript):
            return self.data_expr(e.value) or self.data_expr(e.slice)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self.data_expr(x) for x in e.elts)
        if isinstance(e, ast.BinOp):
            return self.data_expr(e.left) or self.data_expr(e.right)
        if isinstance(e, ast.UnaryOp):
            return self.data_expr(e.operand)
        if isinstance(e, ast.IfExp):
            return self.data_expr(e.body) or self.data_expr(e.orelse)
        if isinstance(e, ast.Starred):
            return self.data_expr(e.value)
        if isinstance(e, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self.data_expr(e.elt) or any(
                self.data_expr(g.iter) for g in e.generators
            )
        return False

    # -- assignment fixpoint -------------------------------------------
    def _assign(self, target: ast.expr, is_dev: bool, is_data: bool) -> bool:
        changed = False
        if isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                changed |= self._assign(el, is_dev, is_data)
            return changed
        if isinstance(target, ast.Starred):
            return self._assign(target.value, is_dev, is_data)
        if isinstance(target, ast.Name):
            if is_dev and target.id not in self.dev:
                self.dev.add(target.id)
                changed = True
            if is_data and target.id not in self.data:
                self.data.add(target.id)
                changed = True
        return changed

    def _fixpoint(self, func: ast.AST) -> None:
        for _ in range(8):  # chains are short; 8 rounds is generous
            changed = False
            for sub in ast.walk(func):
                if isinstance(sub, ast.Assign):
                    d, t = self.dev_expr(sub.value), self.data_expr(sub.value)
                    for tgt in sub.targets:
                        changed |= self._assign(tgt, d, t)
                elif isinstance(sub, ast.AugAssign):
                    d, t = self.dev_expr(sub.value), self.data_expr(sub.value)
                    changed |= self._assign(sub.target, d, t)
                elif isinstance(sub, (ast.AnnAssign,)) and sub.value is not None:
                    d, t = self.dev_expr(sub.value), self.data_expr(sub.value)
                    changed |= self._assign(sub.target, d, t)
            if not changed:
                return


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _calls_count_sync(func: ast.AST) -> bool:
    for sub in ast.walk(func):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "count_sync"
        ):
            return True
    return False


def _sync_findings(
    tree: ast.Module, path: str, kernels: Dict
) -> Tuple[List[LintFinding], Set[str]]:
    """SYNC001 over one hot-path module.  Returns the findings plus the
    set of allowlist keys actually matched (for staleness checking)."""
    findings: List[LintFinding] = []
    matched: Set[str] = set()
    for func in _functions(tree):
        taint = _Taint(func, kernels)
        accounted = _calls_count_sync(func)
        own_defs = {
            id(s)
            for s in ast.walk(func)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef))
            and s is not func
        }

        def in_nested(node: ast.AST) -> bool:
            for s in ast.walk(func):
                if id(s) in own_defs:
                    end = getattr(s, "end_lineno", s.lineno)
                    if s.lineno <= node.lineno <= end:
                        return True
            return False

        for sub in ast.walk(func):
            if not isinstance(sub, ast.Call) or in_nested(sub):
                continue
            sink = _sink_kind(sub)
            if sink is None:
                continue
            desc, arg = sink
            if not taint.dev_expr(arg):
                continue
            key = _allow_key(path, func)
            if key in SYNC001_ALLOWED:
                matched.add(key)
                continue
            if accounted:
                continue  # count_sync in the same scope IS the ledger
            findings.append(
                LintFinding(
                    "SYNC001",
                    path,
                    sub.lineno,
                    f"implicit device->host sync: {desc} on a JAX value "
                    f"in `{getattr(func, 'name', '?')}` — account it via "
                    "telemetry.count_sync in the same function, or pin "
                    "it in SYNC001_ALLOWED with its host_sync_elements "
                    "citation",
                )
            )
    return findings, matched


def _retrace_findings(
    tree: ast.Module, path: str, kernels: Dict
) -> List[LintFinding]:
    """RETRACE002 over one module: every static argument at every
    same-module kernel call site must be static-safe."""
    findings: List[LintFinding] = []
    statics_by_kernel = {
        name: (params, statics)
        for name, (params, statics) in kernels.items()
        if statics
    }
    if not statics_by_kernel:
        return findings
    for func in _functions(tree):
        taint = _Taint(func, kernels)
        for sub in ast.walk(func):
            if not (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Name)
                and sub.func.id in statics_by_kernel
            ):
                continue
            params, statics = statics_by_kernel[sub.func.id]
            static_args: List[Tuple[str, ast.expr]] = []
            for i, a in enumerate(sub.args):
                if i < len(params) and params[i] in statics:
                    static_args.append((params[i], a))
            for kw in sub.keywords:
                if kw.arg in statics:
                    static_args.append((kw.arg, kw.value))
            for pname, expr in static_args:
                if not taint.data_expr(expr):
                    continue
                key = _allow_key(path, func)
                if key in RETRACE002_ALLOWED and RETRACE002_ALLOWED[key]:
                    continue
                findings.append(
                    LintFinding(
                        "RETRACE002",
                        path,
                        expr.lineno,
                        f"static argument `{pname}` of kernel "
                        f"`{sub.func.id}` derives from device DATA "
                        f"(`{ast.unparse(expr)}`) — every distinct value "
                        "is a fresh trace+compile (the r06 class); "
                        "launder through the pow2 bucket "
                        "(`1 << max(n - 1, 0).bit_length()`) or a "
                        "shape/dtype derivation",
                    )
                )
    return findings


def _allowlist_findings(path: str) -> List[LintFinding]:
    """Per-file meta-rule: every allowlist entry for THIS file must
    carry a non-empty accounting citation — zero unexplained
    allowances.  Staleness (an entry no live sync site matches) is a
    WHOLE-TREE property and lives in
    :func:`allowlist_global_findings` — a single-file lint cannot tell
    a stale entry from one whose site it simply is not looking at."""
    findings: List[LintFinding] = []
    base = Path(path).name
    for table_name, table in (
        ("SYNC001_ALLOWED", SYNC001_ALLOWED),
        ("RETRACE002_ALLOWED", RETRACE002_ALLOWED),
    ):
        code = table_name.split("_")[0]
        for key, citation in table.items():
            if not key.startswith(base + ":"):
                continue
            if not citation.strip():
                findings.append(
                    LintFinding(
                        code,
                        path,
                        1,
                        f"{table_name} entry `{key}` has no written "
                        "accounting citation — unexplained allowances "
                        "fail lint",
                    )
                )
            elif code == "SYNC001" and not any(
                tok in citation
                for tok in ("host_sync_elements", "count_sync", "no transfer")
            ):
                findings.append(
                    LintFinding(
                        code,
                        path,
                        1,
                        f"{table_name} entry `{key}` must cite its "
                        "host_sync_elements / count_sync accounting "
                        "(or state why no transfer happens)",
                    )
                )
    return findings


def allowlist_global_findings(matched: Set[str]) -> List[LintFinding]:
    """Whole-tree meta-rule (the ``global_checks`` lint pass): every
    allowlist entry must have matched a live sync site somewhere in the
    tree — *matched* is the union of matched keys over every linted
    hot-path file.  A key nothing matched is a stale allowance: the
    sync it blessed was removed or renamed, so the entry must go too
    (it would silently bless a FUTURE sync under the same name)."""
    findings: List[LintFinding] = []
    for table_name, table in (
        ("SYNC001_ALLOWED", SYNC001_ALLOWED),
        ("RETRACE002_ALLOWED", RETRACE002_ALLOWED),
    ):
        code = table_name.split("_")[0]
        for key in table:
            if key not in matched:
                findings.append(
                    LintFinding(
                        code,
                        key.split(":", 1)[0],
                        1,
                        f"stale {table_name} entry `{key}`: no current "
                        "sync site matches it — remove the allowance",
                    )
                )
    return findings


def jitlint_findings(
    tree: ast.Module,
    path: str,
    matched_out: Optional[Set[str]] = None,
) -> List[LintFinding]:
    """All RETRACE002/SYNC001 findings for one parsed module.  When
    *matched_out* is given (the whole-tree lint), the allowlist keys
    this file's sync sites matched are accumulated into it for the
    global staleness check."""
    kernels = _kernel_table(tree)
    findings = _retrace_findings(tree, path, kernels)
    if _is_hot_path(path):
        sync, matched = _sync_findings(tree, path, kernels)
        findings.extend(sync)
        findings.extend(_allowlist_findings(path))
        if matched_out is not None:
            matched_out |= matched
    return findings
