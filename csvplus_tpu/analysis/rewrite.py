"""The verifier-checked plan rewriter (ISSUE 16, ROADMAP item 1).

``optimize_plan`` applies exactly three rewrite rules, each one only
when the provenance domain (:mod:`.provenance`) PROVES it bitwise-safe
against the executor's semantics, and records a typed
:class:`~.provenance.ProvenanceDiagnostic` naming the blocking stage
for every refusal:

* **predicate pushdown** — ``Filter``/``Except`` stages bubble toward
  the leaf across Map/Select/Drop/Join stages
  (:func:`~.provenance.prove_swap_before` per crossing);
* **filter reordering** — inside a run of adjacent narrowing stages,
  most-selective-first by the cost domain's estimates (each adjacent
  swap individually proven);
* **projection pushdown** — leaf columns no stage reads or writes and
  the final schema omits are dropped right after the leaf
  (:func:`~.provenance.live_columns`); a ``DropCols`` there is a pure
  dict filter with no error semantics, and the big win is ``Join``'s
  ``materialize()`` no longer gathering dead columns.

The rewritten plan is re-verified with the existing static verifier and
the EQUIVALENCE VERDICT is asserted: admission verdict (``ok``) and
emptiness prediction must match the original report's, else
:class:`RewriteVerdictMismatch` — a rewrite that changes what the
verifier can prove is a prover bug, never something to execute.

**Replay.**  The serving plan cache stores shapes, not plans: the same
structural key admits later submissions over DIFFERENT tables.  A
rewrite therefore ships as a :class:`PlanRecipe` — a data-only
description (slot permutation + leaf drop list) replayed onto each
submitted root by :func:`apply_recipe`.  The structural key pins op
types, predicate/expr shapes, column names/lanes/placements and the
cardinality class, but NOT cell presence — so every presence fact a
proof consumed is recorded as a leaf-level obligation
(``require_present``) and re-checked against the submitted table by
:func:`leaf_presence_ok` (O(columns), metadata only) before the recipe
replays.  Proofs only ever consume presence facts that are *stable*:
derivable from leaf presence through stages that provably do not touch
the column, so the replay-time check implies the original proof.

``CSVPLUS_OPTIMIZE=0`` disables the rewriter everywhere (the plan
cache then admits and executes the submitted plan byte-identically to
the pre-optimizer behavior).
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from .. import plan as P
from ..errors import CsvPlusError
from . import provenance as PV
from .provenance import ProvenanceDiagnostic, StageFacts
from .schema import Presence

__all__ = [
    "PlanRecipe",
    "RewriteResult",
    "RewriteVerdictMismatch",
    "optimize_enabled",
    "optimize_plan",
    "apply_recipe",
    "leaf_presence_ok",
]


def optimize_enabled() -> bool:
    return os.environ.get("CSVPLUS_OPTIMIZE", "1") != "0"


class RewriteVerdictMismatch(CsvPlusError):
    """Re-verifying the rewritten plan produced a different verdict
    than the original — the rewrite is discarded and this is raised so
    the prover bug is loud (callers on the serving path fall back to
    the unrewritten plan and count it)."""


@dataclass(frozen=True)
class PlanRecipe:
    """A data-only rewrite, replayable onto any root with the same
    structural cache key.  ``steps`` entries are ``("permute", slots)``
    (a reordering of the :func:`~csvplus_tpu.plan.linearize` chain) or
    ``("drop_after_leaf", columns)``.  ``require_present`` are leaf
    columns whose cells must be PRESENT for the proofs to hold on the
    submitted table."""

    steps: Tuple[Tuple, ...]
    require_present: Tuple[str, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.steps)


@dataclass(frozen=True)
class RewriteResult:
    """Outcome of :func:`optimize_plan` over one plan."""

    root: P.PlanNode  # rewritten (or the original when nothing applied)
    report: "object"  # PlanReport of `root`
    original_report: "object"
    recipe: Optional[PlanRecipe]
    applied: Tuple[str, ...] = ()
    blocked: Tuple[ProvenanceDiagnostic, ...] = ()


def apply_recipe(root: P.PlanNode, recipe: PlanRecipe) -> P.PlanNode:
    """Replay *recipe* onto *root* (same structural shape) and rebuild
    the chain — O(nodes), no verification, no table access beyond the
    leaf reference already in hand."""
    chain: List[P.PlanNode] = list(P.linearize(root))
    for step in recipe.steps:
        if step[0] == "permute":
            chain = [chain[i] for i in step[1]]
        elif step[0] == "drop_after_leaf":
            chain.insert(1, P.DropCols(chain[0], tuple(step[1])))
        else:  # unknown step kind: a recipe from a newer writer — refuse
            raise ValueError(f"unknown recipe step {step[0]!r}")
    node = chain[0]
    for stage in chain[1:]:
        node = dataclasses.replace(stage, child=node)
    return node


def leaf_presence_ok(root: P.PlanNode, columns: Sequence[str]) -> bool:
    """Are all *columns* provably PRESENT on *root*'s leaf table?  The
    replay-time check for :attr:`PlanRecipe.require_present` — cached
    metadata only (``col_info_for`` never syncs)."""
    if not columns:
        return True
    from .schema import col_info_for

    table = getattr(P.linearize(root)[0], "table", None)
    cols = getattr(table, "columns", None)
    if not cols:
        return False
    for name in columns:
        col = cols.get(name)
        if col is None or col_info_for(col).presence is not Presence.PRESENT:
            return False
    return True


# ---------------------------------------------------------------------------


def _stable_presence_fn(
    facts: Sequence[StageFacts],
    leaf_present: frozenset,
    upto: int,
    consumed: set,
) -> Callable[[str], bool]:
    """Presence oracle for the input state of ORIGINAL chain slot
    *upto*: True only when the column is PRESENT at the leaf and no
    earlier stage can touch it — the *stable* presence the replay-time
    leaf check can re-establish.  Columns certified True are recorded
    into *consumed* (they become recipe obligations)."""

    def ok(col: str) -> bool:
        if col not in leaf_present:
            return False
        for q in range(1, upto):
            f = facts[q]
            if f.barrier or f.reads is None:
                return False
            if col in f.writes or col in f.removes:
                return False
            if f.keeps_only is not None and col not in f.keeps_only:
                return False
        consumed.add(col)
        return True

    return ok


def _is_mover(f: StageFacts) -> bool:
    return f.op in ("Filter", "Except")


def optimize_plan(root: P.PlanNode, report=None, *,
                  sketches=None) -> RewriteResult:
    """Apply every provenance-proven rewrite to *root*, re-verify, and
    assert the equivalence verdict.  See the module docstring for the
    rule set and the replay contract."""
    from .verify import verify_plan

    if report is None:
        report = verify_plan(root)
    chain = P.linearize(root)
    facts = PV.plan_facts(root)
    n = len(chain)
    applied: List[str] = []
    blocked: List[ProvenanceDiagnostic] = []
    consumed: set = set()
    leaf_present = frozenset(
        name for name, info in report.states[0].schema.items()
        if info.presence is Presence.PRESENT
    )

    def try_swap(rule: str, order: List[int], j: int) -> bool:
        """Prove + perform the swap of order[j] before order[j-1]."""
        p, q = order[j], order[j - 1]
        oracle = _stable_presence_fn(facts, leaf_present, q, consumed)
        diag = PV.prove_swap_before(rule, facts[p], facts[q], oracle)
        if diag is not None:
            blocked.append(diag)
            return False
        order[j - 1], order[j] = order[j], order[j - 1]
        return True

    # 1. Predicate pushdown: bubble each narrowing stage toward the
    # leaf across non-narrowing stages (narrow-vs-narrow order is the
    # reordering rule's job, with a cost argument).
    order = list(range(n))
    pushed: set = set()
    changed = True
    while changed:
        changed = False
        for j in range(2, n):
            p, q = order[j], order[j - 1]
            if not _is_mover(facts[p]) or q == 0 or _is_mover(facts[q]):
                continue
            if try_swap("predicate-pushdown", order, j):
                pushed.add(p)
                changed = True
    for p in sorted(pushed):
        applied.append(
            f"predicate-pushdown: {facts[p].label} moved to slot "
            f"{order.index(p)}")

    # 2. Filter reordering: most-selective-first inside each run of
    # adjacent narrowing stages (plain bubble sort; every adjacent swap
    # is individually proven, so a blocked pair simply stays put).
    from .cost import estimate_plan

    ests = estimate_plan(root, sketches=sketches)
    sel = {p: (ests[p].selectivity if ests[p].selectivity is not None
               else 1.0) for p in range(n)}
    reordered: set = set()
    changed = True
    while changed:
        changed = False
        for j in range(2, n):
            p, q = order[j], order[j - 1]
            if not _is_mover(facts[p]) or not _is_mover(facts[q]):
                continue
            if sel[p] < sel[q] and try_swap("filter-reorder", order, j):
                reordered.add(p)
                changed = True
    for p in sorted(reordered):
        applied.append(
            f"filter-reorder: {facts[p].label} hoisted "
            f"(selectivity {sel[p]:.4f})")

    # 3. Projection pushdown: drop dead leaf columns right after the
    # leaf.  Liveness is order-independent (a union over stage
    # footprints), so the permutation above does not change it.
    steps: List[Tuple] = []
    if order != list(range(n)):
        steps.append(("permute", tuple(order)))
    final_schema = tuple(report.states[-1].schema.keys())
    live = PV.live_columns(facts[1:], final_schema)
    if live is None:
        bad = next((f for f in facts[1:]
                    if f.barrier or f.reads is None
                    or (f.op == "Join" and f.fallback_writes is None)),
                   None)
        if bad is not None:
            blocked.append(ProvenanceDiagnostic(
                "projection-pushdown", bad.label,
                f"{bad.op} has an unknown column footprint — no liveness "
                f"claim is sound"))
    else:
        leaf_cols = list(report.states[0].schema.keys())
        dead = tuple(c for c in leaf_cols if c not in live)
        if dead and len(dead) < len(leaf_cols):
            steps.append(("drop_after_leaf", dead))
            applied.append(
                f"projection-pushdown: drop {list(dead)} after "
                f"{facts[0].label}")

    # The bubble passes re-attempt stuck pairs once per sweep; keep the
    # first refusal only.
    seen: set = set()
    unique_blocked = tuple(
        d for d in blocked
        if (d.rule, d.stage, d.message) not in seen
        and not seen.add((d.rule, d.stage, d.message)))

    if not steps:
        return RewriteResult(root, report, report, None, tuple(applied),
                             unique_blocked)

    recipe = PlanRecipe(tuple(steps), tuple(sorted(consumed)))
    new_root = apply_recipe(root, recipe)
    opt_report = verify_plan(new_root)
    if (opt_report.ok != report.ok
            or opt_report.predicts_empty != report.predicts_empty):
        raise RewriteVerdictMismatch(
            f"rewritten plan verdict (ok={opt_report.ok}, "
            f"predicts_empty={opt_report.predicts_empty}) diverged from "
            f"original (ok={report.ok}, "
            f"predicts_empty={report.predicts_empty}); rewrite discarded")
    return RewriteResult(new_root, opt_report, report, recipe,
                         tuple(applied), unique_blocked)
