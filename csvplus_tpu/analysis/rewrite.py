"""The verifier-checked plan rewriter (ISSUE 16 + 17 + 19, ROADMAP item 1).

``optimize_plan`` applies exactly six rewrite rules, each one only
when the provenance domain (:mod:`.provenance`) PROVES it bitwise-safe
against the executor's semantics, and records a typed
:class:`~.provenance.ProvenanceDiagnostic` naming the blocking stage
for every refusal:

* **predicate pushdown** — ``Filter``/``Except`` stages bubble toward
  the leaf across Map/Select/Drop/Join stages
  (:func:`~.provenance.prove_swap_before` per crossing);
* **filter reordering** — inside a run of adjacent narrowing stages,
  most-selective-first by the cost domain's estimates (each adjacent
  swap individually proven);
* **join ordering** (ISSUE 17) — the cost domain's best *provable*
  ranked ordering of the longest Join/Except run
  (:func:`~.cost.rank_join_orders`) is realized by re-proving each
  hoist with the live presence oracle; the chosen permutation is
  recorded on the recipe (``join_order``) so the serving cache can
  attribute replays to it;
* **multiway fuse** (ISSUE 17) — a run of two or more consecutive
  ``Join`` stages (post-permutation) collapses into one fused
  :class:`~csvplus_tpu.plan.MultiwayJoin` physical operator when the
  cost model prices the single-pass form cheaper
  (:func:`~.cost.choose_join_operator`) AND every later dimension's
  key columns are provably PRESENT on the stream entering the run —
  the exact condition under which the cascade could neither fill a
  later key from an earlier build side (stream-wins merge) nor raise
  a key error at an intermediate row number the fused pass would
  report differently.  ``CSVPLUS_MULTIWAY=0`` disables just this rule;
* **probe-pass fusion** (ISSUE 19) — the licensed Filter/Map/projection
  run immediately before the chain's first probe collapses into one
  fused :class:`~csvplus_tpu.plan.FusedProbe` physical operator when
  the per-placement pricing rule (:func:`~.cost.choose_fusion`)
  approves: the absorbed ops evaluate against the executor's lazy
  selection view and the probe consumes the selection directly, so the
  staged pre-join ``materialize()`` (a full-width gather of every live
  column) never happens and the emit gather composes through the
  selection instead — bitwise-identical by gather associativity.  The
  license is structural (every absorbed op row-linear with a known
  footprint — the ops execute through the SAME executor code paths,
  only the node boundary moves, so no new presence obligations arise);
  the pricing is per placement lane, the r06 RSS lesson.
  ``CSVPLUS_FUSE=0`` disables just this rule;
* **projection pushdown** — leaf columns no stage reads or writes and
  the final schema omits are dropped right after the leaf
  (:func:`~.provenance.live_columns`); a ``DropCols`` there is a pure
  dict filter with no error semantics, and the big win is ``Join``'s
  ``materialize()`` — or the fused pass's key/emit gathers — no longer
  touching dead columns.

The rewritten plan is re-verified with the existing static verifier and
the EQUIVALENCE VERDICT is asserted: admission verdict (``ok``) and
emptiness prediction must match the original report's, else
:class:`RewriteVerdictMismatch` — a rewrite that changes what the
verifier can prove is a prover bug, never something to execute.

**Replay.**  The serving plan cache stores shapes, not plans: the same
structural key admits later submissions over DIFFERENT tables.  A
rewrite therefore ships as a :class:`PlanRecipe` — a data-only
description (slot permutation + leaf drop list) replayed onto each
submitted root by :func:`apply_recipe`.  The structural key pins op
types, predicate/expr shapes, column names/lanes/placements and the
cardinality class, but NOT cell presence — so every presence fact a
proof consumed is recorded as a leaf-level obligation
(``require_present``) and re-checked against the submitted table by
:func:`leaf_presence_ok` (O(columns), metadata only) before the recipe
replays.  Proofs only ever consume presence facts that are *stable*:
derivable from leaf presence through stages that provably do not touch
the column, so the replay-time check implies the original proof.

``CSVPLUS_OPTIMIZE=0`` disables the rewriter everywhere (the plan
cache then admits and executes the submitted plan byte-identically to
the pre-optimizer behavior).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Callable, List, Optional, Sequence, Tuple

from .. import plan as P
from ..errors import CsvPlusError
from ..utils.env import env_str
from . import provenance as PV
from .provenance import ProvenanceDiagnostic, StageFacts
from .schema import Presence

__all__ = [
    "PlanRecipe",
    "RewriteResult",
    "RewriteVerdictMismatch",
    "fuse_enabled",
    "multiway_enabled",
    "optimize_enabled",
    "optimize_plan",
    "apply_recipe",
    "leaf_presence_ok",
]


def optimize_enabled() -> bool:
    return env_str("CSVPLUS_OPTIMIZE", "1") != "0"


def multiway_enabled() -> bool:
    """The multiway-fuse rule's own hatch (``CSVPLUS_MULTIWAY=0``),
    nested under the global ``CSVPLUS_OPTIMIZE`` switch — the bench's
    cascaded leg runs with the optimizer ON but the fuse OFF so both
    legs share every other rewrite."""
    return optimize_enabled() and env_str("CSVPLUS_MULTIWAY", "1") != "0"


def fuse_enabled() -> bool:
    """The probe-pass fusion rule's own hatch (``CSVPLUS_FUSE=0``),
    nested under the global ``CSVPLUS_OPTIMIZE`` switch — the
    macro-bench's staged leg runs with the optimizer ON but fusion OFF
    so both legs share every other rewrite."""
    return optimize_enabled() and env_str("CSVPLUS_FUSE", "1") != "0"


class RewriteVerdictMismatch(CsvPlusError):
    """Re-verifying the rewritten plan produced a different verdict
    than the original — the rewrite is discarded and this is raised so
    the prover bug is loud (callers on the serving path fall back to
    the unrewritten plan and count it)."""


@dataclass(frozen=True)
class PlanRecipe:
    """A data-only rewrite, replayable onto any root with the same
    structural cache key.  ``steps`` entries are ``("permute", slots)``
    (a reordering of the :func:`~csvplus_tpu.plan.linearize` chain),
    ``("fuse_joins", lo, k)`` (collapse the ``k`` consecutive ``Join``
    stages starting at post-permute slot ``lo`` into one
    :class:`~csvplus_tpu.plan.MultiwayJoin`),
    ``("fuse_chain", s, m)`` (collapse the ``m`` stages starting at
    slot ``s`` — a Filter/Map/projection run ending in a probe — into
    one :class:`~csvplus_tpu.plan.FusedProbe`), or
    ``("drop_after_leaf", columns)``.  ``require_present`` are leaf
    columns whose cells must be PRESENT for the proofs to hold on the
    submitted table.  ``join_order`` is the cost-chosen execution order
    of the plan's probe run (original chain slots) when the join-order
    rule picked one — advisory metadata for the serving cache's
    attribution counters and ``explain``; the executable form already
    rides the permute step."""

    steps: Tuple[Tuple, ...]
    require_present: Tuple[str, ...] = ()
    join_order: Tuple[int, ...] = ()

    def __bool__(self) -> bool:
        return bool(self.steps)


@dataclass(frozen=True)
class RewriteResult:
    """Outcome of :func:`optimize_plan` over one plan."""

    root: P.PlanNode  # rewritten (or the original when nothing applied)
    report: "object"  # PlanReport of `root`
    original_report: "object"
    recipe: Optional[PlanRecipe]
    applied: Tuple[str, ...] = ()
    blocked: Tuple[ProvenanceDiagnostic, ...] = ()


def apply_recipe(root: P.PlanNode, recipe: PlanRecipe) -> P.PlanNode:
    """Replay *recipe* onto *root* (same structural shape) and rebuild
    the chain — O(nodes), no verification, no table access beyond the
    leaf reference already in hand."""
    chain: List[P.PlanNode] = list(P.linearize(root))
    for step in recipe.steps:
        if step[0] == "permute":
            chain = [chain[i] for i in step[1]]
        elif step[0] == "fuse_joins":
            lo, k = int(step[1]), int(step[2])
            run = chain[lo:lo + k]
            if len(run) != k or not all(isinstance(s, P.Join) for s in run):
                # the structural key pins op types, so this only fires on
                # a recipe replayed against the wrong shape — refuse loud
                raise ValueError("fuse_joins step does not address a Join run")
            joins = tuple((s.index, tuple(s.columns)) for s in run)
            chain[lo:lo + k] = [P.MultiwayJoin(run[0].child, joins)]
        elif step[0] == "fuse_chain":
            s, m = int(step[1]), int(step[2])
            run = chain[s:s + m]
            kinds = {P.Filter: "filter", P.MapExpr: "map",
                     P.SelectCols: "select", P.DropCols: "drop"}
            last = run[-1] if run else None
            if (len(run) != m or m < 2
                    or not isinstance(last, (P.Join, P.MultiwayJoin))
                    or not all(type(nd) in kinds for nd in run[:-1])):
                raise ValueError(
                    "fuse_chain step does not address an op run ending "
                    "in a probe")
            ops = []
            for nd in run[:-1]:
                kind = kinds[type(nd)]
                if kind == "filter":
                    payload = nd.pred
                elif kind == "map":
                    payload = nd.expr
                else:
                    payload = tuple(nd.columns)
                ops.append((kind, payload))
            joins = (
                last.joins if isinstance(last, P.MultiwayJoin)
                else ((last.index, tuple(last.columns)),)
            )
            chain[s:s + m] = [
                P.FusedProbe(run[0].child, tuple(ops), tuple(joins))
            ]
        elif step[0] == "drop_after_leaf":
            chain.insert(1, P.DropCols(chain[0], tuple(step[1])))
        else:  # unknown step kind: a recipe from a newer writer — refuse
            raise ValueError(f"unknown recipe step {step[0]!r}")
    node = chain[0]
    for stage in chain[1:]:
        node = dataclasses.replace(stage, child=node)
    return node


def leaf_presence_ok(root: P.PlanNode, columns: Sequence[str]) -> bool:
    """Are all *columns* provably PRESENT on *root*'s leaf table?  The
    replay-time check for :attr:`PlanRecipe.require_present` — cached
    metadata only (``col_info_for`` never syncs)."""
    if not columns:
        return True
    from .schema import col_info_for

    table = getattr(P.linearize(root)[0], "table", None)
    cols = getattr(table, "columns", None)
    if not cols:
        return False
    for name in columns:
        col = cols.get(name)
        if col is None or col_info_for(col).presence is not Presence.PRESENT:
            return False
    return True


# ---------------------------------------------------------------------------


def _stable_presence_fn(
    facts: Sequence[StageFacts],
    leaf_present: frozenset,
    upto: int,
    consumed: set,
) -> Callable[[str], bool]:
    """Presence oracle for the input state of ORIGINAL chain slot
    *upto*: True only when the column is PRESENT at the leaf and no
    earlier stage can touch it — the *stable* presence the replay-time
    leaf check can re-establish.  Columns certified True are recorded
    into *consumed* (they become recipe obligations)."""

    def ok(col: str) -> bool:
        if col not in leaf_present:
            return False
        for q in range(1, upto):
            f = facts[q]
            if f.barrier or f.reads is None:
                return False
            if col in f.writes or col in f.removes:
                return False
            if f.keeps_only is not None and col not in f.keeps_only:
                return False
        consumed.add(col)
        return True

    return ok


def _is_mover(f: StageFacts) -> bool:
    return f.op in ("Filter", "Except")


def optimize_plan(root: P.PlanNode, report=None, *,
                  sketches=None) -> RewriteResult:
    """Apply every provenance-proven rewrite to *root*, re-verify, and
    assert the equivalence verdict.  See the module docstring for the
    rule set and the replay contract."""
    from .verify import verify_plan

    if report is None:
        report = verify_plan(root)
    chain = P.linearize(root)
    facts = PV.plan_facts(root)
    n = len(chain)
    applied: List[str] = []
    blocked: List[ProvenanceDiagnostic] = []
    consumed: set = set()
    leaf_present = frozenset(
        name for name, info in report.states[0].schema.items()
        if info.presence is Presence.PRESENT
    )

    def try_swap(rule: str, order: List[int], j: int) -> bool:
        """Prove + perform the swap of order[j] before order[j-1]."""
        p, q = order[j], order[j - 1]
        oracle = _stable_presence_fn(facts, leaf_present, q, consumed)
        diag = PV.prove_swap_before(rule, facts[p], facts[q], oracle)
        if diag is not None:
            blocked.append(diag)
            return False
        order[j - 1], order[j] = order[j], order[j - 1]
        return True

    # 1. Predicate pushdown: bubble each narrowing stage toward the
    # leaf across non-narrowing stages (narrow-vs-narrow order is the
    # reordering rule's job, with a cost argument).
    order = list(range(n))
    pushed: set = set()
    changed = True
    while changed:
        changed = False
        for j in range(2, n):
            p, q = order[j], order[j - 1]
            if not _is_mover(facts[p]) or q == 0 or _is_mover(facts[q]):
                continue
            if try_swap("predicate-pushdown", order, j):
                pushed.add(p)
                changed = True
    for p in sorted(pushed):
        applied.append(
            f"predicate-pushdown: {facts[p].label} moved to slot "
            f"{order.index(p)}")

    # 2. Filter reordering: most-selective-first inside each run of
    # adjacent narrowing stages (plain bubble sort; every adjacent swap
    # is individually proven, so a blocked pair simply stays put).
    from .cost import choose_join_operator, estimate_plan, rank_join_orders

    ests = estimate_plan(root, sketches=sketches)
    sel = {p: (ests[p].selectivity if ests[p].selectivity is not None
               else 1.0) for p in range(n)}
    reordered: set = set()
    changed = True
    while changed:
        changed = False
        for j in range(2, n):
            p, q = order[j], order[j - 1]
            if not _is_mover(facts[p]) or not _is_mover(facts[q]):
                continue
            if sel[p] < sel[q] and try_swap("filter-reorder", order, j):
                reordered.add(p)
                changed = True
    for p in sorted(reordered):
        applied.append(
            f"filter-reorder: {facts[p].label} hoisted "
            f"(selectivity {sel[p]:.4f})")

    # 3. Join ordering: realize the cost domain's best PROVABLE ranked
    # ordering of the longest probe run (``rank_join_orders`` has marked
    # them since r16; nothing executed them until ISSUE 17).  Provable
    # orderings preserve expander order, so only NARROW stages ever
    # move — in most plans passes 1-2 already landed the target and this
    # pass just records the chosen order; stragglers are bubbled with
    # every hoist re-proven against the live oracle.
    join_order: Tuple[int, ...] = ()
    ranked = rank_join_orders(root, report, sketches=sketches)
    best = next((r for r in ranked if r["provable"]), None)
    if best is not None and not best["submitted"]:
        run_set = set(best["run"])
        target = list(best["slots"])
        rank_of = {p: i for i, p in enumerate(target)}
        changed = True
        while changed:
            changed = False
            for j in range(2, n):
                p, q = order[j], order[j - 1]
                if p not in run_set or q not in run_set:
                    continue
                if rank_of[p] < rank_of[q] and try_swap(
                        "join-order", order, j):
                    changed = True
        if [p for p in order if p in run_set] == target:
            join_order = tuple(target)
            applied.append(
                f"join-order: probe run executes as {best['order']} "
                f"(est {best['est_intermediate_rows']:.0f} intermediate "
                f"rows)")

    steps: List[Tuple] = []
    if order != list(range(n)):
        steps.append(("permute", tuple(order)))

    # 4. Multiway fuse (ISSUE 17): collapse a post-permutation run of
    # >= 2 consecutive Joins into one single-pass MultiwayJoin when the
    # cost model prices the fused operator cheaper AND every later
    # dimension's key columns are provably PRESENT entering the run.
    # The license is exactly the bitwise-parity condition: with later
    # keys PRESENT, no earlier build side can fill them (stream-wins
    # merge keeps present cells), and no per-level key check can raise
    # at an intermediate row number the fused pass would report
    # differently — so probing the original stream IS probing the
    # cascade's intermediate.
    if multiway_enabled():
        permuted = apply_recipe(root, PlanRecipe(tuple(steps))) if steps else root
        choice = choose_join_operator(permuted, sketches=sketches)
        if choice is not None and choice["chosen"] == "multiway":
            lo, k = int(choice["slots"][0]), int(choice["dims"])
            pchain = P.linearize(permuted)
            later = sorted(
                {c for nd in pchain[lo + 1:lo + k] for c in nd.columns})
            pre = [order[j] for j in range(1, lo)]

            def fuse_ok(col: str) -> bool:
                if col not in leaf_present:
                    return False
                for q in pre:
                    f = facts[q]
                    if f.barrier or f.reads is None:
                        return False
                    if col in f.writes or col in f.removes:
                        return False
                    if f.keeps_only is not None and col not in f.keeps_only:
                        return False
                return True

            bad = [c for c in later if not fuse_ok(c)]
            if bad:
                blocked.append(ProvenanceDiagnostic(
                    "multiway-fuse", facts[order[lo]].label,
                    f"later-dimension key(s) {bad} not provably PRESENT "
                    f"entering the run — the cascade could fill them from "
                    f"an earlier build side or error at an intermediate "
                    f"row"))
            else:
                consumed.update(later)
                steps.append(("fuse_joins", lo, k))
                applied.append(
                    f"multiway-fuse: {k}-way run at slot {lo} (est "
                    f"cascade {choice['cascade_intermediate_bytes']:.0f}B "
                    f"intermediate vs multiway "
                    f"{choice['multiway_bytes']:.0f}B)")

    # 5. Probe-pass fusion (ISSUE 19): absorb the licensed Filter/Map/
    # projection run immediately before the chain's first probe into
    # one FusedProbe when the per-placement pricing approves.  The
    # license is structural — choose_fusion only extends the run across
    # ops whose provenance facts are row-linear with a known footprint,
    # and the absorbed ops execute through the SAME executor code paths
    # (masks, metadata updates, error sites), only the node boundary
    # moves — so fusion adds NO presence obligations; parity is by
    # construction (gather associativity), re-checked by the verdict
    # equivalence below like every other rule.
    if fuse_enabled():
        from .cost import choose_fusion

        cur = apply_recipe(root, PlanRecipe(tuple(steps))) if steps else root
        fchoice = choose_fusion(cur, sketches=sketches)
        if fchoice is not None:
            if fchoice.get("blocked_by"):
                blocked.append(ProvenanceDiagnostic(
                    "probe-fuse", fchoice["blocked_by"],
                    "opaque predicate/expr bounds the absorbable run — "
                    "its column footprint is unknown"))
            if fchoice["chosen"] == "fuse" and fchoice["ops"]:
                s = int(fchoice["slots"][0])
                m = len(fchoice["slots"])
                steps.append(("fuse_chain", s, m))
                staged_b = (fchoice["staged_bytes_host"]
                            + fchoice["staged_bytes_device"])
                fused_b = (fchoice["fused_bytes_host"]
                           + fchoice["fused_bytes_device"])
                applied.append(
                    f"probe-fuse: {len(fchoice['ops'])} op(s) fused into "
                    f"the probe at slot {s} (est staged materialize "
                    f"{staged_b:.0f}B vs fused key gathers {fused_b:.0f}B)")
            elif fchoice["ops"]:
                blocked.append(ProvenanceDiagnostic(
                    "probe-fuse", fchoice["run"][-1],
                    f"cost model prices staged cheaper "
                    f"({fchoice['note']})"))

    # 6. Projection pushdown: drop dead leaf columns right after the
    # leaf.  Liveness is order-independent (a union over stage
    # footprints, identical for the fused operators by construction), so
    # neither the permutation nor the fuses above change it.
    final_schema = tuple(report.states[-1].schema.keys())
    live = PV.live_columns(facts[1:], final_schema)
    if live is None:
        bad = next((f for f in facts[1:]
                    if f.barrier or f.reads is None
                    or (f.op == "Join" and f.fallback_writes is None)),
                   None)
        if bad is not None:
            blocked.append(ProvenanceDiagnostic(
                "projection-pushdown", bad.label,
                f"{bad.op} has an unknown column footprint — no liveness "
                f"claim is sound"))
    else:
        leaf_cols = list(report.states[0].schema.keys())
        dead = tuple(c for c in leaf_cols if c not in live)
        if dead and len(dead) < len(leaf_cols):
            steps.append(("drop_after_leaf", dead))
            applied.append(
                f"projection-pushdown: drop {list(dead)} after "
                f"{facts[0].label}")

    # The bubble passes re-attempt stuck pairs once per sweep; keep the
    # first refusal only.
    seen: set = set()
    unique_blocked = tuple(
        d for d in blocked
        if (d.rule, d.stage, d.message) not in seen
        and not seen.add((d.rule, d.stage, d.message)))

    if not steps:
        return RewriteResult(root, report, report, None, tuple(applied),
                             unique_blocked)

    recipe = PlanRecipe(tuple(steps), tuple(sorted(consumed)), join_order)
    new_root = apply_recipe(root, recipe)
    opt_report = verify_plan(new_root)
    if (opt_report.ok != report.ok
            or opt_report.predicts_empty != report.predicts_empty):
        raise RewriteVerdictMismatch(
            f"rewritten plan verdict (ok={opt_report.ok}, "
            f"predicts_empty={opt_report.predicts_empty}) diverged from "
            f"original (ok={report.ok}, "
            f"predicts_empty={report.predicts_empty}); rewrite discarded")
    return RewriteResult(new_root, opt_report, report, recipe,
                         tuple(applied), unique_blocked)
