"""Repo-specific AST lint: rules generic linters cannot know.

Two boundary classes have bitten this codebase and are mechanically
checkable from the AST:

* **CTYPES001** — the native scanner boundary.  The C ABI's ``c_char``
  takes EXACTLY one byte; ctypes raises a cryptic ``TypeError`` (or
  silently truncates, for sliced bytes) when a multi-byte encoding of a
  user-supplied delimiter/comment reaches it.  Every ``.encode(...)``
  expression flowing into a ``c_char`` parameter position (positions are
  discovered from the module's own ``lib.X.argtypes = [...]``
  assignments) must be gated in the same function by a
  ``len(<that expression>) == 1`` / ``!= 1`` test or an explicit
  single-byte slice ``[0:1]``.  The round-5 fused-path bug — a
  multi-byte delimiter reaching ``csv_scan_parse_i32`` ungated — is
  exactly this rule.
* **JIT001** — the retrace boundary.  A ``jax.jit``-ed function whose
  body iterates one of its PARAMETERS in a comprehension has a
  tuple-of-arrays signature: every distinct tuple LENGTH is a fresh
  trace + compile (one per chunk-count in the ingest profile).  Such
  kernels should be eager, take a fixed arity, or carry an explicit
  suppression acknowledging the retrace cost.

Suppression: a ``# analysis: allow[CODE]`` comment on the flagged line
or on the enclosing ``def`` line.

Run over the tree with ``python -m csvplus_tpu.analysis <paths...>``
(wired into ``make lint``).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = ["LintFinding", "lint_source", "lint_file", "lint_paths"]


@dataclass(frozen=True)
class LintFinding:
    code: str  # "CTYPES001" | "JIT001"
    path: str
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: {self.code} {self.message}"


def _is_c_char(node: ast.expr) -> bool:
    return (isinstance(node, ast.Attribute) and node.attr == "c_char") or (
        isinstance(node, ast.Name) and node.id == "c_char"
    )


def _c_char_positions(tree: ast.Module) -> Dict[str, Tuple[int, ...]]:
    """``{function_name: c_char argument positions}`` from every
    ``<lib>.NAME.argtypes = [...]`` assignment in the module."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        tgt = node.targets[0]
        if not (
            isinstance(tgt, ast.Attribute)
            and tgt.attr == "argtypes"
            and isinstance(tgt.value, ast.Attribute)
        ):
            continue
        if not isinstance(node.value, (ast.List, ast.Tuple)):
            continue
        pos = tuple(
            i for i, el in enumerate(node.value.elts) if _is_c_char(el)
        )
        if pos:
            out[tgt.value.attr] = pos
    return out


def _find_encode(node: ast.expr) -> Optional[ast.Call]:
    """The ``<something>.encode(...)`` call inside *node*, if any."""
    for sub in ast.walk(node):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "encode"
        ):
            return sub
    return None


def _is_single_byte_slice(node: ast.expr) -> bool:
    """``X[0:1]`` — an explicit truncation to at most one byte."""
    if not (isinstance(node, ast.Subscript) and isinstance(node.slice, ast.Slice)):
        return False
    s = node.slice
    return (
        isinstance(s.lower, ast.Constant)
        and s.lower.value == 0
        and isinstance(s.upper, ast.Constant)
        and s.upper.value == 1
        and s.step is None
    )


def _len_one_guards(func: ast.AST) -> Set[str]:
    """Unparsed sources ``X`` for every ``len(X) == 1`` / ``len(X) != 1``
    comparison anywhere in *func* (either operand order)."""
    out: Set[str] = set()

    def record(len_side: ast.expr, const_side: ast.expr) -> None:
        if (
            isinstance(len_side, ast.Call)
            and isinstance(len_side.func, ast.Name)
            and len_side.func.id == "len"
            and len(len_side.args) == 1
            and isinstance(const_side, ast.Constant)
            and const_side.value == 1
        ):
            out.add(ast.unparse(len_side.args[0]))

    for node in ast.walk(func):
        if not isinstance(node, ast.Compare) or len(node.ops) != 1:
            continue
        if not isinstance(node.ops[0], (ast.Eq, ast.NotEq)):
            continue
        record(node.left, node.comparators[0])
        record(node.comparators[0], node.left)
    return out


def _local_assignments(func: ast.AST) -> Dict[str, ast.expr]:
    """Simple single-target ``name = expr`` bindings in *func* (last one
    wins — good enough for the guard-resolution heuristic)."""
    out: Dict[str, ast.expr] = {}
    for node in ast.walk(func):
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
        ):
            out[node.targets[0].id] = node.value
    return out


class _FunctionStack(ast.NodeVisitor):
    """Visitor that tracks the enclosing function for every node."""

    def __init__(self) -> None:
        self.stack: List[ast.AST] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.stack.append(node)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef  # type: ignore[assignment]

    @property
    def current(self) -> Optional[ast.AST]:
        return self.stack[-1] if self.stack else None


class _CtypesVisitor(_FunctionStack):
    def __init__(self, positions: Dict[str, Tuple[int, ...]], path: str):
        super().__init__()
        self.positions = positions
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        fn = node.func
        if not (isinstance(fn, ast.Attribute) and fn.attr in self.positions):
            return
        func = self.current
        guards = _len_one_guards(func) if func is not None else set()
        local = _local_assignments(func) if func is not None else {}
        for pos in self.positions[fn.attr]:
            if pos >= len(node.args):
                continue
            arg = node.args[pos]
            name = None
            if isinstance(arg, ast.Name):
                name = arg.id
                arg = local.get(arg.id, arg)
            enc = _find_encode(arg)
            if enc is None:
                continue
            if _is_single_byte_slice(arg):
                continue
            gate_keys = {ast.unparse(arg), ast.unparse(enc)}
            if name is not None:
                gate_keys.add(name)
            if gate_keys & guards:
                continue
            self.findings.append(
                LintFinding(
                    "CTYPES001",
                    self.path,
                    node.args[pos].lineno,
                    f"{ast.unparse(enc)} flows into c_char parameter "
                    f"{pos} of {fn.attr} without a len(...) == 1 gate "
                    "in the enclosing function",
                )
            )


def _is_jit_decorator(dec: ast.expr) -> bool:
    """``@jax.jit``, ``@jit``, or any decorator CALL mentioning ``jit``
    (``functools.partial(jax.jit, ...)``)."""
    for node in ast.walk(dec):
        if isinstance(node, ast.Attribute) and node.attr == "jit":
            return True
        if isinstance(node, ast.Name) and node.id == "jit":
            return True
    return False


class _JitVisitor(ast.NodeVisitor):
    def __init__(self, path: str):
        self.path = path
        self.findings: List[LintFinding] = []

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self.generic_visit(node)
        if not any(_is_jit_decorator(d) for d in node.decorator_list):
            return
        params = {
            a.arg
            for a in (
                node.args.posonlyargs + node.args.args + node.args.kwonlyargs
            )
        }

        def iterates_param(it: ast.expr) -> Optional[str]:
            if isinstance(it, ast.Name) and it.id in params:
                return it.id
            # zip(maps, cks) / enumerate(cks) over parameters
            if isinstance(it, ast.Call):
                for a in it.args:
                    if isinstance(a, ast.Name) and a.id in params:
                        return a.id
            return None

        # one finding per function: the signature is the problem, not
        # each comprehension that exhibits it
        for sub in ast.walk(node):
            if isinstance(
                sub, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.For)
            ):
                its = (
                    [g.iter for g in sub.generators]
                    if not isinstance(sub, ast.For)
                    else [sub.iter]
                )
                for it in its:
                    hit = iterates_param(it)
                    if hit is not None:
                        self.findings.append(
                            LintFinding(
                                "JIT001",
                                self.path,
                                sub.lineno,
                                f"jit-compiled `{node.name}` iterates "
                                f"parameter `{hit}`: a tuple-of-arrays "
                                "signature retraces per distinct length",
                            )
                        )
                        return


def _suppressed(finding: LintFinding, lines: List[str], tree: ast.Module) -> bool:
    marker = f"analysis: allow[{finding.code}]"

    def line_has(idx: int) -> bool:
        return 0 < idx <= len(lines) and marker in lines[idx - 1]

    if line_has(finding.line):
        return True
    # any enclosing def line (a flagged closure inherits its outer
    # function's acknowledgment)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= finding.line <= end and line_has(node.lineno):
                return True
    return False


def lint_source(source: str, path: str = "<string>") -> List[LintFinding]:
    """All unsuppressed findings for one module's source text."""
    tree = ast.parse(source, filename=path)
    findings: List[LintFinding] = []
    positions = _c_char_positions(tree)
    if positions:
        v = _CtypesVisitor(positions, path)
        v.visit(tree)
        findings.extend(v.findings)
    j = _JitVisitor(path)
    j.visit(tree)
    findings.extend(j.findings)
    lines = source.splitlines()
    findings = [f for f in findings if not _suppressed(f, lines, tree)]
    findings.sort(key=lambda f: (f.path, f.line, f.code))
    return findings


def lint_file(path) -> List[LintFinding]:
    p = Path(path)
    return lint_source(p.read_text(encoding="utf-8"), str(p))


def lint_paths(paths: Iterable) -> List[LintFinding]:
    """Lint every ``.py`` file under each path (file or directory)."""
    findings: List[LintFinding] = []
    for path in paths:
        p = Path(path)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(lint_file(f))
    return findings
